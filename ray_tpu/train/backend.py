"""Framework backends: per-worker distributed setup.

Role-equivalent of the reference's backend configs
(train/v2/jax/config.py:21,73 — JaxConfig/_JaxBackend setting
JAX_PLATFORMS=tpu and running jax.distributed.initialize(master, n, rank) on
every ranked worker; train/torch/config.py — process-group bootstrap).

TPU-first: the JAX backend is the primary one. Rank 0 advertises a
coordinator address; every worker initializes the JAX distributed runtime so
the whole slice forms one multi-controller SPMD program and in-jit
collectives ride ICI.
"""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger(__name__)


class BackendConfig:
    """Base: no distributed setup."""

    def backend(self) -> "Backend":
        return Backend()


class Backend:
    def on_start(self, worker_group) -> None:
        pass

    def on_shutdown(self, worker_group) -> None:
        pass


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _host_ip() -> str:
    import socket

    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


# -- JAX ---------------------------------------------------------------------


class JaxConfig(BackendConfig):
    """JAX distributed runtime bootstrap.

    ``distributed=None`` (default) auto-enables jax.distributed for
    multi-worker TPU groups and disables it for single-worker or CPU test
    groups (where each worker process is an independent single-device JAX;
    cross-worker sync then goes through the framework's GCS collective
    group).
    """

    def __init__(self, use_tpu: bool = False, distributed: Optional[bool] = None):
        self.use_tpu = use_tpu
        self.distributed = distributed

    def backend(self) -> "Backend":
        return _JaxBackend(self)


def _jax_worker_setup(
    coordinator: Optional[str],
    num_processes: int,
    process_id: int,
    use_tpu: bool,
):
    import os

    if use_tpu:
        os.environ.setdefault("JAX_PLATFORMS", "tpu")
    if coordinator is not None:
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        logger.info(
            "jax.distributed up: rank %d/%d coordinator %s devices=%d",
            process_id,
            num_processes,
            coordinator,
            jax.device_count(),
        )
    return True


def _jax_shutdown():
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    return True


class _JaxBackend(Backend):
    def __init__(self, config: JaxConfig):
        self._config = config
        self._initialized_distributed = False

    def on_start(self, worker_group):
        n = len(worker_group.workers)
        use_dist = self._config.distributed
        if use_dist is None:
            use_dist = self._config.use_tpu and n > 1
        coordinator = None
        if use_dist:
            # rank 0 advertises host:free-port (reference: config.py:41-68
            # master-address broadcast via worker 0)
            coordinator = worker_group.execute_single(
                0, lambda: f"{_host_ip()}:{_free_port()}"
            )
            self._initialized_distributed = True
        import functools

        refs = []
        for w in worker_group.workers:
            refs.append(
                w.actor.execute.remote(
                    _jax_worker_setup,
                    coordinator,
                    n,
                    w.world_rank,
                    self._config.use_tpu,
                )
            )
        from .. import api as ray_api

        ray_api.get(refs)

    def on_shutdown(self, worker_group):
        if self._initialized_distributed:
            try:
                worker_group.execute(_jax_shutdown)
            except Exception:
                pass


# -- Torch -------------------------------------------------------------------


class TorchConfig(BackendConfig):
    """torch.distributed process-group bootstrap over TCP/gloo (CPU) for
    parity with the reference's TorchTrainer (train/torch/config.py)."""

    def __init__(self, backend: str = "gloo", timeout_s: int = 1800):
        self.backend_name = backend
        self.timeout_s = timeout_s

    def backend(self) -> "Backend":
        return _TorchBackend(self)


def _torch_worker_setup(master_addr, master_port, world_size, rank, backend, timeout_s):
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = str(master_addr)
    os.environ["MASTER_PORT"] = str(master_port)
    if not dist.is_initialized():
        dist.init_process_group(
            backend=backend,
            init_method=f"tcp://{master_addr}:{master_port}",
            world_size=world_size,
            rank=rank,
            timeout=datetime.timedelta(seconds=timeout_s),
        )
    return True


def _torch_shutdown():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    return True


class _TorchBackend(Backend):
    def __init__(self, config: TorchConfig):
        self._config = config

    def on_start(self, worker_group):
        addr_port = worker_group.execute_single(
            0, lambda: (_host_ip(), _free_port())
        )
        n = len(worker_group.workers)
        from .. import api as ray_api

        refs = [
            w.actor.execute.remote(
                _torch_worker_setup,
                addr_port[0],
                addr_port[1],
                n,
                w.world_rank,
                self._config.backend_name,
                self._config.timeout_s,
            )
            for w in worker_group.workers
        ]
        ray_api.get(refs)

    def on_shutdown(self, worker_group):
        try:
            worker_group.execute(_torch_shutdown)
        except Exception:
            pass


# -- TensorFlow --------------------------------------------------------------


class TensorflowConfig(BackendConfig):
    """TF_CONFIG cluster bootstrap (reference: train/tensorflow/config.py —
    each ranked worker gets the full worker address list + its own index so
    tf.distribute.MultiWorkerMirroredStrategy forms the collective ring)."""

    def backend(self) -> "Backend":
        return _TensorflowBackend()


def _tf_advertise():
    return f"{_host_ip()}:{_free_port()}"


def _tf_worker_setup(cluster, rank):
    import json
    import os

    os.environ["TF_CONFIG"] = json.dumps(
        {
            "cluster": {"worker": list(cluster)},
            "task": {"type": "worker", "index": rank},
        }
    )
    return True


class _TensorflowBackend(Backend):
    def on_start(self, worker_group):
        from .. import api as ray_api

        # every worker advertises its own host:port — multi-host correct,
        # unlike deriving all addresses on rank 0; gathered concurrently
        # (workers are rank-ordered, so the list index IS the task index)
        cluster = ray_api.get(
            [
                w.actor.execute.remote(_tf_advertise)
                for w in worker_group.workers
            ]
        )
        ray_api.get(
            [
                w.actor.execute.remote(_tf_worker_setup, cluster, w.world_rank)
                for w in worker_group.workers
            ]
        )
