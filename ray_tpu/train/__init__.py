"""ray_tpu.train: distributed training on TPU slices.

Role-equivalent of the reference's Ray Train v2 (python/ray/train/v2) built
TPU-first: JaxTrainer gang-schedules one ranked worker per slice host via a
slice-reserving placement group, bootstraps jax.distributed, and the user
loop compiles to pjit/GSPMD with collectives over ICI.
"""

from . import collective
from .backend import BackendConfig, JaxConfig, TorchConfig
from .callbacks import (
    TPUReservationCallback,
    TrainCallback,
    WeightPublishCallback,
)
from .checkpoint import Checkpoint, CheckpointManager, load_latest_checkpoint
from .sharded_checkpoint import (
    ShardedCheckpointWriter,
    restore_sharded,
    save_sharded,
)
from .config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from .controller import Result, RunState, TrainController
from .elastic import publish_train_state, restore_train_state
from .session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    in_session,
    report,
)
from .trainer import DataParallelTrainer, JaxTrainer, TorchTrainer
from .integrations import (
    LightGBMTrainer,
    LightningTrainer,
    TensorflowTrainer,
    XGBoostTrainer,
)
from .worker_group import WorkerGroup


def __getattr__(name):
    # PEP 562 lazy submodule (same pattern as ray_tpu/__init__.py): the
    # transformers import behind train.huggingface costs seconds and must
    # not tax every worker bootstrap that only needs Jax/Torch trainers
    if name == "huggingface":
        import importlib

        module = importlib.import_module(".huggingface", __name__)
        globals()[name] = module
        return module
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "save_sharded",
    "restore_sharded",
    "ShardedCheckpointWriter",
    "BackendConfig",
    "JaxConfig",
    "TorchConfig",
    "TrainCallback",
    "TPUReservationCallback",
    "Checkpoint",
    "CheckpointManager",
    "load_latest_checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "RunConfig",
    "ScalingConfig",
    "Result",
    "RunState",
    "TrainController",
    "DataParallelTrainer",
    "JaxTrainer",
    "TorchTrainer",
    "WorkerGroup",
    "LightningTrainer",
    "TensorflowTrainer",
    "XGBoostTrainer",
    "LightGBMTrainer",
    "huggingface",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
    "in_session",
    "report",
    "publish_train_state",
    "restore_train_state",
]
