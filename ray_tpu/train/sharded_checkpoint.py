"""Sharded (orbax) checkpointing for jax param/optimizer pytrees.

Role-equivalent of the reference's Checkpoint storage layer
(ray.train.Checkpoint + StorageContext, train/_checkpoint.py:56 and SURVEY
§5 "TPU equivalent: orbax-style async sharded checkpoint"): on a device
mesh every host writes only its own shards (orbax OCDBT), restore re-lays
the arrays out to any target sharding — so a checkpoint taken on one mesh
restores onto a differently-sized one (elastic restarts recompile and
re-shard). Plain ray_tpu.train.Checkpoint stays the directory-of-files
handle; this module is the tensor-state fast path inside it.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

logger = logging.getLogger(__name__)

_STATE_SUBDIR = "sharded_state"


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(ocp.PyTreeCheckpointHandler())


def _async_checkpointer():
    import orbax.checkpoint as ocp

    return ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())


class ShardedCheckpointWriter:
    """Async writer: ``save`` returns immediately while device->storage
    transfer continues in the background (orbax AsyncCheckpointer); call
    ``wait`` (or save again) to join the previous write. The train loop
    overlaps the next step with checkpoint IO — the reference's async
    checkpoint upload, done TPU-style with per-host shard writes."""

    def __init__(self):
        self._ckptr = None

    def save(self, path: str, state: Any) -> str:
        if self._ckptr is None:
            self._ckptr = _async_checkpointer()
        else:
            self._ckptr.wait_until_finished()
        target = os.path.join(os.path.abspath(path), _STATE_SUBDIR)
        self._ckptr.save(target, state, force=True)
        return target

    def wait(self):
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()

    def close(self):
        self.wait()
        if self._ckptr is not None:
            self._ckptr.close()
            self._ckptr = None


def save_sharded(path: str, state: Any) -> str:
    """One-shot sharded save of a pytree of jax arrays (params, opt state).
    Each host writes only the shards it owns."""
    target = os.path.join(os.path.abspath(path), _STATE_SUBDIR)
    ckptr = _checkpointer()
    try:
        ckptr.save(target, state, force=True)
    finally:
        ckptr.close()
    return target


def restore_sharded(
    path: str,
    *,
    target: Optional[Any] = None,
    shardings: Optional[Any] = None,
) -> Any:
    """Restore a sharded checkpoint.

    ``target``: template pytree (abstract or concrete) fixing structure and
    dtypes. ``shardings``: matching pytree of jax.sharding.Sharding laying
    the restored arrays onto the CURRENT mesh — pass the new mesh's
    shardings to restore a checkpoint from a differently-shaped run.
    """
    import jax
    import orbax.checkpoint as ocp

    src = os.path.join(os.path.abspath(path), _STATE_SUBDIR)
    if not os.path.exists(src):
        raise FileNotFoundError(f"no sharded state under {path}")
    ckptr = _checkpointer()
    try:
        if target is None and shardings is None:
            return ckptr.restore(src)
        restore_args = None
        if shardings is not None:
            def _arg(s, t=None):
                return ocp.ArrayRestoreArgs(
                    sharding=s,
                    dtype=(t.dtype if t is not None and hasattr(t, "dtype") else None),
                )

            if target is not None:
                restore_args = jax.tree.map(_arg, shardings, target)
            else:
                restore_args = jax.tree.map(_arg, shardings)
        return ckptr.restore(
            src,
            args=ocp.args.PyTreeRestore(
                item=target,
                restore_args=restore_args,
            ),
        )
    finally:
        ckptr.close()
