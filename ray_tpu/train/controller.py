"""Train controller: the run's state machine.

Role-equivalent of the reference's TrainController
(train/v2/_internal/execution/controller/controller.py:100; control loop
:396-509, states controller/state.py): bring up the worker group (with any
TPU slice reservation from callbacks), bootstrap the backend, start the
user loop everywhere, poll workers, register checkpoints, and apply the
failure policy — restart the whole gang (SPMD requires all-or-nothing) up to
``FailureConfig.max_failures`` times, resuming from the latest checkpoint.
"""

from __future__ import annotations

import enum
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..exceptions import CollectiveAbortedError
from ..runtime.gcs import keys as gcs_keys
from .backend import BackendConfig
from .checkpoint import Checkpoint, CheckpointManager, load_latest_checkpoint
from .config import RunConfig, ScalingConfig
from .session import TrainingReport
from .worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class RunState(enum.Enum):
    INITIALIZING = "INITIALIZING"
    SCHEDULING = "SCHEDULING"
    RUNNING = "RUNNING"
    # elastic recovery: survivors kept, dead ranks dropped, group re-formed
    # at the surviving world size under a new collective epoch
    RESIZING = "RESIZING"
    # gang recovery: whole worker group torn down and respawned full-size
    RESTARTING = "RESTARTING"
    FINISHED = "FINISHED"
    ERRORED = "ERRORED"


@dataclass
class Result:
    """What fit() returns (reference: ray.train.Result)."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    path: str = ""
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class TrainController:
    def __init__(
        self,
        train_fn: Callable,
        train_fn_config: Optional[dict],
        scaling_config: ScalingConfig,
        run_config: RunConfig,
        backend_config: BackendConfig,
        datasets: Optional[Dict[str, Any]] = None,
        poll_interval: float = 0.1,
        callbacks: Optional[List[Any]] = None,
        quantized: bool = False,
        overlap: bool = False,
        bucket_bytes: Optional[int] = None,
        stale_grad: int = 0,
        slice_size: Optional[int] = None,
    ):
        self._train_fn = train_fn
        self._train_fn_config = train_fn_config
        self._scaling = scaling_config
        self._run_config = run_config
        self._backend_config = backend_config
        self._datasets = datasets or {}
        self._poll_interval = poll_interval
        self._callbacks = (
            callbacks if callbacks is not None else list(run_config.callbacks)
        )
        self.state = RunState.INITIALIZING
        self._checkpoints = CheckpointManager(
            run_config.run_dir, run_config.checkpoint_config
        )
        from .scaling_policy import make_scaling_policy

        self._scaling_policy = make_scaling_policy(scaling_config)
        # elastic configs carry (min, max); the policy's config is the
        # concrete max-sized one used for per-worker resource shapes
        self._scaling = self._scaling_policy.scaling_config
        self._failures = 0
        self._metrics_history: List[Dict[str, Any]] = []
        # collective group epoch within the current attempt; bumped on
        # every elastic resize so the re-formed gang's rendezvous keys
        # never collide with an aborted epoch's
        self._epoch = 0
        self._resizes = 0
        self._restart_t0: Optional[float] = None
        # int8+error-feedback transport for the run's collective group and
        # train-state publishes; threaded into every worker's TrainContext
        self._quantized = quantized
        # overlapped-reduction knobs (trainer.py docs them); all four ride
        # the same _run_fields -> TrainContext path as quantized, so a
        # resize/restart re-forms the gang with identical settings
        self._overlap = overlap
        self._bucket_bytes = bucket_bytes
        self._stale_grad = stale_grad
        self._slice_size = slice_size

    # -- lifecycle ---------------------------------------------------------

    def run(self) -> Result:
        import os

        os.makedirs(self._run_config.run_dir, exist_ok=True)
        max_failures = self._run_config.failure_config.max_failures
        while True:
            try:
                result = self._run_attempt()
                self.state = RunState.FINISHED
                for cb in self._callbacks:
                    _safe(cb.after_run, result)
                return result
            except _WorkerGroupFailure as f:
                self._failures += 1
                retriable = max_failures < 0 or self._failures <= max_failures
                if not retriable:
                    self.state = RunState.ERRORED
                    result = Result(
                        metrics=self._latest_metrics(),
                        checkpoint=self._checkpoints.latest_checkpoint,
                        error=f.error,
                        path=self._run_config.run_dir,
                        metrics_history=list(self._metrics_history),
                    )
                    for cb in self._callbacks:
                        _safe(cb.after_run, result)
                    return result
                self.state = RunState.RESTARTING
                from ..util import metrics

                metrics.record_train_restart(self._run_config.name or "")
                self._restart_t0 = time.perf_counter()
                logger.warning(
                    "worker group failed (attempt %d/%s): %s — restarting from "
                    "latest checkpoint",
                    self._failures,
                    "inf" if max_failures < 0 else max_failures,
                    f.error,
                )

    def _run_attempt(self) -> Result:
        self.state = RunState.SCHEDULING
        # the scaling policy sizes this attempt's gang (elastic: shrink to
        # what fits now, grow back on later restarts)
        decision = self._scaling_policy.decide(self._failures)
        attempt_scaling = self._scaling
        if decision.num_workers != attempt_scaling.num_workers:
            from dataclasses import replace

            attempt_scaling = replace(
                attempt_scaling, num_workers=decision.num_workers
            )
        overrides: Dict[str, Any] = {}
        for cb in self._callbacks:
            out = cb.before_worker_group_start(attempt_scaling)
            if out:
                overrides.update(out)
        wg = WorkerGroup(
            attempt_scaling,
            placement_group_override=overrides.get("placement_group_override"),
            bundle_label_selector=overrides.get("bundle_label_selector"),
        )
        self._epoch = 0
        try:
            wg.create()
            for cb in self._callbacks:
                _safe(cb.after_worker_group_start, wg)
            wg.init_contexts(self._run_fields())
            self._setup_dataset_shards(wg)
            backend = self._backend_config.backend()
            backend.on_start(wg)
            self._push_resume(wg)
            self.state = RunState.RUNNING
            wg.start_training(self._train_fn, self._train_fn_config)
            self._publish_run_record(wg, "RUNNING")
            if self._restart_t0 is not None:
                from ..util import metrics

                metrics.record_train_recovery(
                    self._run_config.name or "",
                    time.perf_counter() - self._restart_t0,
                    kind="restart",
                )
                self._restart_t0 = None
            error = self._poll_until_done(wg)
            backend.on_shutdown(wg)
            if error is not None:
                raise _WorkerGroupFailure(error)
            return Result(
                metrics=self._latest_metrics(),
                checkpoint=self._checkpoints.latest_checkpoint,
                error=None,
                path=self._run_config.run_dir,
                metrics_history=list(self._metrics_history),
            )
        finally:
            self._delete_run_record()
            for cb in self._callbacks:
                _safe(cb.before_worker_group_shutdown, wg)
            wg.shutdown()

    def _run_fields(self) -> dict:
        # attempt-scoped group name: a restarted gang must not read the
        # failed attempt's stale rendezvous keys from the GCS KV; within an
        # attempt, elastic resizes keep the name and bump the epoch instead
        return dict(
            experiment_name=self._run_config.name,
            run_dir=self._run_config.run_dir,
            collective_group=self._group_name(),
            collective_epoch=self._epoch,
            collective_quantized=self._quantized,
            collective_overlap=self._overlap,
            collective_bucket_bytes=self._bucket_bytes,
            collective_stale_grad=self._stale_grad,
            collective_slice_size=self._slice_size,
        )

    def _group_name(self) -> str:
        return f"train:{self._run_config.name}:{self._failures}"

    def _push_resume(self, wg: WorkerGroup):
        # resume: push the latest checkpoint into each worker context
        resume = self._checkpoints.latest_checkpoint or load_latest_checkpoint(
            self._run_config.run_dir
        )
        if resume is not None:
            def _set_resume(ckpt=resume):
                from . import session

                session.get_context().latest_checkpoint = ckpt

            wg.execute(_set_resume)

    def _poll_until_done(self, wg: WorkerGroup) -> Optional[Exception]:
        """Drain reports until every worker finishes or one fails. With
        ``FailureConfig(elastic=True)`` a worker/actor death (or an aborted
        collective) triggers an in-place resize instead of failing the
        attempt: survivors are kept, ranks re-assigned, and training
        resumes at the surviving world size."""
        elastic = self._run_config.failure_config.elastic
        while True:
            statuses = wg.poll_each()
            dead = [
                i for i, s in enumerate(statuses) if not isinstance(s, dict)
            ]
            for s in statuses:
                if isinstance(s, dict):
                    for report in s["reports"]:
                        self._process_report(report)
            aborted = False
            for s in statuses:
                if isinstance(s, dict) and s["error"] is not None:
                    exc = s.get("error_exc") or RuntimeError(s["error"])
                    if elastic and isinstance(exc, CollectiveAbortedError):
                        # a resize casualty, not a user failure: the worker's
                        # in-flight collective was aborted by a peer death
                        aborted = True
                    else:
                        return exc
            if dead or aborted:
                if not elastic:
                    return statuses[dead[0]]
                error = self._resize(wg)
                if error is not None:
                    return error
                continue
            if all(s["done"] for s in statuses):
                return None
            time.sleep(self._poll_interval)

    def _resize(self, wg: WorkerGroup) -> Optional[Exception]:
        """Elastic recovery: abort the epoch, drop dead ranks, re-rank the
        survivors, bump the epoch, and restart training without respawning
        healthy processes. Returns an exception when a resize can't satisfy
        ``min_workers`` — the caller then falls back to a gang restart
        (which counts against ``max_failures``)."""
        from .. import collective
        from ..util import metrics

        fc = self._run_config.failure_config
        run_name = self._run_config.name or ""
        t0 = time.perf_counter()
        self.state = RunState.RESIZING
        self._publish_run_record(wg, "RESIZING")
        # belt and braces: the GCS death path normally writes the abort the
        # moment the raylet reports the worker gone, but an explicit write
        # here also covers deaths the pub path missed (partitioned raylet)
        try:
            collective.abort_collective_group(
                self._group_name(), self._epoch, reason="controller resize"
            )
        except Exception:
            pass
        alive = wg.ping()
        dead_idx = [i for i, ok in enumerate(alive) if not ok]
        survivors = len(wg.workers) - len(dead_idx)
        if survivors < max(fc.min_workers, 1):
            return RuntimeError(
                f"elastic resize impossible: {survivors} survivor(s) < "
                f"min_workers={fc.min_workers} — falling back to gang restart"
            )
        try:
            if dead_idx:
                removed = wg.remove_workers(dead_idx)
                logger.warning(
                    "elastic resize: lost rank(s) %s — re-forming at "
                    "world_size=%d",
                    [w.world_rank for w in removed],
                    len(wg.workers),
                )
            # survivors' aborted train threads must exit before the re-form
            wg.reset_for_restart()
            # final drain: reports queued between the abort and the thread
            # exit would otherwise vanish when init_contexts replaces the
            # context
            for s in wg.poll_each():
                if isinstance(s, dict):
                    for report in s["reports"]:
                        self._process_report(report)
            self._epoch += 1
            wg.init_contexts(self._run_fields())
            self._setup_dataset_shards(wg)
            self._push_resume(wg)
            wg.start_training(self._train_fn, self._train_fn_config)
        except Exception as e:  # a second death mid-re-form etc.
            logger.warning("elastic resize failed (%s) — gang restart", e)
            return e
        self.state = RunState.RUNNING
        self._resizes += 1
        metrics.record_train_resize(run_name)
        metrics.record_train_recovery(
            run_name, time.perf_counter() - t0, kind="resize"
        )
        self._publish_run_record(wg, "RUNNING")
        logger.warning(
            "elastic resize complete: world_size=%d epoch=%d (%.2fs)",
            len(wg.workers), self._epoch, time.perf_counter() - t0,
        )
        return None

    # -- run record (chaos CLI / dashboards) -------------------------------

    def _publish_run_record(self, wg: WorkerGroup, state: str):
        """Publish this run's live topology to the GCS KV
        (``trainrun:<name>``) so out-of-process tooling — the chaos CLI,
        dashboards — can target a specific rank/pid or the collective
        group/epoch."""
        try:
            record = {
                "state": state,
                "group": self._group_name(),
                "epoch": self._epoch,
                "world_size": len(wg.workers),
                "resizes": self._resizes,
                "failures": self._failures,
                "workers": [
                    {
                        "rank": w.world_rank,
                        "pid": w.metadata.get("pid"),
                        "node_id": w.node_id,
                        "hostname": w.metadata.get("hostname"),
                    }
                    for w in wg.workers
                ],
            }
            self._kv_call(
                "kv_put",
                gcs_keys.TRAIN_RUN.key(self._run_config.name),
                json.dumps(record).encode(),
                True,
            )
        except Exception:
            pass

    def _delete_run_record(self):
        try:
            self._kv_call(
                "kv_del", gcs_keys.TRAIN_RUN.key(self._run_config.name)
            )
        except Exception:
            pass

    @staticmethod
    def _kv_call(method: str, *args):
        from .. import _worker_api

        worker = _worker_api.get_core_worker()
        client = worker.client_pool.get(*worker.gcs_address)
        return _worker_api.run_on_worker_loop(client.call(method, *args))

    def _process_report(self, report: TrainingReport):
        if report.metrics:
            entry = dict(report.metrics)
            entry["_world_rank"] = report.world_rank
            entry["_report_index"] = report.index
            self._metrics_history.append(entry)
        if report.checkpoint is not None:
            self._checkpoints.register(
                report.checkpoint, report.index, report.metrics
            )
        for cb in self._callbacks:
            _safe(cb.on_report, report)

    def _setup_dataset_shards(self, wg: WorkerGroup):
        if not self._datasets:
            return
        n = len(wg.workers)
        for name, ds in self._datasets.items():
            shards = _split_dataset(ds, n)
            from .. import api as ray_api

            ray_api.get(
                [
                    w.actor.set_dataset_shard.remote(name, shards[w.world_rank])
                    for w in wg.workers
                ]
            )

    def _latest_metrics(self) -> Dict[str, Any]:
        # last report from rank 0, falling back to any rank
        for entry in reversed(self._metrics_history):
            if entry.get("_world_rank") == 0:
                return {k: v for k, v in entry.items() if not k.startswith("_")}
        if self._metrics_history:
            return {
                k: v
                for k, v in self._metrics_history[-1].items()
                if not k.startswith("_")
            }
        return {}


def _split_dataset(ds, n: int):
    """Split a dataset across n workers: ray_tpu.data datasets use
    streaming_split; plain lists/iterables are sharded round-robin."""
    if hasattr(ds, "streaming_split"):
        return ds.streaming_split(n, equal=True)
    items = list(ds)
    return [items[i::n] for i in range(n)]


class _WorkerGroupFailure(Exception):
    def __init__(self, error: Exception):
        super().__init__(str(error))
        self.error = error


def _safe(fn, *args):
    try:
        fn(*args)
    except Exception:
        logger.exception("train callback %s failed", fn)
