"""Train controller: the run's state machine.

Role-equivalent of the reference's TrainController
(train/v2/_internal/execution/controller/controller.py:100; control loop
:396-509, states controller/state.py): bring up the worker group (with any
TPU slice reservation from callbacks), bootstrap the backend, start the
user loop everywhere, poll workers, register checkpoints, and apply the
failure policy — restart the whole gang (SPMD requires all-or-nothing) up to
``FailureConfig.max_failures`` times, resuming from the latest checkpoint.
"""

from __future__ import annotations

import enum
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .backend import BackendConfig
from .checkpoint import Checkpoint, CheckpointManager, load_latest_checkpoint
from .config import RunConfig, ScalingConfig
from .session import TrainingReport
from .worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class RunState(enum.Enum):
    INITIALIZING = "INITIALIZING"
    SCHEDULING = "SCHEDULING"
    RUNNING = "RUNNING"
    RESTARTING = "RESTARTING"
    FINISHED = "FINISHED"
    ERRORED = "ERRORED"


@dataclass
class Result:
    """What fit() returns (reference: ray.train.Result)."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    path: str = ""
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class TrainController:
    def __init__(
        self,
        train_fn: Callable,
        train_fn_config: Optional[dict],
        scaling_config: ScalingConfig,
        run_config: RunConfig,
        backend_config: BackendConfig,
        datasets: Optional[Dict[str, Any]] = None,
        poll_interval: float = 0.1,
        callbacks: Optional[List[Any]] = None,
    ):
        self._train_fn = train_fn
        self._train_fn_config = train_fn_config
        self._scaling = scaling_config
        self._run_config = run_config
        self._backend_config = backend_config
        self._datasets = datasets or {}
        self._poll_interval = poll_interval
        self._callbacks = (
            callbacks if callbacks is not None else list(run_config.callbacks)
        )
        self.state = RunState.INITIALIZING
        self._checkpoints = CheckpointManager(
            run_config.run_dir, run_config.checkpoint_config
        )
        from .scaling_policy import make_scaling_policy

        self._scaling_policy = make_scaling_policy(scaling_config)
        # elastic configs carry (min, max); the policy's config is the
        # concrete max-sized one used for per-worker resource shapes
        self._scaling = self._scaling_policy.scaling_config
        self._failures = 0
        self._metrics_history: List[Dict[str, Any]] = []

    # -- lifecycle ---------------------------------------------------------

    def run(self) -> Result:
        import os

        os.makedirs(self._run_config.run_dir, exist_ok=True)
        max_failures = self._run_config.failure_config.max_failures
        while True:
            try:
                result = self._run_attempt()
                self.state = RunState.FINISHED
                for cb in self._callbacks:
                    _safe(cb.after_run, result)
                return result
            except _WorkerGroupFailure as f:
                self._failures += 1
                retriable = max_failures < 0 or self._failures <= max_failures
                if not retriable:
                    self.state = RunState.ERRORED
                    result = Result(
                        metrics=self._latest_metrics(),
                        checkpoint=self._checkpoints.latest_checkpoint,
                        error=f.error,
                        path=self._run_config.run_dir,
                        metrics_history=list(self._metrics_history),
                    )
                    for cb in self._callbacks:
                        _safe(cb.after_run, result)
                    return result
                self.state = RunState.RESTARTING
                logger.warning(
                    "worker group failed (attempt %d/%s): %s — restarting from "
                    "latest checkpoint",
                    self._failures,
                    "inf" if max_failures < 0 else max_failures,
                    f.error,
                )

    def _run_attempt(self) -> Result:
        self.state = RunState.SCHEDULING
        # the scaling policy sizes this attempt's gang (elastic: shrink to
        # what fits now, grow back on later restarts)
        decision = self._scaling_policy.decide(self._failures)
        attempt_scaling = self._scaling
        if decision.num_workers != attempt_scaling.num_workers:
            from dataclasses import replace

            attempt_scaling = replace(
                attempt_scaling, num_workers=decision.num_workers
            )
        overrides: Dict[str, Any] = {}
        for cb in self._callbacks:
            out = cb.before_worker_group_start(attempt_scaling)
            if out:
                overrides.update(out)
        wg = WorkerGroup(
            attempt_scaling,
            placement_group_override=overrides.get("placement_group_override"),
            bundle_label_selector=overrides.get("bundle_label_selector"),
        )
        try:
            wg.create()
            for cb in self._callbacks:
                _safe(cb.after_worker_group_start, wg)
            # attempt-scoped group name: a restarted gang must not read the
            # failed attempt's stale rendezvous keys from the GCS KV
            run_fields = dict(
                experiment_name=self._run_config.name,
                run_dir=self._run_config.run_dir,
                collective_group=f"train:{self._run_config.name}:{self._failures}",
            )
            wg.init_contexts(run_fields)
            self._setup_dataset_shards(wg)
            backend = self._backend_config.backend()
            backend.on_start(wg)
            # resume: push the latest checkpoint into each worker context
            resume = self._checkpoints.latest_checkpoint or load_latest_checkpoint(
                self._run_config.run_dir
            )
            if resume is not None:
                def _set_resume(ckpt=resume):
                    from . import session

                    session.get_context().latest_checkpoint = ckpt

                wg.execute(_set_resume)
            self.state = RunState.RUNNING
            wg.start_training(self._train_fn, self._train_fn_config)
            error = self._poll_until_done(wg)
            backend.on_shutdown(wg)
            if error is not None:
                raise _WorkerGroupFailure(error)
            return Result(
                metrics=self._latest_metrics(),
                checkpoint=self._checkpoints.latest_checkpoint,
                error=None,
                path=self._run_config.run_dir,
                metrics_history=list(self._metrics_history),
            )
        finally:
            for cb in self._callbacks:
                _safe(cb.before_worker_group_shutdown, wg)
            wg.shutdown()

    def _poll_until_done(self, wg: WorkerGroup) -> Optional[Exception]:
        """Drain reports until every worker finishes or one fails."""
        while True:
            try:
                statuses = wg.poll()
            except Exception as e:  # worker/actor died (node loss etc.)
                return e
            for status in statuses:
                for report in status["reports"]:
                    self._process_report(report)
            for status in statuses:
                if status["error"] is not None:
                    exc = status.get("error_exc") or RuntimeError(status["error"])
                    return exc
            if all(s["done"] for s in statuses):
                return None
            time.sleep(self._poll_interval)

    def _process_report(self, report: TrainingReport):
        if report.metrics:
            entry = dict(report.metrics)
            entry["_world_rank"] = report.world_rank
            entry["_report_index"] = report.index
            self._metrics_history.append(entry)
        if report.checkpoint is not None:
            self._checkpoints.register(
                report.checkpoint, report.index, report.metrics
            )
        for cb in self._callbacks:
            _safe(cb.on_report, report)

    def _setup_dataset_shards(self, wg: WorkerGroup):
        if not self._datasets:
            return
        n = len(wg.workers)
        for name, ds in self._datasets.items():
            shards = _split_dataset(ds, n)
            from .. import api as ray_api

            ray_api.get(
                [
                    w.actor.set_dataset_shard.remote(name, shards[w.world_rank])
                    for w in wg.workers
                ]
            )

    def _latest_metrics(self) -> Dict[str, Any]:
        # last report from rank 0, falling back to any rank
        for entry in reversed(self._metrics_history):
            if entry.get("_world_rank") == 0:
                return {k: v for k, v in entry.items() if not k.startswith("_")}
        if self._metrics_history:
            return {
                k: v
                for k, v in self._metrics_history[-1].items()
                if not k.startswith("_")
            }
        return {}


def _split_dataset(ds, n: int):
    """Split a dataset across n workers: ray_tpu.data datasets use
    streaming_split; plain lists/iterables are sharded round-robin."""
    if hasattr(ds, "streaming_split"):
        return ds.streaming_split(n, equal=True)
    items = list(ds)
    return [items[i::n] for i in range(n)]


class _WorkerGroupFailure(Exception):
    def __init__(self, error: Exception):
        super().__init__(str(error))
        self.error = error


def _safe(fn, *args):
    try:
        fn(*args)
    except Exception:
        logger.exception("train callback %s failed", fn)
