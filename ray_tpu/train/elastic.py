"""Weight-plane train-state resume for elastic training.

On an elastic resize the surviving workers restart their train fn and must
pick up where the gang left off WITHOUT a filesystem checkpoint restore —
recovery has to land in seconds. The mechanism: rank 0 publishes a small
replicated record ``{"params", "opt_state", "step"}`` to the weight plane
(``train-state:<experiment>``) alongside (or instead of) each checkpoint;
after a resize every worker re-resolves the latest version over the
broadcast tree and continues from ``step + 1``.

    def train_loop(config):
        state = restore_train_state()          # None on a fresh start
        step = state["step"] + 1 if state else 0
        params = state["params"] if state else init_params()
        while step < config["steps"]:
            params = train_step(params)        # CollectiveAbortedError
            publish_train_state(params, step=step)   # rank 0 only
            ray_tpu.train.report({"step": step})
            step += 1

The step rides inside the published pytree (the registry's ``get`` returns
no metadata), and is duplicated into the publish metadata so
``ray_tpu list weights`` shows it.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..runtime.gcs import keys as gcs_keys
from .session import get_context


def _state_name(name: Optional[str]) -> str:
    return name if name else gcs_keys.TRAIN_STATE.key(
        get_context().experiment_name
    )


def publish_train_state(
    params: Any,
    opt_state: Any = None,
    step: int = 0,
    *,
    name: Optional[str] = None,
    meta: Optional[dict] = None,
    quantized: Optional[bool] = None,
):
    """Publish the run's resumable state to the weight plane. Rank 0 only —
    other ranks no-op (SPMD state is replicated) and return None. Returns
    the published :class:`WeightHandle` on rank 0. ``quantized`` defaults
    to the run's transport setting (``JaxTrainer(quantized=True)``): a
    quantized run resumes from int8-coded state, halving resize recovery
    bytes the same way its gradient collectives are halved."""
    ctx = get_context()
    if ctx.world_rank != 0:
        return None
    if quantized is None:
        quantized = ctx.collective_quantized
    from .. import weights

    payload = {
        "params": params,
        "opt_state": opt_state,
        # int64 scalar rides as a pytree leaf: chunk_pytree np.asarray's
        # every leaf, so it round-trips exactly
        "step": np.int64(step),
    }
    full_meta = {"step": int(step), "world_size": ctx.world_size}
    if meta:
        full_meta.update(meta)
    return weights.publish(
        _state_name(name), payload, meta=full_meta, quantized=quantized
    )


def restore_train_state(
    *, name: Optional[str] = None, sharding: Any = None
) -> Optional[Dict[str, Any]]:
    """Fetch the latest published train state over the weight plane.
    Returns ``{"params", "opt_state", "step", "version"}`` or None when
    nothing has been published yet (fresh start)."""
    from .. import weights

    # a resumed loop must start with a clean reduction pipeline: any
    # delayed (stale_grad) gradients still pending belong to the aborted
    # epoch's group and would poison the first step after the re-form
    ctx = get_context()
    ctx._grad_scheduler = None

    try:
        version, payload = weights.fetch(_state_name(name), sharding=sharding)
    except KeyError:
        return None
    return {
        "params": payload.get("params"),
        "opt_state": payload.get("opt_state"),
        "step": int(payload["step"]),
        "version": version,
    }
