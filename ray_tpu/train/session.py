"""Worker-side training session: get_context() / report().

Role-equivalent of the reference's ray.train.get_context + report
(train/v2/_internal/execution/context.py, train/context.py): inside
``train_loop_per_worker`` the user asks for ranks/world size, reports
metrics+checkpoints, and fetches dataset shards. Reports are queued in the
worker and drained by the controller's poll loop (reference: thread_runner +
ReportCallbackHandler).
"""

from __future__ import annotations

import os
import shutil
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from .checkpoint import Checkpoint


@dataclass
class TrainingReport:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    index: int
    world_rank: int


@dataclass
class TrainContext:
    world_rank: int
    local_rank: int
    node_rank: int
    world_size: int
    local_world_size: int
    experiment_name: str
    run_dir: str
    collective_group: str = ""
    # bumped by the controller on every elastic resize; scopes the
    # collective rendezvous keys so a re-formed gang never reads an
    # aborted epoch's state
    collective_epoch: int = 0
    # int8-with-error-feedback collectives for this run's group, and the
    # default codec for publish_train_state — must be gang-uniform, so it
    # rides in the context rather than per-call arguments
    collective_quantized: bool = False
    # overlapped gradient reduction (collective/scheduler.py): when True,
    # train.collective.reduce_gradients() dispatches bucketized async
    # allreduces instead of one blocking op. All gang-uniform for the same
    # reason quantized is — every rank must bucketize and dispatch
    # identically or the rendezvous sequence desyncs.
    collective_overlap: bool = False
    collective_bucket_bytes: Optional[int] = None
    collective_stale_grad: int = 0
    # hierarchical topology: ranks per slice (None = flat group)
    collective_slice_size: Optional[int] = None
    latest_checkpoint: Optional[Checkpoint] = None
    dataset_shards: Dict[str, Any] = field(default_factory=dict)

    # report queue drained by TrainWorker.poll()
    _reports: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _report_count: int = 0
    # report-to-report step telemetry (compute/collective split +
    # scaling-efficiency gauge; util/metrics.StepBreakdown)
    _step_breakdown: Any = None
    # per-worker step-time series (util/timeseries.py) — the straggler
    # detector's cross-worker input; wall-clock of the previous report
    _step_series: Any = None
    _last_report_t: Optional[float] = None
    # lazily-built GradientReduceScheduler for this run's group (one per
    # context: the re-formed gang's context rebuilds it at the new epoch)
    _grad_scheduler: Any = None

    # -- user-facing accessors (reference: TrainContext methods) ----------

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_storage_path(self) -> str:
        return self.run_dir

    # -- report -----------------------------------------------------------

    def report(
        self,
        metrics: Dict[str, Any],
        checkpoint: Optional[Checkpoint] = None,
    ):
        """Queue metrics (and persist a checkpoint) for the controller.

        A reported checkpoint directory is *uploaded* (copied) into the
        run's storage as ``checkpoint_{index:06d}``; all ranks reporting the
        same index merge into one logical sharded checkpoint (files must be
        rank-unique, which orbax guarantees via per-process shards).
        """
        index = self._report_count
        self._report_count += 1
        # each report marks a train-step boundary: record the interval's
        # compute/collective breakdown for the scaling-efficiency gauge
        if self._step_breakdown is None:
            from ..util.metrics import StepBreakdown

            self._step_breakdown = StepBreakdown(role="train")
        self._step_breakdown.mark()
        self._record_step_series()
        persisted: Optional[Checkpoint] = None
        if checkpoint is not None:
            dest = os.path.join(self.run_dir, f"checkpoint_{index:06d}")
            if os.path.abspath(checkpoint.path) != dest:
                os.makedirs(dest, exist_ok=True)
                shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            persisted = Checkpoint(dest)
            self.latest_checkpoint = persisted
        with self._lock:
            self._reports.append(
                TrainingReport(dict(metrics), persisted, index, self.world_rank)
            )

    def _record_step_series(self):
        """Publish this worker's report-to-report wall clock into the
        telemetry plane. Labels name the run/group/rank so the GCS-side
        MAD detector can compare ranks inside one gang; the point carries
        the worker's root trace id as an exemplar so a STRAGGLER_DETECTED
        event links straight to its trace timeline. Never raises."""
        import time as _time

        now = _time.time()
        last, self._last_report_t = self._last_report_t, now
        if last is None:
            return
        try:
            if self._step_series is None:
                from ..util import timeseries as _ts

                self._step_series = _ts.register_series(
                    _ts.STEP_TIME_S,
                    labels={
                        "run": self.experiment_name,
                        "group": self.collective_group,
                        "rank": str(self.world_rank),
                    },
                )
            from ..util import tracing as _tracing

            ctx = _tracing.current_context()
            self._step_series.record(
                now - last, ts=now,
                exemplar=ctx["trace_id"] if ctx else None,
            )
        except Exception:
            pass  # telemetry is best-effort; never fail a report

    def drain_reports(self):
        with self._lock:
            out, self._reports = self._reports, []
        return out


_context: Optional[TrainContext] = None


def set_context(ctx: Optional[TrainContext]):
    global _context
    _context = ctx


def get_context() -> TrainContext:
    if _context is None:
        raise RuntimeError(
            "ray_tpu.train.get_context() called outside a training worker"
        )
    return _context


def in_session() -> bool:
    return _context is not None


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    get_context().report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_context().latest_checkpoint


def get_dataset_shard(name: str = "train"):
    """Per-worker dataset shard (reference: ray.train.get_dataset_shard,
    fed by Dataset.streaming_split — data/dataset.py:1863)."""
    shards = get_context().dataset_shards
    if name not in shards:
        raise KeyError(
            f"no dataset shard {name!r}; pass datasets={{'{name}': ds}} to the "
            f"trainer"
        )
    return shards[name]
