"""LoRA parameter partitioning for frozen-base fine-tuning.

Reference role: the reference fine-tunes through torch/PEFT outside Ray core
(BASELINE.json config 3 — Llama-2-7B LoRA via JaxTrainer); here the split is
a pytree transform so ``jax.grad`` differentiates ONLY the adapter leaves and
the optimizer state exists ONLY for them. The frozen base rides through the
loss closure untouched — no wgrad compute, no adamw moments for 7B params.

Leaves named ``lora_a``/``lora_b`` (models/llama.py:LoRADense) are adapters;
everything else is base.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from flax import traverse_util


def _is_lora_key(path: Tuple[str, ...]) -> bool:
    return path[-1] in ("lora_a", "lora_b")


def split_lora(params: Any) -> Tuple[Dict, Dict]:
    """Split a flax param dict into (base, lora) trees of flat dicts."""
    flat = traverse_util.flatten_dict(params)
    base = {k: v for k, v in flat.items() if not _is_lora_key(k)}
    lora = {k: v for k, v in flat.items() if _is_lora_key(k)}
    return base, lora


def merge_lora(base: Dict, lora: Dict) -> Any:
    """Inverse of split_lora: one nested param dict for model.apply."""
    return traverse_util.unflatten_dict({**base, **lora})


def lora_label_fn(params: Any) -> Any:
    """Per-leaf 'lora'/'frozen' labels for optax.multi_transform when a
    caller prefers masking over splitting (keeps one tree, e.g. for
    orbax checkpoints of the full state)."""
    flat = traverse_util.flatten_dict(params)
    labels = {k: ("lora" if _is_lora_key(k) else "frozen") for k in flat}
    return traverse_util.unflatten_dict(labels)
