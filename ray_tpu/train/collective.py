"""Train-worker collectives (control-plane, host-side).

Role-equivalent of the reference's ray.train.collective
(train/collective/collectives.py:16,59 — broadcast_from_rank_zero / barrier
through a sync actor). Here they ride the framework's GCS-KV collective
group that every train worker joins at context init; device-plane
collectives (gradient psum etc.) belong *inside* jit via jax.lax — these
are only for small host-side control data (configs, coordinator addresses,
early-stop flags).
"""

from __future__ import annotations

from typing import Any

from .. import collective as _collective
from .session import get_context


def _group() -> str:
    name = get_context().collective_group
    if not name:
        raise RuntimeError("no collective group for this training run")
    return name


def broadcast_from_rank_zero(data: Any = None) -> Any:
    """Every worker calls this; all return rank 0's value (reference:
    collectives.py:16)."""
    return _collective.broadcast(data, src_rank=0, group_name=_group())


def barrier() -> None:
    """Block until every training worker arrives (reference:
    collectives.py:59)."""
    _collective.barrier(group_name=_group())


def allreduce(value, op=None):
    """Sum (default) a small host-side value across workers."""
    kwargs = {} if op is None else {"op": op}
    return _collective.allreduce(value, group_name=_group(), **kwargs)


def allgather(value) -> list:
    return _collective.allgather(value, group_name=_group())
