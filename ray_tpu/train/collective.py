"""Train-worker collectives (control-plane, host-side).

Role-equivalent of the reference's ray.train.collective
(train/collective/collectives.py:16,59 — broadcast_from_rank_zero / barrier
through a sync actor). Here they ride the framework's GCS-KV collective
group that every train worker joins at context init; device-plane
collectives (gradient psum etc.) belong *inside* jit via jax.lax — these
are only for small host-side control data (configs, coordinator addresses,
early-stop flags).
"""

from __future__ import annotations

from typing import Any

from .. import collective as _collective
from .session import get_context


def _group() -> str:
    name = get_context().collective_group
    if not name:
        raise RuntimeError("no collective group for this training run")
    return name


def broadcast_from_rank_zero(data: Any = None) -> Any:
    """Every worker calls this; all return rank 0's value (reference:
    collectives.py:16)."""
    return _collective.broadcast(data, src_rank=0, group_name=_group())


def barrier() -> None:
    """Block until every training worker arrives (reference:
    collectives.py:59)."""
    _collective.barrier(group_name=_group())


def allreduce(value, op=None):
    """Sum (default) a small host-side value across workers."""
    kwargs = {} if op is None else {"op": op}
    return _collective.allreduce(value, group_name=_group(), **kwargs)


def allgather(value) -> list:
    return _collective.allgather(value, group_name=_group())


def gradient_scheduler():
    """This run's :class:`~ray_tpu.collective.GradientReduceScheduler`,
    built lazily from the context's gang-uniform knobs (overlap /
    bucket_bytes / stale_grad set on the trainer) and cached on the
    context — a re-formed gang's fresh context rebuilds it over the new
    epoch's group."""
    from ..collective.bucketizer import DEFAULT_BUCKET_BYTES
    from ..collective.scheduler import GradientReduceScheduler

    ctx = get_context()
    if ctx._grad_scheduler is None:
        ctx._grad_scheduler = GradientReduceScheduler(
            _collective.get_group(_group()),
            bucket_bytes=ctx.collective_bucket_bytes or DEFAULT_BUCKET_BYTES,
            overlap=ctx.collective_overlap,
            stale_grad=ctx.collective_stale_grad,
        )
    return ctx._grad_scheduler


def reduce_gradients(grads: Any):
    """Sum a gradient pytree across the gang through the overlapped
    scheduler — the sanctioned gradient-reduction path in train loops
    (analysis rule RT010). Returns the summed tree; at ``stale_grad=1``
    the PREVIOUS step's (None on the first step — skip the update)."""
    return gradient_scheduler().step(grads)
