"""Llama LoRA fine-tune through JaxTrainer — the north-star Train config.

Reference config (BASELINE.json configs[2]): "Llama-2-7B LoRA fine-tune via
Ray Train JaxTrainer on v5e-64". This example is that pipeline end-to-end in
this framework: JaxTrainer gang-schedules one ranked worker per host (slice
reservation via TPUReservationCallback when ``use_tpu``/topology are set),
the Jax backend bootstraps jax.distributed so the slice is one SPMD program,
and each worker runs the same pjit/GSPMD-sharded LoRA step:

- base params bf16, frozen (no wgrads, no optimizer moments — train/lora.py
  split); LoRA adapters in adamw
- stacked layers under lax.scan + full per-layer remat (models/llama.py
  scan_layers — the form bench.py measures at ~0.70 MFU on one v5e chip)
- params sharded by the logical-axis rule table (embed→fsdp, mlp/heads→tp)
  over a mesh built from however many devices the slice exposes

``train_config`` keys: model ("tiny" | "7b"), epochs, steps_per_epoch,
batch_per_worker, seq, lora_rank, mesh axes overrides. The tiny default
runs on a CPU test cluster in seconds; "7b" is the v5e-64 flagship.
"""

from __future__ import annotations


def train_loop_per_worker(config: dict):
    import jax
    import jax.numpy as jnp
    import optax

    from ... import train as rt_train
    from ...models.llama import LlamaConfig, init_params, next_token_loss
    from ...parallel.mesh import make_mesh
    from ...parallel.sharding import param_shardings, unbox_params
    from ...train.lora import merge_lora, split_lora

    from ...parallel.sharding import process_local_batch

    ctx = rt_train.get_context()
    n_dev = len(jax.devices())

    if config.get("model") == "7b":
        cfg = LlamaConfig(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, intermediate=11008,
            max_seq_len=config.get("seq", 2048),
            param_dtype=jnp.bfloat16, remat=True, scan_layers=True,
            lora_rank=config.get("lora_rank", 16),
        )
    else:
        cfg = LlamaConfig.tiny(
            max_seq_len=config.get("seq", 128),
            lora_rank=config.get("lora_rank", 4),
            scan_layers=True, remat=True,
        )

    # mesh over every device jax.distributed exposes to this SPMD program;
    # fsdp by default (ZeRO-style param sharding), tp if requested
    axes = {"fsdp": config.get("fsdp", n_dev), "tp": config.get("tp", 1)}
    mesh = make_mesh(num_devices=n_dev, **axes)
    # activations shard batch over the data axes (dcn x dp x fsdp): the
    # per-worker batch must be a multiple of that product
    shape = dict(mesh.shape)
    data_shards = (
        shape.get("dcn", 1) * shape.get("dp", 1) * shape.get("fsdp", 1)
    )

    boxed = init_params(cfg, jax.random.PRNGKey(0))
    shardings = param_shardings(mesh, boxed)
    params = jax.jit(lambda p: p, out_shardings=shardings)(
        unbox_params(boxed)
    )
    base, lora = split_lora(params)
    del params
    optimizer = optax.adamw(config.get("lr", 1e-4))
    opt_state = jax.jit(optimizer.init)(lora)

    def loss_fn(lora_p, base_p, tokens):
        return next_token_loss(cfg, mesh, merge_lora(base_p, lora_p), tokens)

    @jax.jit
    def train_step(base_p, lp, s, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(lp, base_p, tokens)
        updates, s2 = optimizer.update(grads, s, lp)
        return optax.apply_updates(lp, updates), s2, loss

    # per-PROCESS batch: the global batch (batch * process_count) must be a
    # multiple of the mesh's data extent, so each process's share rounds to
    # a multiple of its local slice of that extent
    local_shards = max(data_shards // jax.process_count(), 1)
    batch = config.get("batch_per_worker", 2)
    batch = max(batch, local_shards)
    batch -= batch % local_shards
    seq = cfg.max_seq_len
    # one checkpoint dir per run, epochs overwrite (no per-epoch /tmp leak)
    ckpt_dir = None
    steps = config.get("steps_per_epoch", 4)
    rank = ctx.get_world_rank()
    loss = None
    for epoch in range(config.get("epochs", 2)):
        for step in range(steps):
            # each process contributes ITS shard of the global batch —
            # process_local_batch assembles the global sharded jax.Array
            # (feeding a rank-local array into a jit over a multi-host mesh
            # is an error). Seeded by WORLD RANK: under jax.distributed
            # rank == process_index, and in the non-distributed multi-worker
            # mode (independent single-process JAX per worker) every
            # process_index is 0 while ranks still differ.
            local = jax.random.randint(
                jax.random.PRNGKey(epoch * 10_000 + step * 100 + rank),
                (batch, seq), 0, cfg.vocab_size,
            )
            tokens = process_local_batch(mesh, local)
            lora, opt_state, loss = train_step(base, lora, opt_state, tokens)
        checkpoint = None
        if rank == 0:
            # LoRA-only checkpoint: adapters are the entire trainable state.
            # Real runs point RunConfig at shared storage; this example
            # keeps one reused node-local directory for the whole run.
            import os
            import pickle
            import tempfile

            from ...train.checkpoint import Checkpoint

            if ckpt_dir is None:
                ckpt_dir = tempfile.mkdtemp(prefix="lora_ckpt_")
            with open(os.path.join(ckpt_dir, "lora.pkl"), "wb") as f:
                pickle.dump(
                    {"lora": jax.device_get(lora), "epoch": epoch}, f
                )
            checkpoint = Checkpoint.from_directory(ckpt_dir)
        rt_train.report(
            {"epoch": epoch, "loss": float(loss), "rank": rank},
            checkpoint=checkpoint,
        )


def make_trainer(
    num_workers: int = 1,
    use_tpu: bool = False,
    topology: str = "",
    train_config: dict | None = None,
):
    """Build the JaxTrainer for this example (reference shape:
    JaxTrainer(train_loop, scaling_config=ScalingConfig(use_tpu=True,
    topology="v5e-64")))."""
    from ... import train as rt_train

    return rt_train.JaxTrainer(
        train_loop_per_worker,
        train_loop_config=dict(train_config or {}),
        scaling_config=rt_train.ScalingConfig(
            num_workers=num_workers, use_tpu=use_tpu,
            topology=topology or None,
        ),
        run_config=rt_train.RunConfig(name="llama-lora"),
    )


if __name__ == "__main__":
    import argparse

    import ray_tpu

    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="tiny", choices=["tiny", "7b"])
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--topology", default="", help='e.g. "v5e-64"')
    parser.add_argument("--epochs", type=int, default=2)
    args = parser.parse_args()

    ray_tpu.init(ignore_reinit_error=True)
    result = make_trainer(
        num_workers=args.num_workers,
        use_tpu=bool(args.topology),
        topology=args.topology,
        train_config={"model": args.model, "epochs": args.epochs},
    ).fit()
    if result.error is not None:
        raise SystemExit(f"training failed: {result.error}")
    print({"final": result.metrics})
