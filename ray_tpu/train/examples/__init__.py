"""Runnable Train examples for the BASELINE.json reference configs."""
