"""ResNet via DataParallelTrainer — BASELINE.json configs[1].

Reference config: "ResNet-50 ImageNet via DataParallelTrainer (XLA
collective backend)". Each ranked worker runs the same jitted SGD step on
its shard of the batch; with the batch axis sharded over the mesh's data
axes XLA inserts the gradient all-reduce (the role NCCL-DDP plays in the
reference) and the plain-jnp BatchNorm reductions become sync-BN.

``train_config`` keys: model ("tiny" | "50"), image_size, epochs,
steps_per_epoch, batch_per_worker, lr. Data is synthetic (the data plane
is exercised by ray_tpu.data tests; this example isolates the trainer).
"""

from __future__ import annotations


def train_loop_per_worker(config: dict):
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ... import train as rt_train
    from ...models.resnet import (
        ResNetConfig,
        apply_train,
        cross_entropy,
        init_train_state,
    )
    from ...parallel.mesh import make_mesh
    from ...parallel.sharding import process_local_batch

    ctx = rt_train.get_context()
    rank = ctx.get_world_rank()
    if config.get("model") == "50":
        cfg = ResNetConfig.resnet50()
        image_size = config.get("image_size", 224)
    else:
        cfg = ResNetConfig.tiny()
        image_size = config.get("image_size", 32)

    # data-parallel mesh over every device jax.distributed exposes: params
    # replicate, the batch axis shards over dp — XLA inserts the gradient
    # all-reduce (NCCL-DDP's role in the reference) and the BN batch-mean
    # reductions become sync-BN
    n_dev = len(jax.devices())
    mesh = make_mesh(num_devices=n_dev, dp=n_dev)
    replicated = NamedSharding(mesh, P())

    params, batch_stats = init_train_state(
        cfg, jax.random.PRNGKey(0), image_size=image_size
    )
    params = jax.device_put(params, replicated)
    batch_stats = jax.device_put(batch_stats, replicated)
    optimizer = optax.sgd(config.get("lr", 0.1), momentum=0.9)
    opt_state = jax.device_put(optimizer.init(params), replicated)

    def loss_fn(p, stats, images, labels):
        logits, new_stats = apply_train(cfg, p, stats, images)
        return cross_entropy(logits, labels), new_stats

    @jax.jit
    def train_step(p, stats, s, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            p, stats, images, labels
        )
        updates, s2 = optimizer.update(grads, s, p)
        return optax.apply_updates(p, updates), new_stats, s2, loss

    # per-process batch, rounded so the global batch divides over dp
    local_shards = max(n_dev // jax.process_count(), 1)
    batch = config.get("batch_per_worker", 8)
    batch = max(batch, local_shards)
    batch -= batch % local_shards
    steps = config.get("steps_per_epoch", 4)
    loss = None
    for epoch in range(config.get("epochs", 2)):
        for step in range(steps):
            # world rank, not process_index: in the non-distributed
            # multi-worker mode every process_index is 0 (see llama_lora)
            key = jax.random.PRNGKey(epoch * 10_000 + step * 100 + rank)
            images = process_local_batch(
                mesh,
                jax.random.normal(
                    key, (batch, image_size, image_size, 3), jnp.float32
                ),
            )
            labels = process_local_batch(
                mesh,
                jax.random.randint(
                    jax.random.fold_in(key, 1), (batch,), 0, cfg.num_classes
                ),
            )
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, images, labels
            )
        rt_train.report({"epoch": epoch, "loss": float(loss), "rank": rank})


def make_trainer(
    num_workers: int = 1,
    use_tpu: bool = False,
    topology: str = "",
    train_config: dict | None = None,
):
    from ... import train as rt_train

    return rt_train.DataParallelTrainer(
        train_loop_per_worker,
        train_loop_config=dict(train_config or {}),
        scaling_config=rt_train.ScalingConfig(
            num_workers=num_workers, use_tpu=use_tpu,
            topology=topology or None,
        ),
        run_config=rt_train.RunConfig(name="resnet"),
        backend_config=rt_train.JaxConfig(use_tpu=use_tpu),
    )


if __name__ == "__main__":
    import argparse

    import ray_tpu

    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="tiny", choices=["tiny", "50"])
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--epochs", type=int, default=2)
    args = parser.parse_args()

    ray_tpu.init(ignore_reinit_error=True)
    result = make_trainer(
        num_workers=args.num_workers,
        train_config={"model": args.model, "epochs": args.epochs},
    ).fit()
    if result.error is not None:
        raise SystemExit(f"training failed: {result.error}")
    print({"final": result.metrics})
