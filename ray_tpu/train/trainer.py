"""Trainers: the user-facing fit() entry points.

Role-equivalent of the reference's DataParallelTrainer
(train/v2/api/data_parallel_trainer.py:152) and JaxTrainer
(train/v2/jax/jax_trainer.py:19): wrap a per-worker train loop, gang-launch
it through the TrainController, and return a Result.

TPU-first: JaxTrainer is the flagship — with ``ScalingConfig(use_tpu=True,
topology="v5e-16")`` it reserves a slice via TPUReservationCallback, runs
one ranked worker per host, bootstraps jax.distributed so the slice is a
single SPMD program, and the user loop uses pjit/GSPMD shardings (see
ray_tpu.parallel) with in-jit collectives over ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .backend import BackendConfig, JaxConfig, TorchConfig
from .callbacks import TPUReservationCallback
from .config import RunConfig, ScalingConfig
from .controller import Result, TrainController


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend_config: Optional[BackendConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        quantized: bool = False,
        overlap: bool = False,
        bucket_bytes: Optional[int] = None,
        stale_grad: int = 0,
        slice_size: Optional[int] = None,
    ):
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config or BackendConfig()
        self.datasets = datasets
        # quantized transport plane: int8+error-feedback collectives for
        # the run's gang and the int8 chunk codec for train-state publishes
        # (halves bf16 gradient/weight bytes on the wire; loss parity is
        # maintained by error feedback — see docs/ARCHITECTURE.md §16)
        self.quantized = quantized
        # overlapped gradient reduction: the worker loop's
        # train.collective.reduce_gradients() bucketizes the grad tree and
        # dispatches async allreduces under the step's remaining compute
        # (docs/ARCHITECTURE.md §17). stale_grad=1 additionally defers the
        # update one step so the tail reduce hides under the next forward.
        # slice_size switches the gang to the hierarchical ("hier")
        # backend: intra-slice reduce + inter-slice leader reduce.
        self.overlap = overlap
        self.bucket_bytes = bucket_bytes
        self.stale_grad = stale_grad
        self.slice_size = slice_size

    def _default_callbacks(self):
        return []

    def fit(self) -> Result:
        # combined list built per-fit; the user's RunConfig is not mutated,
        # so repeated fit() calls don't stack default callbacks
        callbacks = self._default_callbacks() + list(self.run_config.callbacks)
        controller = TrainController(
            self._train_loop,
            self._train_loop_config,
            self.scaling_config,
            self.run_config,
            self.backend_config,
            datasets=self.datasets,
            callbacks=callbacks,
            quantized=self.quantized,
            overlap=self.overlap,
            bucket_bytes=self.bucket_bytes,
            stale_grad=self.stale_grad,
            slice_size=self.slice_size,
        )
        return controller.run()


class JaxTrainer(DataParallelTrainer):
    """JAX/TPU trainer (reference: v2/jax/jax_trainer.py:19)."""

    def __init__(self, train_loop_per_worker, **kwargs):
        scaling = kwargs.get("scaling_config") or ScalingConfig()
        kwargs.setdefault(
            "backend_config", JaxConfig(use_tpu=scaling.use_tpu)
        )
        super().__init__(train_loop_per_worker, **kwargs)

    def _default_callbacks(self):
        if self.scaling_config.use_tpu and self.scaling_config.topology:
            return [TPUReservationCallback()]
        return []


class TorchTrainer(DataParallelTrainer):
    """CPU/GPU torch trainer for reference parity
    (train/torch/torch_trainer.py)."""

    def __init__(self, train_loop_per_worker, **kwargs):
        kwargs.setdefault("backend_config", TorchConfig())
        super().__init__(train_loop_per_worker, **kwargs)
