"""Scaling policies: decide the worker-group size per attempt.

Role-equivalent of the reference's ScalingPolicy
(train/v2/_internal/execution/scaling_policy/scaling_policy.py:29 —
FixedScalingPolicy and the elastic ScalingDecision path): the controller
asks the policy for a ScalingDecision before every worker-group (re)start.
Elastic training resizes at restart boundaries — JAX SPMD gangs are
all-or-nothing, so mid-run resizes require a gang restart anyway, and every
restart resumes from the latest checkpoint with a freshly compiled program
for the new mesh size (the reference's elastic semantics, adapted to XLA's
static-world compilation model).
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass
from typing import Optional

from .config import ScalingConfig

logger = logging.getLogger(__name__)


@dataclass
class ScalingDecision:
    num_workers: int


class ScalingPolicy:
    """ABC: ``decide`` is called before each worker-group start attempt."""

    def __init__(self, scaling_config: ScalingConfig):
        self.scaling_config = scaling_config

    def decide(self, attempt: int) -> ScalingDecision:
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured world size (reference: FixedScalingPolicy)."""

    def decide(self, attempt: int) -> ScalingDecision:
        return ScalingDecision(num_workers=self.scaling_config.num_workers)


class ElasticScalingPolicy(ScalingPolicy):
    """Size the gang to what the cluster can actually schedule, clamped to
    [min_workers, max_workers]. On the first attempt it waits up to
    ``grace_s`` for the full max size before settling for less; restarts
    re-measure, so a recovered node grows the gang back."""

    def __init__(
        self,
        scaling_config: ScalingConfig,
        min_workers: int,
        max_workers: int,
        grace_s: float = 10.0,
    ):
        super().__init__(scaling_config)
        if not 1 <= min_workers <= max_workers:
            raise ValueError("need 1 <= min_workers <= max_workers")
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.grace_s = grace_s

    def _fit_to_cluster(self) -> int:
        from .. import api

        per_worker = self.scaling_config._resources_per_worker_not_none
        try:
            avail = api.available_resources()
        except Exception:
            return self.min_workers
        fits = math.inf
        for k, v in per_worker.items():
            if v > 0:
                fits = min(fits, avail.get(k, 0.0) // v)
        if not math.isfinite(fits):
            fits = self.max_workers
        return int(fits)

    def decide(self, attempt: int) -> ScalingDecision:
        import time

        # full grace only on the initial start; restarts keep a short window
        # so resources of the just-failed workers can be reclaimed (zero
        # would snapshot availability mid-teardown and shrink a healthy gang)
        grace = self.grace_s if attempt == 0 else min(self.grace_s, 3.0)
        deadline = time.time() + grace
        n = self._fit_to_cluster()
        while n < self.max_workers and time.time() < deadline:
            time.sleep(0.5)
            n = max(n, self._fit_to_cluster())
        n = max(min(n, self.max_workers), self.min_workers)
        if n < self.max_workers:
            logger.warning(
                "elastic scaling: running with %d/%d workers (attempt %d)",
                n, self.max_workers, attempt,
            )
        return ScalingDecision(num_workers=n)


def make_scaling_policy(scaling_config: ScalingConfig) -> ScalingPolicy:
    """num_workers given as (min, max) selects elastic; an int stays fixed
    (reference: elastic num_workers tuple in Train's elastic API)."""
    nw = scaling_config.num_workers
    if isinstance(nw, tuple):
        from dataclasses import replace

        lo, hi = nw
        return ElasticScalingPolicy(
            replace(scaling_config, num_workers=hi), lo, hi
        )
    return FixedScalingPolicy(scaling_config)
