"""Ranked worker group gang-scheduled onto the cluster.

Role-equivalent of the reference's Train v2 WorkerGroup
(train/v2/_internal/execution/worker_group/worker_group.py:104): N actor
workers placed by one placement group, assigned ranks sorted by node
(worker_group.py:728-813 rank sorting), each running the user train fn on a
background thread (worker_group/thread_runner.py) while the controller polls
statuses.

TPU-first: with a slice reservation the PG bundles carry the slice's label
selector so every ranked worker lands on one ICI domain, one worker per
host.
"""

from __future__ import annotations

import logging
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .. import api as ray_api
from ..util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from ..util.scheduling_strategies import PlacementGroupSchedulingStrategy
from .config import ScalingConfig
from .session import TrainContext, set_context

logger = logging.getLogger(__name__)


class TrainWorker:
    """Actor hosting one ranked training process."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[str] = None
        self._error_exc: Optional[Exception] = None
        self._done = False
        self._ctx: Optional[TrainContext] = None
        # bumped on reset_for_restart: a zombie train thread from a previous
        # generation (join timed out mid-abort) must not write done/error
        # state into the restarted run
        self._gen = 0

    def get_metadata(self) -> dict:
        import os
        import socket

        from ..runtime_context import get_runtime_context

        rc = get_runtime_context()
        return {
            "node_id": rc.get_node_id(),
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "tpu_chips": _visible_tpu_chips(),
        }

    def init_context(self, ctx_fields: dict):
        self._ctx = TrainContext(**ctx_fields)
        set_context(self._ctx)
        if self._ctx.collective_group:
            from .. import collective

            kwargs = dict(
                group_name=self._ctx.collective_group,
                epoch=self._ctx.collective_epoch,
                quantized=self._ctx.collective_quantized,
            )
            slice_size = self._ctx.collective_slice_size
            if slice_size and self._ctx.world_size % slice_size == 0:
                # two-tier topology: intra-slice + inter-slice leader reduce
                backend = "hier"
                kwargs["slice_size"] = slice_size
            else:
                # flat group; also the fallback when an elastic resize
                # leaves a world size the slice shape no longer divides
                backend = "gcs"
            collective.init_collective_group(
                self._ctx.world_size,
                self._ctx.world_rank,
                backend=backend,
                **kwargs,
            )
        return True

    def set_dataset_shard(self, name: str, shard):
        self._ctx.dataset_shards[name] = shard
        return True

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in this worker (backend setup etc.)."""
        return fn(*args, **kwargs)

    def start_training(self, train_fn: Callable, config: Optional[dict]):
        """Launch the user loop on a thread so poll() stays responsive
        (reference: thread_runner.py)."""
        if self._thread is not None:
            raise RuntimeError("training already started")
        gen = self._gen

        def _run():
            try:
                import inspect

                sig = inspect.signature(train_fn)
                if len(sig.parameters) >= 1:
                    train_fn(config if config is not None else {})
                else:
                    train_fn()
            except BaseException as e:  # noqa: BLE001
                if self._gen == gen:
                    self._error = traceback.format_exc()
                    self._error_exc = (
                        e if isinstance(e, Exception) else RuntimeError(str(e))
                    )
                    logger.error("train fn failed:\n%s", self._error)
            finally:
                if self._gen == gen:
                    self._done = True

        self._thread = threading.Thread(target=_run, daemon=True, name="train_fn")
        self._thread.start()
        return True

    def poll(self) -> dict:
        # read done/error BEFORE draining: if the train thread finishes
        # between a drain and the done check, its final report would be
        # dropped — capturing done first means a done=True answer can only
        # accompany a complete drain
        done = self._done
        error = self._error
        error_exc = self._error_exc
        reports = self._ctx.drain_reports() if self._ctx else []
        return {
            "reports": reports,
            "done": done,
            "error": error,
            "error_exc": error_exc,
        }

    def reset_for_restart(self, join_timeout: float = 30.0) -> dict:
        """Prepare this surviving worker for an elastic re-form: wait for
        the (aborted) train thread to exit, tear down the poisoned
        collective group, and clear run state — WITHOUT killing the actor
        process. The controller then re-ranks, re-inits contexts at the
        next epoch, and restarts training."""
        self._gen += 1
        thread_exited = True
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            thread_exited = not self._thread.is_alive()
        if self._ctx and self._ctx.collective_group:
            from .. import collective

            try:
                collective.destroy_collective_group(self._ctx.collective_group)
            except Exception:
                pass
        self._thread = None
        self._error = None
        self._error_exc = None
        self._done = False
        return {"thread_exited": thread_exited}

    def shutdown(self):
        if self._ctx and self._ctx.collective_group:
            from .. import collective

            try:
                collective.destroy_collective_group(self._ctx.collective_group)
            except Exception:
                pass
        set_context(None)
        return True


def _visible_tpu_chips() -> int:
    import glob

    return len(glob.glob("/dev/accel*"))


@dataclass
class WorkerInfo:
    actor: Any
    world_rank: int
    local_rank: int
    node_rank: int
    node_id: str
    metadata: dict = field(default_factory=dict)


class WorkerGroup:
    """Create, rank, command, and tear down the gang of train workers."""

    def __init__(
        self,
        scaling_config: ScalingConfig,
        *,
        placement_group_override: Optional[PlacementGroup] = None,
        bundle_label_selector: Optional[Dict[str, str]] = None,
    ):
        self._scaling = scaling_config
        self._pg: Optional[PlacementGroup] = placement_group_override
        self._owns_pg = placement_group_override is None
        self._label_selector = bundle_label_selector
        self.workers: List[WorkerInfo] = []

    def create(self, pg_timeout: float = 60.0):
        n = self._scaling.num_workers
        res = self._scaling._resources_per_worker_not_none
        if self._pg is None:
            selectors = (
                [dict(self._label_selector) for _ in range(n)]
                if self._label_selector
                else None
            )
            self._pg = placement_group(
                [dict(res) for _ in range(n)],
                strategy=self._scaling.placement_strategy,
                bundle_label_selector=selectors,
            )
        if not self._pg.ready(timeout=pg_timeout):
            raise TimeoutError(
                f"placement group for {n} train workers "
                f"({res} each, {self._scaling.placement_strategy}) not ready "
                f"in {pg_timeout}s — cluster lacks resources"
            )
        worker_cls = ray_api.remote(TrainWorker)
        actors = []
        for i in range(n):
            actors.append(
                worker_cls.options(
                    num_cpus=res.get("CPU", 0),
                    resources={k: v for k, v in res.items() if k != "CPU"},
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        self._pg, placement_group_bundle_index=i
                    ),
                ).remote()
            )
        metas = ray_api.get([a.get_metadata.remote() for a in actors])
        self.workers = self._assign_ranks(list(zip(actors, metas)))
        return self

    @staticmethod
    def _assign_ranks(pairs: List[tuple]) -> List[WorkerInfo]:
        """Rank assignment: group by node, sort nodes by id for determinism,
        rank 0 first (reference: worker_group rank sorting :728-813).
        ``pairs`` is (actor, metadata) in a stable pre-order."""
        n = len(pairs)
        order = sorted(range(n), key=lambda i: (pairs[i][1]["node_id"], i))
        node_ids: List[str] = []
        workers: List[WorkerInfo] = []
        local_counts: Dict[str, int] = {}
        for world_rank, idx in enumerate(order):
            actor, meta = pairs[idx]
            node_id = meta["node_id"]
            if node_id not in node_ids:
                node_ids.append(node_id)
            local_rank = local_counts.get(node_id, 0)
            local_counts[node_id] = local_rank + 1
            workers.append(
                WorkerInfo(
                    actor=actor,
                    world_rank=world_rank,
                    local_rank=local_rank,
                    node_rank=node_ids.index(node_id),
                    node_id=node_id,
                    metadata=meta,
                )
            )
        return workers

    @property
    def placement_group(self) -> Optional[PlacementGroup]:
        return self._pg

    def init_contexts(self, run_fields: dict):
        local_sizes: Dict[str, int] = {}
        for w in self.workers:
            local_sizes[w.node_id] = local_sizes.get(w.node_id, 0) + 1
        refs = []
        for w in self.workers:
            fields = dict(
                world_rank=w.world_rank,
                local_rank=w.local_rank,
                node_rank=w.node_rank,
                world_size=len(self.workers),
                local_world_size=local_sizes[w.node_id],
                **run_fields,
            )
            refs.append(w.actor.init_context.remote(fields))
        ray_api.get(refs)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker, return results ordered by world rank."""
        return ray_api.get(
            [w.actor.execute.remote(fn, *args, **kwargs) for w in self.workers]
        )

    def execute_single(self, world_rank: int, fn: Callable, *args, **kwargs):
        return ray_api.get(
            self.workers[world_rank].actor.execute.remote(fn, *args, **kwargs)
        )

    def start_training(self, train_fn: Callable, config: Optional[dict]):
        ray_api.get(
            [w.actor.start_training.remote(train_fn, config) for w in self.workers]
        )

    def poll(self) -> List[dict]:
        return ray_api.get([w.actor.poll.remote() for w in self.workers])

    def poll_each(self, timeout: float = 30.0) -> List[Any]:
        """Per-worker poll: each entry is the status dict OR the exception
        that poll raised (a dead actor yields ActorDiedError instead of
        failing the whole batch — the elastic controller needs to know
        exactly which ranks died)."""
        refs = [w.actor.poll.remote() for w in self.workers]
        out: List[Any] = []
        for ref in refs:
            try:
                out.append(ray_api.get(ref, timeout=timeout))
            except Exception as e:  # noqa: BLE001
                out.append(e)
        return out

    def ping(self, timeout: float = 10.0) -> List[bool]:
        """Liveness probe ordered like ``workers``: False = actor dead or
        unresponsive."""
        refs = [w.actor.get_metadata.remote() for w in self.workers]
        alive = []
        for ref in refs:
            try:
                ray_api.get(ref, timeout=timeout)
                alive.append(True)
            except Exception:
                alive.append(False)
        return alive

    def remove_workers(self, indices: List[int]) -> List[WorkerInfo]:
        """Drop the given (current-list) indices — killing their actors
        best-effort — and re-rank the survivors. Returns the removed
        WorkerInfos. The placement group is kept as-is: removing it would
        tear down the surviving placed actors, and the dead ranks' bundles
        stay reserved as grow-back capacity for a later full restart."""
        doomed = set(indices)
        removed = []
        survivors = []
        for i, w in enumerate(self.workers):
            (removed if i in doomed else survivors).append(w)
        for w in removed:
            try:
                ray_api.kill(w.actor)
            except Exception:
                pass
        # survivors keep their relative rank order (stable re-rank): pass
        # them in current world_rank order so rank gaps close without
        # reshuffling the remaining ranks
        self.workers = self._assign_ranks(
            [(w.actor, w.metadata) for w in survivors]
        )
        return removed

    def reset_for_restart(self, join_timeout: float = 30.0) -> List[dict]:
        """Elastic re-form step: every surviving worker joins its aborted
        train thread and clears run state (see TrainWorker.reset_for_restart)."""
        return ray_api.get(
            [
                w.actor.reset_for_restart.remote(join_timeout)
                for w in self.workers
            ],
            timeout=join_timeout + 30.0,
        )

    def shutdown(self):
        for w in self.workers:
            try:
                ray_api.get(w.actor.shutdown.remote(), timeout=5)
            except Exception:
                pass
        for w in self.workers:
            try:
                ray_api.kill(w.actor)
            except Exception:
                pass
        self.workers = []
        if self._pg is not None and self._owns_pg:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
        self._pg = None
