"""transformers (Hugging Face) integration.

Role-equivalent of the reference's ``ray.train.huggingface.transformers``
(prepare_trainer + RayTrainReportCallback): run a ``transformers.Trainer``
inside a ray_tpu Train worker loop, bridging its logging/checkpoint events
into ``ray_tpu.train.report`` so the controller sees metrics and the
CheckpointManager tracks HF checkpoints. Typical use:

    def train_loop(config):
        trainer = transformers.Trainer(model=..., args=..., ...)
        trainer = ray_tpu.train.huggingface.prepare_trainer(trainer)
        trainer.train()

    TorchTrainer(train_loop, scaling_config=ScalingConfig(num_workers=N))
"""

from __future__ import annotations

from typing import Optional

try:
    import transformers
    from transformers.trainer_callback import TrainerCallback
except ImportError as _e:  # pragma: no cover — transformers is in the image
    transformers = None

    class TrainerCallback:  # type: ignore[no-redef]
        pass


class RayTrainReportCallback(TrainerCallback):
    """Bridge transformers Trainer events to ray_tpu.train.report
    (reference: huggingface/transformers/_transformers_utils.py
    RayTrainReportCallback — report on log, attach checkpoint on save)."""

    def on_log(self, args, state, control, logs=None, **kwargs):
        from . import session

        if not session.in_session() or not logs:
            return
        metrics = {
            k: v for k, v in logs.items() if isinstance(v, (int, float))
        }
        metrics["step"] = state.global_step
        metrics["epoch"] = float(state.epoch or 0.0)
        session.report(metrics)

    def on_save(self, args, state, control, **kwargs):
        from . import session
        from .checkpoint import Checkpoint

        if not session.in_session():
            return
        ckpt_dir = transformers.trainer_utils.get_last_checkpoint(
            args.output_dir
        )
        if ckpt_dir:
            session.report(
                {"step": state.global_step, "checkpoint_saved": True},
                checkpoint=Checkpoint.from_directory(ckpt_dir),
            )


def prepare_trainer(trainer):
    """Attach the report bridge exactly once (reference: prepare_trainer).
    Returns the same Trainer for chaining."""
    if transformers is None:
        raise ImportError(
            "transformers is not installed; TorchTrainer/JaxTrainer work "
            "without it — prepare_trainer only wraps transformers.Trainer"
        )
    if not any(
        isinstance(cb, RayTrainReportCallback)
        for cb in trainer.callback_handler.callbacks
    ):
        trainer.add_callback(RayTrainReportCallback())
    return trainer
