"""Train configuration dataclasses.

Role-equivalent of the reference's Train v2 configs
(python/ray/train/v2/api/config.py:30,70 — ScalingConfig with
use_tpu/topology/accelerator_type; RunConfig with storage/checkpoint/failure
config) re-shaped for TPU-first scheduling: a worker is one *host* of a
slice, chips per host follow the pod type, and gang placement is a
STRICT_SPREAD placement group pinned to one ICI domain.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .._internal.accelerators import chips_per_host, pod_type_num_hosts


@dataclass
class ScalingConfig:
    """How many training workers and what each one holds.

    With ``use_tpu=True`` and a ``topology`` (pod type, e.g. "v5e-16"),
    ``num_workers`` defaults to the slice's host count and every worker gets
    the host's full chip allotment — JAX SPMD requires exactly one process
    per host, all running the same program (reference: ScalingConfig
    v2/api/config.py:70, tpu.py topology tables).
    """

    num_workers: Optional[int] = None
    use_tpu: bool = False
    topology: Optional[str] = None
    accelerator_type: Optional[str] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"

    def __post_init__(self):
        if self.topology is not None and not self.use_tpu:
            raise ValueError("topology requires use_tpu=True")
        if self.num_workers is None:
            self.num_workers = (
                pod_type_num_hosts(self.topology) if self.topology else 1
            )
        # (min, max) selects elastic scaling (scaling_policy.py); size checks
        # below apply to the fixed case only
        if isinstance(self.num_workers, tuple):
            return
        if self.use_tpu and self.topology and self.num_workers > 1:
            # one ranked worker per slice host, spread across hosts
            self.placement_strategy = "STRICT_SPREAD"

    @property
    def _resources_per_worker_not_none(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        if self.use_tpu:
            n = chips_per_host(self.topology) if self.topology else 1
            return {"CPU": 1.0, "TPU": float(n)}
        return {"CPU": 1.0}

    @property
    def total_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for k, v in self._resources_per_worker_not_none.items():
            out[k] = v * (self.num_workers or 1)
        return out


@dataclass
class CheckpointConfig:
    """Top-K checkpoint retention (reference:
    train/v2/_internal/execution/checkpoint/checkpoint_manager.py)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive")


@dataclass
class FailureConfig:
    """Worker-group-level failure policy (reference:
    v2/_internal/execution/failure_handling/failure_policy.py).

    Two recovery moves, tried in order:

    - **Elastic resize** (``elastic=True``): on a worker/node death the
      controller keeps the surviving ``TrainWorker`` actors, aborts the
      in-flight collectives (survivors raise ``CollectiveAbortedError``
      within ~1 s), drops the dead ranks, re-ranks, bumps the group epoch,
      and resumes training at the surviving world size — as long as at
      least ``min_workers`` survive. Workers re-resolve params/step from
      the weight plane (``restore_train_state``), so a resize needs no
      filesystem checkpoint restore. Resizes do NOT count against
      ``max_failures``: they are the steady-state recovery move on
      preemptible fleets, not a retry.

    - **Gang restart** (always available): tear down the whole group and
      respawn it full-size from the latest checkpoint. Used when
      ``elastic=False`` (the default — today's all-or-nothing behavior),
      or when survivors fall below ``min_workers``, or when a worker fails
      with a real user-code error. Each gang restart consumes one unit of
      ``max_failures``; ``max_failures=-1`` retries forever, ``0`` (the
      default) fails the run on the first gang-level failure.
    """

    max_failures: int = 0
    elastic: bool = False
    min_workers: int = 1

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError("min_workers must be >= 1")


def _default_storage_path() -> str:
    return os.environ.get(
        "RAY_TPU_STORAGE_PATH",
        os.path.join(os.path.expanduser("~"), "ray_tpu_results"),
    )


@dataclass
class RunConfig:
    """Where results/checkpoints go and how failures are handled
    (reference: v2/api/config.py RunConfig)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    callbacks: List[Any] = field(default_factory=list)

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = _default_storage_path()
        if self.name is None:
            import time
            import uuid

            self.name = f"train_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:6]}"

    @property
    def run_dir(self) -> str:
        return os.path.join(self.storage_path, self.name)
