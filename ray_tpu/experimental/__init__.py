"""ray_tpu.experimental: device objects (RDT) and other previews."""

from .device_objects import (
    DeviceObjectRef,
    device_get,
    device_put_object,
    free_device_object,
)

__all__ = [
    "DeviceObjectRef",
    "device_put_object",
    "device_get",
    "free_device_object",
]
