"""Device objects: tensors stay on the producing worker's device.

Role-equivalent of the reference's RDT / GPU objects
(python/ray/experimental/gpu_object_manager/gpu_object_manager.py:85 and
``@ray.method(tensor_transport="nccl")``): an actor method tagged with
``tensor_transport="device"`` keeps its returned jax arrays resident in the
producing process's device object store; what travels through the normal
object path is a small ``DeviceObjectRef`` descriptor. A consumer actor
tagged the same way gets refs in its arguments resolved automatically —
a local hit is zero-copy (the very pytree, still on device HBM); a remote
fetch goes worker->worker over the RPC plane (host RAM), bypassing the
raylet object store entirely.

TPU note: true chip-to-chip movement on TPU rides ICI *inside* jit
programs (jax collectives — see ray_tpu.parallel); the reference's
NCCL-p2p-between-actors pattern maps to host-path transfer here because
separate processes own separate chips through separate XLA clients.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, Optional, Tuple

from .. import _worker_api

_lock = threading.Lock()
_store: Dict[str, Any] = {}


class DeviceObjectRef:
    """Descriptor for a device-resident pytree. Serializable; the payload
    stays with the owner worker."""

    __slots__ = ("id", "owner_address", "spec")

    def __init__(self, id: str, owner_address: Tuple[str, int], spec: str):
        self.id = id
        self.owner_address = owner_address
        self.spec = spec  # human-readable shape/dtype summary

    def __repr__(self):
        return (
            f"DeviceObjectRef({self.id[:8]}, owner={self.owner_address}, "
            f"{self.spec})"
        )

    def __reduce__(self):
        return (DeviceObjectRef, (self.id, self.owner_address, self.spec))


def _summarize(value: Any) -> str:
    import jax

    leaves = jax.tree.leaves(value)
    arrs = [x for x in leaves if hasattr(x, "shape")]
    n = sum(getattr(x, "size", 0) for x in arrs)
    return f"{len(arrs)} arrays, {n} elements"


def device_put_object(value: Any) -> DeviceObjectRef:
    """Store a pytree of (jax) arrays in this worker's device object store
    and return a descriptor (reference: GPUObjectStore.put)."""
    worker = _worker_api.get_core_worker()
    obj_id = uuid.uuid4().hex
    with _lock:
        _store[obj_id] = value
    return DeviceObjectRef(obj_id, worker.address, _summarize(value))


def device_get(ref: DeviceObjectRef, *, to_device: bool = True) -> Any:
    """Resolve a DeviceObjectRef. Local hit: the stored pytree itself (zero
    copy, still on device). Remote: fetch numpy leaves from the owner over
    RPC; ``to_device`` re-materializes them as jax arrays."""
    worker = _worker_api.get_core_worker()
    with _lock:
        if ref.id in _store:
            return _store[ref.id]
    if tuple(ref.owner_address) == tuple(worker.address):
        raise KeyError(f"device object {ref.id} was freed on its owner")
    payload = _worker_api.run_on_worker_loop(
        worker.client_pool.get(*ref.owner_address).call(
            "fetch_device_object", ref.id
        )
    )
    if payload is None:
        raise KeyError(f"device object {ref.id} not found on owner")
    if to_device:
        import jax
        import jax.numpy as jnp

        payload = jax.tree.map(
            lambda x: jnp.asarray(x) if hasattr(x, "shape") else x, payload
        )
    return payload


def free_device_object(ref: DeviceObjectRef) -> bool:
    """Drop the owner's copy (reference: GPU object freeing on ref removal;
    explicit here — descriptors are plain values with no distributed
    refcount)."""
    worker = _worker_api.get_core_worker()
    with _lock:
        if ref.id in _store:
            del _store[ref.id]
            return True
    if tuple(ref.owner_address) == tuple(worker.address):
        return False  # we are the owner and it is already gone
    try:
        return _worker_api.run_on_worker_loop(
            worker.client_pool.get(*ref.owner_address).call(
                "free_device_object", ref.id
            )
        )
    except Exception:
        return False


# -- owner-side RPC handlers (registered by CoreWorker) ---------------------


async def handle_fetch(obj_id: str):
    """Serialize the stored pytree's leaves to host numpy for the wire.
    The device->host copy runs on a thread: it can take seconds for large
    pytrees and the owner's event loop must keep servicing RPCs."""
    import asyncio

    with _lock:
        value = _store.get(obj_id)
    if value is None:
        return None
    import jax

    return await asyncio.get_running_loop().run_in_executor(
        None,
        lambda: jax.tree.map(
            lambda x: jax.device_get(x) if hasattr(x, "shape") else x, value
        ),
    )


async def handle_free(obj_id: str) -> bool:
    with _lock:
        return _store.pop(obj_id, None) is not None


# -- tensor_transport="device" method integration ---------------------------


def resolve_args(args, kwargs):
    """Replace DeviceObjectRef arguments — including refs nested inside
    lists/dicts/tuples — with their pytrees (reference: the implicit
    resolution GPUObjectManager does for tensor_transport methods)."""
    import jax

    def r(x):
        return device_get(x) if isinstance(x, DeviceObjectRef) else x

    resolve = lambda tree: jax.tree.map(  # noqa: E731
        r, tree, is_leaf=lambda x: isinstance(x, DeviceObjectRef)
    )
    return [resolve(a) for a in args], {
        k: resolve(v) for k, v in kwargs.items()
    }


def wrap_result(result: Any) -> Any:
    """Park a result containing jax arrays in the device store, returning
    the descriptor instead (None/scalars pass through)."""
    import jax

    leaves = jax.tree.leaves(result)
    if any(hasattr(x, "shape") and getattr(x, "ndim", 0) > 0 for x in leaves):
        return device_put_object(result)
    return result
