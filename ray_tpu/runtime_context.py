"""Runtime context introspection.

Role-equivalent of the reference's ray.runtime_context
(python/ray/runtime_context.py): lets driver and task/actor code ask "where
am I running" — node, worker, job, actor, placement group.
"""

from __future__ import annotations

from typing import Optional

from . import _worker_api


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    def get_node_id(self) -> str:
        nid = getattr(self._worker, "node_id", None)
        if nid is None:
            return ""
        # node_id may be a NodeID or the raylet address tuple
        if hasattr(nid, "hex"):
            return nid.hex()
        return str(nid)

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_job_id(self) -> str:
        return self._worker.job_id.hex()

    def get_actor_id(self) -> Optional[str]:
        spec = getattr(self._worker, "_actor_spec", None)
        if spec is None or spec.actor_id is None:
            return None
        return spec.actor_id.hex()

    def get_task_id(self) -> Optional[str]:
        tid = getattr(self._worker, "_current_task_id", None)
        return tid.hex() if tid is not None else None

    @property
    def was_current_actor_reconstructed(self) -> bool:
        spec = getattr(self._worker, "_actor_spec", None)
        return bool(spec is not None and getattr(spec, "attempt", 0) > 0)

    def get(self) -> dict:
        return {
            "node_id": self.get_node_id(),
            "worker_id": self.get_worker_id(),
            "job_id": self.get_job_id(),
            "actor_id": self.get_actor_id(),
            "task_id": self.get_task_id(),
        }


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_worker_api.get_core_worker())
