"""Cross-language call surface.

Role-equivalent of the reference's ``ray.cross_language``
(cross_language.py:15-66 — java_function/cpp_function descriptors invoked
through msgpack serialization). Direction matters: this framework's
cross-language path is INBOUND — non-Python clients call named Python
functions through the client server's xlang endpoint with a C++ frontend
(`ray_tpu/_native/xlang_client.cc`, JSON args over a mini-pickle wire).
Outbound calls INTO C++/Java worker runtimes require those runtimes, which
are not part of this framework; the stubs below say so explicitly instead
of failing deep in submission.
"""

from __future__ import annotations

_HINT = (
    "; this framework's cross-language support is inbound (C++/other "
    "languages calling Python via the client server's xlang endpoint — "
    "see ray_tpu/_native/xlang_client.cc)"
)


def cpp_function(function_name: str):
    raise NotImplementedError(
        f"outbound calls into C++ workers are not supported"
        f" (requested {function_name!r})" + _HINT
    )


def java_function(class_name: str, function_name: str):
    raise NotImplementedError(
        f"outbound calls into Java workers are not supported"
        f" (requested {class_name}.{function_name})" + _HINT
    )


def java_actor_class(class_name: str):
    raise NotImplementedError(
        f"Java actor classes are not supported (requested {class_name!r})"
        + _HINT
    )
