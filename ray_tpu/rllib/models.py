"""RLModule: policy/value networks in flax.

Role-equivalent of the reference's RLModule (rllib/core/rl_module/ — torch
actor-critic modules). TPU-first: one flax module computes logits and value
in a single forward (fused matmuls on the MXU), parameters are a pytree
ready for pjit sharding, and action sampling/log-prob are pure jax
functions usable under jit on both the learner and the env runners.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class ActorCritic(nn.Module):
    action_dim: int
    discrete: bool
    hidden: Tuple[int, ...] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h)(x))
        logits = nn.Dense(self.action_dim)(x)
        v = nn.Dense(1)(x)
        if not self.discrete:
            log_std = self.param(
                "log_std", nn.initializers.zeros, (self.action_dim,)
            )
            return (logits, log_std), jnp.squeeze(v, -1)
        return logits, jnp.squeeze(v, -1)


MLP_HIDDEN: Tuple[int, ...] = (64, 64)


class QNetwork(nn.Module):
    """State-action value network for DQN (reference: dqn_rl_module)."""

    action_dim: int
    hidden: Tuple[int, ...] = MLP_HIDDEN

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.action_dim)(x)


class TwinQ(nn.Module):
    """Two independent Q(s, a) heads for clipped double-Q (reference: SAC's
    twin critics, rllib/algorithms/sac/sac_rl_module)."""

    hidden: Tuple[int, ...] = (256, 256)

    @nn.compact
    def __call__(self, obs, actions):
        x0 = jnp.concatenate([obs, actions], axis=-1)

        def q_head(x, name):
            for i, h in enumerate(self.hidden):
                x = nn.relu(nn.Dense(h, name=f"{name}_d{i}")(x))
            return jnp.squeeze(nn.Dense(1, name=f"{name}_out")(x), -1)

        return q_head(x0, "q1"), q_head(x0, "q2")


class SquashedGaussianActor(nn.Module):
    """tanh-squashed gaussian policy (reference: SAC action dist); outputs
    (mean, log_std) of the pre-squash gaussian."""

    action_dim: int
    hidden: Tuple[int, ...] = (256, 256)
    log_std_min: float = -20.0
    log_std_max: float = 2.0

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        mean = nn.Dense(self.action_dim)(x)
        log_std = nn.Dense(self.action_dim)(x)
        log_std = jnp.clip(log_std, self.log_std_min, self.log_std_max)
        return mean, log_std


def squashed_sample_logp(mean, log_std, key):
    """Sample a = tanh(u), u ~ N(mean, std), with the tanh-corrected
    log-prob (SAC eq. 21)."""
    std = jnp.exp(log_std)
    u = mean + std * jax.random.normal(key, mean.shape)
    a = jnp.tanh(u)
    logp = jnp.sum(
        -0.5 * ((u - mean) / std) ** 2 - log_std - 0.5 * jnp.log(2 * jnp.pi),
        axis=-1,
    )
    # change of variables: log det of d tanh(u)/du, numerically stable form
    logp -= jnp.sum(
        2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1
    )
    return a, logp


def init_actor_critic(obs_dim: int, action_dim: int, discrete: bool, seed: int = 0):
    model = ActorCritic(action_dim=action_dim, discrete=discrete)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim), jnp.float32)
    )["params"]
    return model, params


def sample_actions(model, params, obs, key):
    """jit-able: obs [B, D] -> (actions, log_probs, values)."""
    out, values = model.apply({"params": params}, obs)
    if model.discrete:
        logits = out
        actions = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), actions
        ]
        return actions, logp, values
    mean, log_std = out
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mean.shape)
    actions = mean + std * eps
    logp = _gaussian_logp(actions, mean, log_std)
    return actions, logp, values


def log_prob_entropy(model_discrete: bool, out, actions):
    """Differentiable log-prob + entropy for the PPO loss."""
    if model_discrete:
        logits = out
        all_logp = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            all_logp, actions[:, None].astype(jnp.int32), axis=1
        )[:, 0]
        probs = jnp.exp(all_logp)
        entropy = -jnp.sum(probs * all_logp, axis=-1)
        return logp, entropy
    mean, log_std = out
    logp = _gaussian_logp(actions, mean, log_std)
    entropy = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)
    entropy = jnp.broadcast_to(entropy, logp.shape)
    return logp, entropy


def _gaussian_logp(x, mean, log_std):
    std = jnp.exp(log_std)
    return jnp.sum(
        -0.5 * ((x - mean) / std) ** 2 - log_std - 0.5 * jnp.log(2 * jnp.pi),
        axis=-1,
    )


def compute_gae(
    rewards: np.ndarray,
    values: np.ndarray,
    dones: np.ndarray,
    last_values: np.ndarray,
    gamma: float,
    lam: float,
):
    """Generalized advantage estimation over [T, N] rollouts (reference:
    rllib/evaluation/postprocessing.py compute_advantages)."""
    T, N = rewards.shape
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    next_values = last_values
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_values * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_values = values[t]
    returns = adv + values
    return adv, returns
