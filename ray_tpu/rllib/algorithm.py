"""Algorithm + PPOConfig: the user-facing RL training loop.

Role-equivalent of the reference's Algorithm/AlgorithmConfig
(rllib/algorithms/algorithm.py:212, algorithm_config.py) scoped to PPO:
a builder config (``PPOConfig().environment(...).env_runners(...)
.training(...)``), an EnvRunnerGroup of rollout actors, a driver-side JAX
learner (on the TPU when present), train()/save/restore, and Tune
integration via ``as_trainable``.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .. import api
from .config_base import AlgorithmConfig
from .env import make_env, space_dims
from .env_runner import EnvRunner
from .learner import PPOLearner
from .models import compute_gae


def gae_batch(rollouts, gamma: float, lam: float) -> Dict[str, np.ndarray]:
    """Shared postprocess: per-rollout GAE + [T, N] -> [T*N] flatten ->
    one concatenated PPO batch. Used by the single-agent algorithm and by
    every policy of the multi-agent one — advantage math lives ONCE."""
    obs, actions, logp, adv, ret = [], [], [], [], []
    for ro in rollouts:
        a, r = compute_gae(
            ro["rewards"], ro["values"], ro["dones"], ro["last_values"],
            gamma, lam,
        )
        T, N = ro["rewards"].shape
        obs.append(ro["obs"].reshape(T * N, -1))
        actions.append(ro["actions"].reshape(T * N, *ro["actions"].shape[2:]))
        logp.append(ro["logp"].reshape(T * N))
        adv.append(a.reshape(T * N))
        ret.append(r.reshape(T * N))
    return {
        "obs": np.concatenate(obs).astype(np.float32),
        "actions": np.concatenate(actions),
        "logp_old": np.concatenate(logp),
        "advantages": np.concatenate(adv),
        "returns": np.concatenate(ret),
    }


class PPOConfig(AlgorithmConfig):
    #: connector factories are honored by this algorithm's runners
    supports_connectors = True

    def __init__(self):
        super().__init__()
        self.num_envs_per_runner = 4
        self.rollout_len = 64
        self.gamma = 0.99
        self.lam = 0.95
        self.lr = 3e-4
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 128
        self.max_grad_norm = 0.5


class PPO:
    """PPO with CPU rollout actors + driver-side JAX learner (the learner
    compiles to the TPU when one is attached — the split the reference
    implements as EnvRunnerGroup + LearnerGroup)."""

    def __init__(self, config: PPOConfig):
        if config.env_spec is None:
            raise ValueError("config.environment(...) is required")
        self.config = config
        self.iteration = 0
        # probe spaces locally (cheap env instance)
        probe = make_env(config.env_spec, config.env_config)()
        self.observation_space = probe.observation_space
        self.action_space = probe.action_space
        obs_dim, act_dim, discrete = space_dims(
            probe.observation_space, probe.action_space
        )
        try:
            probe.close()
        except Exception:
            pass
        self.learner = PPOLearner(
            obs_dim,
            act_dim,
            discrete,
            lr=config.lr,
            clip_param=config.clip_param,
            vf_coeff=config.vf_coeff,
            entropy_coeff=config.entropy_coeff,
            num_epochs=config.num_epochs,
            minibatch_size=config.minibatch_size,
            max_grad_norm=config.max_grad_norm,
            seed=config.seed,
        )
        from .weight_sync import broadcaster_for

        self._broadcaster = broadcaster_for(config)
        Runner = api.remote(num_cpus=config.num_cpus_per_runner)(EnvRunner)
        self.runners = [
            Runner.remote(
                config.env_spec,
                config.env_config,
                config.num_envs_per_runner,
                config.rollout_len,
                config.seed + 1000 * (i + 1),
                config.env_to_module_connector,
                config.module_to_env_connector,
            )
            for i in range(config.num_env_runners)
        ]
        api.get([r.ping.remote() for r in self.runners])
        self._ep_return_window: List[float] = []
        # driver-side env-to-module pipeline for compute_single_action —
        # inference must see the SAME transform the policy trained on.
        # (Stateful connector stats, e.g. running normalizers, live
        # per-runner and are not merged back; the reference syncs connector
        # state periodically — documented gap.)
        from .connectors import ConnectorContext, default_env_to_module

        self._infer_ctx = ConnectorContext(
            self.observation_space, self.action_space
        )
        self._infer_connector = (
            config.env_to_module_connector() if config.env_to_module_connector
            else default_env_to_module()
        )
        # per-iteration compute/collective/idle telemetry feeding the
        # scaling-efficiency gauge (util/metrics)
        from ..util.metrics import StepBreakdown

        self._step_breakdown = StepBreakdown(role="rllib")

    # -- training -----------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        """One iteration: parallel rollouts -> GAE -> learner update
        (reference: Algorithm.step / training_step)."""
        t0 = time.time()
        with self._step_breakdown.step():
            # params travel once per iteration (ObjectRef or weight-plane
            # version), never inline per runner — see rllib/weight_sync.py
            params_handle = self._broadcaster.handle(self.learner.get_params())
            rollouts = api.get(
                [r.sample.remote(params_handle) for r in self.runners]
            )
            batch, ep_returns, ep_lengths = self._postprocess(rollouts)
            stats = self.learner.update(batch)
        self.iteration += 1
        self._ep_return_window.extend(ep_returns)
        self._ep_return_window = self._ep_return_window[-100:]
        mean_return = (
            float(np.mean(self._ep_return_window))
            if self._ep_return_window
            else float("nan")
        )
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "num_episodes": len(ep_returns),
            "episode_len_mean": float(np.mean(ep_lengths))
            if ep_lengths
            else float("nan"),
            "num_env_steps_sampled": batch["obs"].shape[0],
            "time_this_iter_s": time.time() - t0,
            **stats,
        }

    def _postprocess(self, rollouts):
        batch = gae_batch(rollouts, self.config.gamma, self.config.lam)
        ep_returns, ep_lengths = [], []
        for ro in rollouts:
            ep_returns.extend(ro["episode_returns"])
            ep_lengths.extend(ro["episode_lengths"])
        return batch, ep_returns, ep_lengths

    # -- checkpointing (reference: Checkpointable) --------------------------

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(
                {
                    "learner": self.learner.state_dict(),
                    "iteration": self.iteration,
                },
                f,
            )
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.learner.load_state_dict(state["learner"])
        self.iteration = state["iteration"]

    def get_policy_params(self):
        return self.learner.get_params()

    def compute_single_action(self, obs):
        import jax
        import jax.numpy as jnp

        from .models import sample_actions

        key = jax.random.PRNGKey(self.iteration)
        encoded = np.asarray(
            self._infer_connector(np.asarray(obs)[None], self._infer_ctx),
            np.float32,
        )
        actions, _, _ = sample_actions(
            self.learner.model,
            self.learner.params,
            jnp.asarray(encoded),
            key,
        )
        return np.asarray(actions)[0]

    def stop(self):
        for r in self.runners:
            try:
                api.kill(r)
            except Exception:
                pass
        self.runners = []


PPOConfig.algo_class = PPO


def as_trainable(config):
    """Adapt ANY algorithm config (PPO/IMPALA/DQN/SAC/BC...) to a Tune
    trainable: tune.Tuner(rllib.as_trainable(cfg), ...). Overrides from the
    trial's param space are applied onto the config (reference: Algorithm
    being a Tune Trainable, rllib/algorithms/algorithm.py:212)."""

    def _train_fn(trial_config: dict):
        from .. import tune

        cfg = copy.deepcopy(config)
        for k, v in trial_config.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
        algo = cfg.build()
        try:
            while True:
                tune.report(algo.train())
        finally:
            algo.stop()

    return _train_fn
