"""IMPALA: asynchronous actor-learner with V-trace off-policy correction.

Role-equivalent of the reference's IMPALA family (rllib/algorithms/impala/
— IMPALAConfig, the async EnvRunner sampling + learner-group pipeline, and
vtrace_torch.py). TPU-first: rollouts arrive asynchronously from stale-
policy runners (api.wait on in-flight sample refs — the decoupling the
reference gets from its aggregation/broadcast actors), and the V-trace
target computation + policy/value update run as ONE jitted program: the
time-axis recursion is a ``lax.scan``, so the whole importance-corrected
update lowers to a single XLA program on the MXU instead of a Python loop.
APPO (the PPO-clipped variant) rides the same machinery via ``use_clip``.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import api
from .config_base import AlgorithmConfig
from .env import VectorEnv, encode_obs, make_env, space_dims
from .models import ActorCritic, log_prob_entropy


class ImpalaRunner:
    """Rollout actor returning [T, N] trajectories + behavior log-probs and
    the bootstrap observation (reference: SingleAgentEnvRunner used by
    IMPALA; values are NOT recorded — the learner recomputes them with its
    own fresh parameters, as V-trace requires)."""

    def __init__(self, env_spec, env_config, num_envs, rollout_len, seed):
        from .models import init_actor_critic, sample_actions

        factory = make_env(env_spec, env_config)
        self._vec = VectorEnv([factory for _ in range(num_envs)])
        self._rollout_len = rollout_len
        obs_dim, act_dim, discrete = space_dims(
            self._vec.observation_space, self._vec.action_space
        )
        self._model, _ = init_actor_critic(obs_dim, act_dim, discrete, seed)
        self._key = jax.random.PRNGKey(seed)
        self._encode = lambda o: encode_obs(self._vec.observation_space, o)
        self._obs = self._encode(self._vec.reset(seed=seed))
        self._ep_ret = np.zeros(num_envs, np.float32)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._sample_fn = jax.jit(
            lambda params, obs, key: sample_actions(
                self._model, params, obs, key
            )
        )

    def sample(self, params) -> Dict[str, Any]:
        from .weight_sync import resolve_params

        params = resolve_params(params)
        T, N = self._rollout_len, self._vec.num_envs
        obs_buf = np.zeros((T, N) + self._obs.shape[1:], np.float32)
        act_buf = None
        logp_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        ep_returns, ep_lengths = [], []
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            actions, logp, _values = self._sample_fn(
                params, self._obs.astype(np.float32), sub
            )
            actions = np.asarray(actions)
            obs_buf[t] = self._obs
            if act_buf is None:
                act_buf = np.zeros((T, N) + actions.shape[1:], actions.dtype)
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            next_obs, rewards, terms, truncs = self._vec.step(actions)
            dones = terms | truncs
            rew_buf[t] = rewards
            done_buf[t] = dones.astype(np.float32)
            self._ep_ret += rewards
            self._ep_len += 1
            for i in np.nonzero(dones)[0]:
                ep_returns.append(float(self._ep_ret[i]))
                ep_lengths.append(int(self._ep_len[i]))
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0
            self._obs = self._encode(next_obs)
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "behavior_logp": logp_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "bootstrap_obs": self._obs.astype(np.float32),
            "episode_returns": ep_returns,
            "episode_lengths": ep_lengths,
        }

    def ping(self):
        return True


class IMPALAConfig(AlgorithmConfig):
    """Builder config (reference: impala/impala.py IMPALAConfig)."""

    def __init__(self):
        super().__init__()
        self.num_envs_per_runner = 4
        self.gamma = 0.99
        self.lr = 5e-4
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.max_grad_norm = 40.0
        # V-trace clippings (IMPALA paper: rho_bar, c_bar)
        self.vtrace_rho_clip = 1.0
        self.vtrace_c_clip = 1.0
        # APPO variant: additionally clip the pg ratio PPO-style
        self.use_clip = False
        self.clip_param = 0.3
        self.num_batches_per_iter = 4


class APPOConfig(IMPALAConfig):
    """APPO = IMPALA machinery + PPO surrogate clipping (reference:
    rllib/algorithms/appo/)."""

    def __init__(self):
        super().__init__()
        self.use_clip = True


class IMPALA:
    """Async actor-learner: runners keep one sample() in flight each with
    whatever params they last received; the learner consumes rollouts as
    they land and corrects the off-policy gap with V-trace."""

    def __init__(self, config: IMPALAConfig):
        if config.env_spec is None:
            raise ValueError("config.environment(...) is required")
        self.config = config
        self.iteration = 0
        probe = make_env(config.env_spec, config.env_config)()
        obs_dim, act_dim, discrete = space_dims(
            probe.observation_space, probe.action_space
        )
        self.observation_space = probe.observation_space
        try:
            probe.close()
        except Exception:
            pass
        self._discrete = discrete
        self.model = ActorCritic(action_dim=act_dim, discrete=discrete)
        key = jax.random.PRNGKey(config.seed)
        self.params = self.model.init(
            key, jnp.zeros((1, obs_dim), jnp.float32)
        )["params"]
        self.tx = optax.chain(
            optax.clip_by_global_norm(config.max_grad_norm),
            optax.adam(config.lr),
        )
        self.opt_state = self.tx.init(self.params)
        self._update = jax.jit(self._update_impl)

        from .weight_sync import broadcaster_for

        self._broadcaster = broadcaster_for(config)
        Runner = api.remote(num_cpus=config.num_cpus_per_runner)(ImpalaRunner)
        self.runners = [
            Runner.remote(
                config.env_spec, config.env_config,
                config.num_envs_per_runner, config.rollout_len,
                config.seed + 1000 * (i + 1),
            )
            for i in range(config.num_env_runners)
        ]
        api.get([r.ping.remote() for r in self.runners])
        # async pipeline: one in-flight sample per runner (params broadcast
        # once — every runner's first rollout shares the same handle)
        params_handle = self._broadcaster.handle(self.params)
        self._inflight: Dict[Any, Any] = {
            r.sample.remote(params_handle): r for r in self.runners
        }
        self._ep_return_window: List[float] = []

    # -- jitted V-trace update ----------------------------------------------

    def _update_impl(self, params, opt_state, batch):
        cfg = self.config

        def loss_fn(p):
            T, N = batch["rewards"].shape
            flat_obs = batch["obs"].reshape(T * N, -1)
            out, values_flat = self.model.apply({"params": p}, flat_obs)
            flat_actions = batch["actions"].reshape(
                (T * N,) + batch["actions"].shape[2:]
            )
            logp_flat, entropy_flat = log_prob_entropy(
                self._discrete, out, flat_actions
            )
            values = values_flat.reshape(T, N)
            target_logp = logp_flat.reshape(T, N)
            _, bootstrap_v = self.model.apply(
                {"params": p}, batch["bootstrap_obs"]
            )

            # V-trace (IMPALA paper eq. 1): backward lax.scan over time
            rhos = jnp.exp(target_logp - batch["behavior_logp"])
            clipped_rho = jnp.minimum(rhos, cfg.vtrace_rho_clip)
            clipped_c = jnp.minimum(rhos, cfg.vtrace_c_clip)
            discounts = cfg.gamma * (1.0 - batch["dones"])
            values_sg = jax.lax.stop_gradient(values)
            bootstrap_sg = jax.lax.stop_gradient(bootstrap_v)

            next_values = jnp.concatenate(
                [values_sg[1:], bootstrap_sg[None]], axis=0
            )
            deltas = clipped_rho * (
                batch["rewards"] + discounts * next_values - values_sg
            )

            def vtrace_step(acc, xs):
                delta_t, discount_t, c_t = xs
                acc = delta_t + discount_t * c_t * acc
                return acc, acc

            _, vs_minus_v = jax.lax.scan(
                vtrace_step,
                jnp.zeros_like(values_sg[0]),
                (deltas, discounts, clipped_c),
                reverse=True,
            )
            vs = vs_minus_v + values_sg
            next_vs = jnp.concatenate([vs[1:], bootstrap_sg[None]], axis=0)
            pg_adv = clipped_rho * (
                batch["rewards"] + discounts * next_vs - values_sg
            )
            pg_adv = jax.lax.stop_gradient(pg_adv)

            if cfg.use_clip:
                # APPO: PPO surrogate on the V-trace advantage
                ratio = jnp.exp(target_logp - batch["behavior_logp"])
                pg1 = ratio * pg_adv
                pg2 = jnp.clip(
                    ratio, 1 - cfg.clip_param, 1 + cfg.clip_param
                ) * pg_adv
                pg_loss = -jnp.mean(jnp.minimum(pg1, pg2))
            else:
                pg_loss = -jnp.mean(target_logp * pg_adv)
            vf_loss = jnp.mean((values - jax.lax.stop_gradient(vs)) ** 2)
            ent = jnp.mean(entropy_flat)
            total = pg_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * ent
            return total, {
                "policy_loss": pg_loss,
                "vf_loss": vf_loss,
                "entropy": ent,
                "total_loss": total,
                "mean_rho": jnp.mean(rhos),
            }

        (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, stats

    # -- async training loop -------------------------------------------------

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        cfg = self.config
        stats_acc: List[Dict[str, float]] = []
        ep_returns: List[float] = []
        steps = 0
        for _ in range(cfg.num_batches_per_iter):
            ready, _ = api.wait(
                list(self._inflight), num_returns=1, timeout=120
            )
            if not ready:
                break
            ref = ready[0]
            runner = self._inflight.pop(ref)
            rollout = api.get(ref)
            batch = {
                "obs": jnp.asarray(rollout["obs"]),
                "actions": jnp.asarray(rollout["actions"]),
                "behavior_logp": jnp.asarray(rollout["behavior_logp"]),
                "rewards": jnp.asarray(rollout["rewards"]),
                "dones": jnp.asarray(rollout["dones"]),
                "bootstrap_obs": jnp.asarray(rollout["bootstrap_obs"]),
            }
            self.params, self.opt_state, stats = self._update(
                self.params, self.opt_state, batch
            )
            stats_acc.append({k: float(v) for k, v in stats.items()})
            ep_returns.extend(rollout["episode_returns"])
            steps += rollout["rewards"].size
            # resubmit with fresh params — the runner's next rollout is at
            # most one update stale (reference: broadcast interval). The
            # broadcaster keys on params identity, so each update broadcasts
            # once even when several runners resubmit between updates.
            self._inflight[
                runner.sample.remote(self._broadcaster.handle(self.params))
            ] = runner

        self.iteration += 1
        self._ep_return_window.extend(ep_returns)
        self._ep_return_window = self._ep_return_window[-100:]
        mean_stats = {
            k: float(np.mean([s[k] for s in stats_acc]))
            for k in (stats_acc[0] if stats_acc else {})
        }
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(self._ep_return_window))
                if self._ep_return_window else float("nan")
            ),
            "num_episodes": len(ep_returns),
            "num_env_steps_sampled": steps,
            "time_this_iter_s": time.time() - t0,
            **mean_stats,
        }

    # -- checkpointing -------------------------------------------------------

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        with open(os.path.join(checkpoint_dir, "impala_state.pkl"), "wb") as f:
            pickle.dump(
                {
                    "params": jax.tree.map(np.asarray, self.params),
                    "iteration": self.iteration,
                },
                f,
            )
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        with open(os.path.join(checkpoint_dir, "impala_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = self.tx.init(self.params)
        self.iteration = state["iteration"]

    def compute_single_action(self, obs):
        from .env import encode_obs
        from .models import sample_actions

        enc = encode_obs(self.observation_space, np.asarray(obs)[None])
        actions, _, _ = sample_actions(
            self.model, self.params, jnp.asarray(enc),
            jax.random.PRNGKey(self.iteration),
        )
        return np.asarray(actions)[0]

    def stop(self):
        for r in self.runners:
            try:
                api.kill(r)
            except Exception:
                pass
        self.runners = []
        self._inflight = {}


IMPALAConfig.algo_class = IMPALA
