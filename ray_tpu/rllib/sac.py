"""SAC: soft actor-critic for continuous control.

Role-equivalent of the reference's SAC family (rllib/algorithms/sac/ —
SACConfig, twin Q networks, squashed gaussian policy, auto-tuned entropy
temperature). TPU-first: actor, both critics, the temperature, and the
polyak target update all advance inside ONE jitted function per train
batch; the ``num_updates_per_iter`` gradient steps run under a single
``lax.scan`` so the whole off-policy update is one XLA program on the MXU.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import api
from .config_base import AlgorithmConfig
from .dqn import ReplayBuffer
from .env import VectorEnv, encode_obs, make_env, space_dims
from .models import SquashedGaussianActor, TwinQ, squashed_sample_logp


class SACRunner:
    """Rollout actor sampling from the squashed gaussian policy, rescaling
    tanh actions into the env's Box bounds."""

    def __init__(self, env_spec, env_config, num_envs, rollout_len, seed):
        factory = make_env(env_spec, env_config)
        self._vec = VectorEnv([factory for _ in range(num_envs)])
        obs_dim, act_dim, discrete = space_dims(
            self._vec.observation_space, self._vec.action_space
        )
        if discrete:
            raise ValueError("SAC requires a continuous (Box) action space")
        self._rollout_len = rollout_len
        self._actor = SquashedGaussianActor(action_dim=act_dim)
        self._key = jax.random.PRNGKey(seed)
        self._encode = lambda o: encode_obs(self._vec.observation_space, o)
        self._obs = self._encode(self._vec.reset(seed=seed))
        space = self._vec.action_space
        self._act_low = np.asarray(space.low, np.float32)
        self._act_high = np.asarray(space.high, np.float32)
        self._ep_ret = np.zeros(num_envs, np.float32)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._np_rng = np.random.default_rng(seed)  # warmup exploration

        def _sample(params, obs, key):
            mean, log_std = self._actor.apply({"params": params}, obs)
            a, _ = squashed_sample_logp(mean, log_std, key)
            return a

        self._sample_fn = jax.jit(_sample)

    def sample(self, params, random_actions: bool = False) -> Dict[str, Any]:
        from .weight_sync import resolve_params

        params = resolve_params(params)
        out: Dict[str, List] = {
            "obs": [], "actions": [], "rewards": [], "next_obs": [],
            "dones": [],
        }
        ep_returns, ep_lengths = [], []
        for _ in range(self._rollout_len):
            if random_actions:  # warmup exploration before learning starts
                a = self._np_rng.uniform(
                    -1.0, 1.0, (len(self._obs), len(self._act_low))
                ).astype(np.float32)
            else:
                self._key, sub = jax.random.split(self._key)
                a = np.asarray(
                    self._sample_fn(
                        params, self._obs.astype(np.float32), sub
                    )
                )
            env_a = self._act_low + (a + 1.0) * 0.5 * (
                self._act_high - self._act_low
            )
            next_obs, rewards, terms, truncs = self._vec.step(env_a)
            next_enc = self._encode(next_obs)
            dones = (terms | truncs).astype(np.float32)
            out["obs"].append(self._obs)
            out["actions"].append(a)  # store the tanh-space action
            out["rewards"].append(rewards)
            out["next_obs"].append(next_enc)
            out["dones"].append(dones)
            self._ep_ret += rewards
            self._ep_len += 1
            for i in np.nonzero(dones)[0]:
                ep_returns.append(float(self._ep_ret[i]))
                ep_lengths.append(int(self._ep_len[i]))
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0
            self._obs = next_enc
        return {
            "obs": np.concatenate(out["obs"]).astype(np.float32),
            "actions": np.concatenate(out["actions"]).astype(np.float32),
            "rewards": np.concatenate(out["rewards"]),
            "next_obs": np.concatenate(out["next_obs"]).astype(np.float32),
            "dones": np.concatenate(out["dones"]),
            "episode_returns": ep_returns,
            "episode_lengths": ep_lengths,
        }

    def ping(self):
        return True


class SACConfig(AlgorithmConfig):
    """Builder config (reference: sac/sac.py SACConfig)."""

    def __init__(self):
        super().__init__()
        self.num_env_runners = 1
        self.num_envs_per_runner = 1
        self.rollout_len = 64
        self.gamma = 0.99
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.tau = 0.005  # polyak coefficient for target critics
        self.initial_alpha = 1.0
        self.target_entropy: Optional[float] = None  # default: -act_dim
        self.buffer_capacity = 100_000
        self.learning_starts = 1000
        self.train_batch_size = 256
        self.num_updates_per_iter = 16


class SAC:
    def __init__(self, config: SACConfig):
        if config.env_spec is None:
            raise ValueError("config.environment(...) is required")
        self.config = config
        self.iteration = 0
        probe = make_env(config.env_spec, config.env_config)()
        obs_dim, act_dim, discrete = space_dims(
            probe.observation_space, probe.action_space
        )
        if discrete:
            raise ValueError("SAC requires a continuous (Box) action space")
        probe_act_low = np.asarray(probe.action_space.low, np.float32)
        probe_act_high = np.asarray(probe.action_space.high, np.float32)
        try:
            probe.close()
        except Exception:
            pass
        self._obs_dim, self._act_dim = obs_dim, act_dim
        self.target_entropy = (
            config.target_entropy
            if config.target_entropy is not None
            else -float(act_dim)
        )

        key = jax.random.PRNGKey(config.seed)
        k_actor, k_critic = jax.random.split(key)
        self.actor = SquashedGaussianActor(action_dim=act_dim)
        self.critic = TwinQ()
        zo = jnp.zeros((1, obs_dim), jnp.float32)
        za = jnp.zeros((1, act_dim), jnp.float32)
        self.state = {
            "actor": self.actor.init(k_actor, zo)["params"],
            "critic": self.critic.init(k_critic, zo, za)["params"],
            "log_alpha": jnp.log(jnp.asarray(config.initial_alpha)),
        }
        self.state["target_critic"] = jax.tree.map(
            jnp.copy, self.state["critic"]
        )
        self.actor_tx = optax.adam(config.actor_lr)
        self.critic_tx = optax.adam(config.critic_lr)
        self.alpha_tx = optax.adam(config.alpha_lr)
        self.opt = {
            "actor": self.actor_tx.init(self.state["actor"]),
            "critic": self.critic_tx.init(self.state["critic"]),
            "alpha": self.alpha_tx.init(self.state["log_alpha"]),
        }
        self._update_scan = jax.jit(self._update_scan_impl)

        self._act_low = np.asarray(probe_act_low, np.float32)
        self._act_high = np.asarray(probe_act_high, np.float32)
        Buffer = api.remote(num_cpus=0)(ReplayBuffer)
        self.buffer = Buffer.remote(
            config.buffer_capacity, obs_dim, (act_dim,), np.float32
        )
        from .weight_sync import broadcaster_for

        self._broadcaster = broadcaster_for(config)
        Runner = api.remote(num_cpus=config.num_cpus_per_runner)(SACRunner)
        self.runners = [
            Runner.remote(
                config.env_spec, config.env_config,
                config.num_envs_per_runner, config.rollout_len,
                config.seed + 1000 * (i + 1),
            )
            for i in range(config.num_env_runners)
        ]
        api.get([r.ping.remote() for r in self.runners])
        self._ep_return_window: List[float] = []

    # -- jitted update (all SAC losses + polyak, scanned over minibatches) ---

    def _one_update(self, carry, batch):
        state, opt, key = carry
        cfg = self.config
        key, k_next, k_cur = jax.random.split(key, 3)

        # critic loss: soft Bellman target from target critics
        mean_n, log_std_n = self.actor.apply(
            {"params": state["actor"]}, batch["next_obs"]
        )
        next_a, next_logp = squashed_sample_logp(mean_n, log_std_n, k_next)
        tq1, tq2 = self.critic.apply(
            {"params": state["target_critic"]}, batch["next_obs"], next_a
        )
        alpha = jnp.exp(state["log_alpha"])
        target_v = jnp.minimum(tq1, tq2) - alpha * next_logp
        target_q = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) * (
            jax.lax.stop_gradient(target_v)
        )

        def critic_loss_fn(cp):
            q1, q2 = self.critic.apply(
                {"params": cp}, batch["obs"], batch["actions"]
            )
            return jnp.mean((q1 - target_q) ** 2) + jnp.mean(
                (q2 - target_q) ** 2
            )

        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(state["critic"])
        c_updates, opt_critic = self.critic_tx.update(
            c_grads, opt["critic"], state["critic"]
        )
        critic_params = optax.apply_updates(state["critic"], c_updates)

        # actor loss: maximize E[min Q - alpha * logp]
        def actor_loss_fn(ap):
            mean, log_std = self.actor.apply({"params": ap}, batch["obs"])
            a, logp = squashed_sample_logp(mean, log_std, k_cur)
            q1, q2 = self.critic.apply(
                {"params": critic_params}, batch["obs"], a
            )
            q = jnp.minimum(q1, q2)
            return jnp.mean(alpha * logp - q), logp

        (a_loss, logp), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(state["actor"])
        a_updates, opt_actor = self.actor_tx.update(
            a_grads, opt["actor"], state["actor"]
        )
        actor_params = optax.apply_updates(state["actor"], a_updates)

        # temperature: drive policy entropy toward target_entropy
        def alpha_loss_fn(log_alpha):
            return -jnp.mean(
                jnp.exp(log_alpha)
                * jax.lax.stop_gradient(logp + self.target_entropy)
            )

        al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(
            state["log_alpha"]
        )
        al_updates, opt_alpha = self.alpha_tx.update(
            al_grad, opt["alpha"], state["log_alpha"]
        )
        log_alpha = optax.apply_updates(state["log_alpha"], al_updates)

        # polyak target update
        tau = cfg.tau
        target_critic = jax.tree.map(
            lambda t, o: (1.0 - tau) * t + tau * o,
            state["target_critic"],
            critic_params,
        )
        new_state = {
            "actor": actor_params,
            "critic": critic_params,
            "target_critic": target_critic,
            "log_alpha": log_alpha,
        }
        new_opt = {
            "actor": opt_actor,
            "critic": opt_critic,
            "alpha": opt_alpha,
        }
        stats = {
            "critic_loss": c_loss,
            "actor_loss": a_loss,
            "alpha_loss": al_loss,
            "alpha": jnp.exp(log_alpha),
            "entropy": -jnp.mean(logp),
        }
        return (new_state, new_opt, key), stats

    def _update_scan_impl(self, state, opt, key, batches):
        (state, opt, _), stats = jax.lax.scan(
            self._one_update, (state, opt, key), batches
        )
        return state, opt, jax.tree.map(jnp.mean, stats)

    # -- training loop -------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        cfg = self.config
        warmup = api.get(self.buffer.size.remote()) < cfg.learning_starts
        actor_handle = self._broadcaster.handle(self.state["actor"])
        rollouts = api.get(
            [r.sample.remote(actor_handle, warmup) for r in self.runners]
        )
        adds, ep_returns = [], []
        for ro in rollouts:
            adds.append(
                self.buffer.add.remote(
                    ro["obs"], ro["actions"], ro["rewards"],
                    ro["next_obs"], ro["dones"],
                )
            )
            ep_returns.extend(ro["episode_returns"])
        buffer_size = api.get(adds)[-1]

        stats: Dict[str, float] = {}
        if buffer_size >= cfg.learning_starts:
            batches = api.get(
                self.buffer.sample_many.remote(
                    cfg.train_batch_size,
                    cfg.num_updates_per_iter,
                    seed=cfg.seed + self.iteration * 997,
                )
            )
            jb = {k: jnp.asarray(v) for k, v in batches.items()}
            self.state, self.opt, jstats = self._update_scan(
                self.state, self.opt,
                jax.random.PRNGKey(cfg.seed + self.iteration),
                jb,
            )
            stats = {k: float(v) for k, v in jstats.items()}

        self.iteration += 1
        self._ep_return_window.extend(ep_returns)
        self._ep_return_window = self._ep_return_window[-100:]
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(self._ep_return_window))
                if self._ep_return_window else float("nan")
            ),
            "num_episodes": len(ep_returns),
            "buffer_size": buffer_size,
            "num_env_steps_sampled": sum(
                len(ro["rewards"]) for ro in rollouts
            ),
            "time_this_iter_s": time.time() - t0,
            **stats,
        }

    # -- checkpointing -------------------------------------------------------

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        with open(os.path.join(checkpoint_dir, "sac_state.pkl"), "wb") as f:
            pickle.dump(
                {
                    "state": jax.tree.map(np.asarray, self.state),
                    "iteration": self.iteration,
                },
                f,
            )
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        with open(os.path.join(checkpoint_dir, "sac_state.pkl"), "rb") as f:
            saved = pickle.load(f)
        self.state = jax.tree.map(jnp.asarray, saved["state"])
        self.opt = {
            "actor": self.actor_tx.init(self.state["actor"]),
            "critic": self.critic_tx.init(self.state["critic"]),
            "alpha": self.alpha_tx.init(self.state["log_alpha"]),
        }
        self.iteration = saved["iteration"]

    def compute_single_action(self, obs):
        """Deterministic (mean) action, rescaled into the env's Box bounds —
        the same mapping the rollout runners apply before env.step."""
        mean, _ = self.actor.apply(
            {"params": self.state["actor"]},
            jnp.asarray(np.asarray(obs, np.float32).reshape(1, -1)),
        )
        a = np.asarray(jnp.tanh(mean))[0]
        return self._act_low + (a + 1.0) * 0.5 * (
            self._act_high - self._act_low
        )

    def stop(self):
        for r in self.runners:
            try:
                api.kill(r)
            except Exception:
                pass
        try:
            api.kill(self.buffer)
        except Exception:
            pass
        self.runners = []


SACConfig.algo_class = SAC
