"""MultiAgentEnv: the dict-keyed environment interface.

Role-equivalent of the reference's MultiAgentEnv
(rllib/env/multi_agent_env.py:30): observations, rewards, and done flags are
dicts keyed by agent id; ``terminateds``/``truncateds`` carry the special
``"__all__"`` key ending the episode for everyone. Agents map to policies
through ``policy_mapping_fn`` (multi_agent.py) — several agents may share one
policy (parameter sharing) or each own their own.

The TPU-side restriction (documented, checked): **simultaneous-move** envs —
every agent in ``possible_agents`` observes and acts on every step. That
keeps per-policy rollouts rectangular ([T, n_agents] arrays), which is what
the jitted GAE/update path consumes; turn-based games need a padding wrapper.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple


class MultiAgentEnv:
    #: stable agent ids, all present every step (simultaneous-move)
    possible_agents: Tuple[str, ...] = ()

    def observation_space(self, agent_id: str):
        raise NotImplementedError

    def action_space(self, agent_id: str):
        raise NotImplementedError

    def reset(self, seed: Optional[int] = None) -> Tuple[Dict[str, Any], Dict]:
        """-> (obs_dict, infos_dict)"""
        raise NotImplementedError

    def step(
        self, action_dict: Dict[str, Any]
    ) -> Tuple[Dict, Dict, Dict, Dict, Dict]:
        """-> (obs, rewards, terminateds, truncateds, infos), all dicts
        keyed by agent id; terminateds/truncateds also carry "__all__"."""
        raise NotImplementedError

    def close(self):
        pass


def episode_done(terminateds: Dict, truncateds: Dict) -> bool:
    """The episode ends when "__all__" is flagged (reference: the __all__
    convention in multi_agent_env.py) or every agent is individually done."""
    if terminateds.get("__all__") or truncateds.get("__all__"):
        return True
    agent_keys = {
        k for k in (*terminateds, *truncateds) if k != "__all__"
    }
    return bool(agent_keys) and all(
        terminateds.get(k) or truncateds.get(k) for k in agent_keys
    )
