"""BC: offline behavior cloning.

Role-equivalent of the reference's BC algorithm (rllib/algorithms/bc/ —
offline RL base: learn the logged policy by supervised learning on
(obs, action) pairs, no environment interaction). TPU-first: the whole
epoch (shuffle + minibatch SGD) is one jitted ``lax.scan``; the offline
dataset arrives either as numpy arrays or as a ``ray_tpu.data.Dataset``
streamed through the object store.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .config_base import AlgorithmConfig
from .env import encode_obs, make_env, space_dims
from .models import ActorCritic, log_prob_entropy


class BCConfig(AlgorithmConfig):
    """Builder config (reference: bc/bc.py BCConfig + offline_data)."""

    def __init__(self):
        super().__init__()
        # offline input: {"obs": [N, D], "actions": [N] or [N, A]} arrays,
        # or a ray_tpu.data.Dataset of such rows
        self.input_data: Any = None
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_epochs_per_iter = 1

    def offline_data(self, input_data) -> "BCConfig":
        self.input_data = input_data
        return self


class BC:
    def __init__(self, config: BCConfig):
        if config.env_spec is None:
            raise ValueError("config.environment(...) is required")
        if config.input_data is None:
            raise ValueError("config.offline_data(...) is required")
        self.config = config
        self.iteration = 0
        probe = make_env(config.env_spec, config.env_config)()
        self._obs_space = probe.observation_space
        obs_dim, act_dim, discrete = space_dims(
            probe.observation_space, probe.action_space
        )
        try:
            probe.close()
        except Exception:
            pass
        self._discrete = discrete
        self.model = ActorCritic(action_dim=act_dim, discrete=discrete)
        self.params = self.model.init(
            jax.random.PRNGKey(config.seed), jnp.zeros((1, obs_dim))
        )["params"]
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self._epoch_fn = jax.jit(self._epoch_impl)
        self._key = jax.random.PRNGKey(config.seed + 1)
        # one-time host->device transfer: the offline dataset is immutable
        self._data = jax.tree.map(
            jnp.asarray, self._materialize(config.input_data, obs_dim)
        )

    def _materialize(self, data, obs_dim) -> Dict[str, np.ndarray]:
        from ..data.dataset import Dataset

        if isinstance(data, Dataset):
            rows = data.take_all()
            obs = np.stack([np.asarray(r["obs"], np.float32) for r in rows])
            actions = np.stack([np.asarray(r["actions"]) for r in rows])
        else:
            obs = np.asarray(data["obs"], np.float32)
            actions = np.asarray(data["actions"])
        obs = encode_obs(self._obs_space, obs)
        assert obs.shape[1] == obs_dim, (obs.shape, obs_dim)
        if self._discrete:
            actions = actions.astype(np.int64).reshape(len(actions))
        else:
            actions = actions.astype(np.float32).reshape(len(actions), -1)
        return {"obs": obs, "actions": actions}

    # -- jitted supervised epoch ---------------------------------------------

    def _loss(self, params, batch):
        out, _values = self.model.apply({"params": params}, batch["obs"])
        logp, _ = log_prob_entropy(self._discrete, out, batch["actions"])
        return -jnp.mean(logp)

    def _epoch_impl(self, params, opt_state, key, data):
        B = data["obs"].shape[0]
        mb = min(self.config.train_batch_size, B)
        n_mb = max(B // mb, 1)

        def step(carry, idx):
            params, opt_state = carry
            batch = jax.tree.map(lambda x: x[idx], data)
            loss, grads = jax.value_and_grad(self._loss)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        perm = jax.random.permutation(key, B)[: n_mb * mb].reshape(n_mb, mb)
        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), perm
        )
        return params, opt_state, jnp.mean(losses)

    # -- training -----------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        losses = []
        for _ in range(self.config.num_epochs_per_iter):
            self._key, sub = jax.random.split(self._key)
            self.params, self.opt_state, loss = self._epoch_fn(
                self.params, self.opt_state, sub, self._data
            )
            losses.append(float(loss))
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "bc_loss": float(np.mean(losses)),
            "num_samples": int(self._data["obs"].shape[0]),
            "time_this_iter_s": time.time() - t0,
        }

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        """Greedy rollouts in the real env (reference: Algorithm.evaluate)."""
        env = make_env(self.config.env_spec, self.config.env_config)()
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=self.config.seed + ep)
            done, total = False, 0.0
            steps = 0
            while not done and steps < 1000:
                a = self.compute_single_action(obs)
                obs, r, term, trunc, _ = env.step(a)
                total += float(r)
                done = term or trunc
                steps += 1
            returns.append(total)
        try:
            env.close()
        except Exception:
            pass
        return {
            "episode_return_mean": float(np.mean(returns)),
            "num_episodes": num_episodes,
        }

    def compute_single_action(self, obs):
        enc = encode_obs(self._obs_space, np.asarray(obs)[None])
        out, _ = self.model.apply({"params": self.params}, jnp.asarray(enc))
        if self._discrete:
            return int(np.asarray(jnp.argmax(out, axis=-1))[0])
        mean, _log_std = out
        return np.asarray(mean)[0]

    # -- checkpointing -------------------------------------------------------

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        with open(os.path.join(checkpoint_dir, "bc_state.pkl"), "wb") as f:
            pickle.dump(
                {
                    "params": jax.tree.map(np.asarray, self.params),
                    "iteration": self.iteration,
                },
                f,
            )
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        with open(os.path.join(checkpoint_dir, "bc_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = self.tx.init(self.params)
        self.iteration = state["iteration"]

    def stop(self):
        pass


BCConfig.algo_class = BC
