"""DreamerV3: model-based RL via latent imagination.

Role-equivalent of the reference's DreamerV3 family
(rllib/algorithms/dreamerv3/ — DreamerV3Config, RSSM world model with
discrete latents, imagination-trained actor-critic; torch/tf in the
reference). TPU-first: the ENTIRE update — world-model observe (a
``lax.scan`` over the sequence), latent imagination (a second scan over
the horizon), and the three gradient steps (world model, actor, critic)
— is ONE jitted XLA program per train batch, so the MXU sees a single
fused schedule with no host round-trips between the phases.

DreamerV3's robustness tricks are kept (they are what makes one set of
hyperparameters work across domains):

- symlog squashing of inputs/targets, two-hot categorical regression for
  reward and value heads (symexp-spaced bins);
- categorical latents (``stoch_groups`` x ``stoch_classes``) with 1%
  uniform-mix ("unimix") and straight-through gradients;
- KL balancing: dynamics loss ``KL(sg(post) || prior)`` at 0.5 vs
  representation loss ``KL(post || sg(prior))`` at 0.1, both clipped
  below 1 free nat;
- percentile return normalization (EMA of the imagined-return 5th..95th
  percentile range) for the actor;
- an EMA "slow" critic both as regularizer target and bootstrap.

Vector observations (Box or one-hot Discrete) with an MLP encoder /
decoder; discrete actions use a categorical actor with REINFORCE
gradients, continuous actions a tanh-gaussian.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from .. import api
from .config_base import AlgorithmConfig
from .env import VectorEnv, encode_obs, make_env, space_dims
from .models import squashed_sample_logp

# ---------------------------------------------------------------------------
# symlog / two-hot regression helpers


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def twohot_bins(n_bins: int, low: float = -20.0, high: float = 20.0):
    """Bin centers in symlog space (decoded values are symexp(bin))."""
    return jnp.linspace(low, high, n_bins, dtype=jnp.float32)


def twohot_encode(y, bins):
    """Scalar targets -> two-hot distribution over ``bins`` (y in symlog
    space). Weight splits linearly between the two straddling bins."""
    y = jnp.clip(y, bins[0], bins[-1])
    idx_hi = jnp.clip(jnp.searchsorted(bins, y), 1, len(bins) - 1)
    idx_lo = idx_hi - 1
    lo, hi = bins[idx_lo], bins[idx_hi]
    frac = (y - lo) / jnp.maximum(hi - lo, 1e-8)
    onehot_lo = jax.nn.one_hot(idx_lo, len(bins))
    onehot_hi = jax.nn.one_hot(idx_hi, len(bins))
    return onehot_lo * (1.0 - frac)[..., None] + onehot_hi * frac[..., None]


def twohot_decode(logits, bins):
    """Expected value of the categorical over bins, back through symexp."""
    return symexp(jax.nn.softmax(logits) @ bins)


def twohot_loss(logits, target_scalar, bins):
    """Cross-entropy of the two-hot target (target in raw space)."""
    target = twohot_encode(symlog(target_scalar), bins)
    return -jnp.sum(target * jax.nn.log_softmax(logits), axis=-1)


# ---------------------------------------------------------------------------
# categorical latent helpers (unimix + straight-through)

UNIMIX = 0.01


def _unimix_probs(logits):
    probs = jax.nn.softmax(logits)
    return (1.0 - UNIMIX) * probs + UNIMIX / logits.shape[-1]


def latent_sample(logits, key):
    """Straight-through sample of (G, C) categorical latents -> flat
    one-hot of shape [..., G*C]."""
    probs = _unimix_probs(logits)
    idx = jax.random.categorical(key, jnp.log(probs))
    onehot = jax.nn.one_hot(idx, logits.shape[-1], dtype=jnp.float32)
    st = onehot + probs - jax.lax.stop_gradient(probs)
    return st.reshape(*st.shape[:-2], -1)


def latent_kl(lhs_logits, rhs_logits):
    """KL(lhs || rhs) summed over latent groups; logits [..., G, C]."""
    lp = _unimix_probs(lhs_logits)
    return jnp.sum(
        lp * (jnp.log(lp) - jnp.log(_unimix_probs(rhs_logits))),
        axis=(-2, -1),
    )


# ---------------------------------------------------------------------------
# network modules


class _MLP(nn.Module):
    out_dim: int
    hidden: int
    layers: int = 2

    @nn.compact
    def __call__(self, x):
        for _ in range(self.layers):
            x = nn.silu(nn.LayerNorm()(nn.Dense(self.hidden)(x)))
        return nn.Dense(self.out_dim)(x)


class _Actor(nn.Module):
    action_dim: int
    discrete: bool
    hidden: int
    layers: int = 2

    @nn.compact
    def __call__(self, x):
        for _ in range(self.layers):
            x = nn.silu(nn.LayerNorm()(nn.Dense(self.hidden)(x)))
        if self.discrete:
            return nn.Dense(self.action_dim)(x)
        mean = nn.Dense(self.action_dim)(x)
        log_std = jnp.clip(nn.Dense(self.action_dim)(x), -5.0, 2.0)
        return mean, log_std


class DreamerNets:
    """All modules + a single init; params live in one pytree so the world
    model / actor / critic optimizers slice it by top-level key."""

    def __init__(self, cfg: "DreamerV3Config", obs_dim: int, act_dim: int,
                 discrete: bool):
        self.cfg = cfg
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.discrete = discrete
        g, c, h = cfg.stoch_groups, cfg.stoch_classes, cfg.hidden_units
        self.stoch_dim = g * c
        self.feat_dim = cfg.deter_dim + self.stoch_dim
        self.encoder = _MLP(out_dim=h, hidden=h)
        self.inp_proj = _MLP(out_dim=h, hidden=h, layers=1)
        self.gru = nn.GRUCell(features=cfg.deter_dim)
        self.prior_head = _MLP(out_dim=g * c, hidden=h, layers=1)
        self.post_head = _MLP(out_dim=g * c, hidden=h, layers=1)
        self.decoder = _MLP(out_dim=obs_dim, hidden=h)
        self.reward_head = _MLP(out_dim=cfg.n_bins, hidden=h)
        self.cont_head = _MLP(out_dim=1, hidden=h)
        self.actor = _Actor(
            action_dim=act_dim, discrete=discrete, hidden=h
        )
        self.critic = _MLP(out_dim=cfg.n_bins, hidden=h)
        self.bins = twohot_bins(cfg.n_bins)

    def init_params(self, key) -> Dict[str, Any]:
        cfg = self.cfg
        ks = jax.random.split(key, 10)
        zo = jnp.zeros((1, self.obs_dim), jnp.float32)
        zd = jnp.zeros((1, cfg.deter_dim), jnp.float32)
        zs = jnp.zeros((1, self.stoch_dim), jnp.float32)
        za = jnp.zeros((1, self.act_dim), jnp.float32)
        zh = jnp.zeros((1, cfg.hidden_units), jnp.float32)
        zf = jnp.zeros((1, self.feat_dim), jnp.float32)
        inp = jnp.concatenate([zs, za], -1)
        wm = {
            "encoder": self.encoder.init(ks[0], zo)["params"],
            "inp_proj": self.inp_proj.init(ks[1], inp)["params"],
            "gru": self.gru.init(ks[2], zd, zh)["params"],
            "prior": self.prior_head.init(ks[3], zd)["params"],
            "post": self.post_head.init(
                ks[4], jnp.concatenate([zd, zh], -1)
            )["params"],
            "decoder": self.decoder.init(ks[5], zf)["params"],
            "reward": self.reward_head.init(ks[6], zf)["params"],
            "cont": self.cont_head.init(ks[7], zf)["params"],
        }
        critic = self.critic.init(ks[9], zf)["params"]
        return {
            "wm": wm,
            "actor": self.actor.init(ks[8], zf)["params"],
            "critic": critic,
            "slow_critic": jax.tree.map(jnp.copy, critic),
        }

    # -- pure-function building blocks (used under jit/scan) ----------------

    def _seq_step(self, wm, deter, stoch, action):
        """(h_{t-1}, z_{t-1}, a_{t-1}) -> h_t."""
        inp = self.inp_proj.apply(
            {"params": wm["inp_proj"]},
            jnp.concatenate([stoch, action], -1),
        )
        deter, _ = self.gru.apply({"params": wm["gru"]}, deter, inp)
        return deter

    def _logits(self, wm, head_name, x):
        head = self.prior_head if head_name == "prior" else self.post_head
        g, c = self.cfg.stoch_groups, self.cfg.stoch_classes
        out = head.apply({"params": wm[head_name]}, x)
        return out.reshape(*out.shape[:-1], g, c)

    def observe(self, wm, obs_seq, action_seq, is_first_seq, key):
        """Filter a batch of sequences through the RSSM.

        obs_seq [B,T,D], action_seq [B,T,A] (a_{t-1}, i.e. the action that
        LED INTO obs_t), is_first_seq [B,T]. Returns (deter, post_logits,
        prior_logits, stoch), each [B,T,...]. One lax.scan over T.
        """
        B = obs_seq.shape[0]
        embed = self.encoder.apply({"params": wm["encoder"]}, symlog(obs_seq))
        deter0 = jnp.zeros((B, self.cfg.deter_dim), jnp.float32)
        stoch0 = jnp.zeros((B, self.stoch_dim), jnp.float32)

        def step(carry, xs):
            deter, stoch, key = carry
            emb_t, act_t, first_t = xs
            key, sub = jax.random.split(key)
            mask = (1.0 - first_t)[:, None]
            deter = deter * mask
            stoch = stoch * mask
            act_t = act_t * mask
            deter = self._seq_step(wm, deter, stoch, act_t)
            prior_logits = self._logits(wm, "prior", deter)
            post_logits = self._logits(
                wm, "post", jnp.concatenate([deter, emb_t], -1)
            )
            stoch = latent_sample(post_logits, sub)
            return (deter, stoch, key), (
                deter, post_logits, prior_logits, stoch
            )

        xs = (
            embed.transpose(1, 0, 2),
            action_seq.transpose(1, 0, 2),
            is_first_seq.transpose(1, 0).astype(jnp.float32),
        )
        _, (deter, post, prior, stoch) = jax.lax.scan(
            step, (deter0, stoch0, key), xs
        )
        to_bt = lambda x: jnp.swapaxes(x, 0, 1)  # noqa: E731
        return to_bt(deter), to_bt(post), to_bt(prior), to_bt(stoch)

    def actor_sample(self, actor_params, feat, key):
        """feat -> (action_repr, logp, entropy). Discrete: one-hot action;
        continuous: tanh-squashed sample in [-1, 1]."""
        out = self.actor.apply({"params": actor_params}, feat)
        if self.discrete:
            probs = _unimix_probs(out)
            logits = jnp.log(probs)
            idx = jax.random.categorical(key, logits)
            onehot = jax.nn.one_hot(idx, self.act_dim)
            logp = jnp.sum(onehot * logits, -1)
            entropy = -jnp.sum(probs * logits, -1)
            return onehot, logp, entropy
        mean, log_std = out
        a, logp = squashed_sample_logp(mean, log_std, key)
        entropy = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), -1)
        return a, logp, entropy

    def imagine(self, params, deter, stoch, horizon: int, key):
        """Roll the prior forward ``horizon`` steps from [N,...] start
        states, acting with the (frozen-gradient) current actor. Returns
        feats [H+1,N,F], actions/logp/entropy [H,N,...]."""
        wm = params["wm"]

        def step(carry, key_t):
            deter, stoch = carry
            ka, kz = jax.random.split(key_t)
            feat = jnp.concatenate([deter, stoch], -1)
            action, logp, ent = self.actor_sample(
                params["actor"], jax.lax.stop_gradient(feat), ka
            )
            deter = self._seq_step(wm, deter, stoch, action)
            prior_logits = self._logits(wm, "prior", deter)
            stoch = latent_sample(prior_logits, kz)
            return (deter, stoch), (feat, action, logp, ent)

        keys = jax.random.split(key, horizon)
        (deter_f, stoch_f), (feats, actions, logps, ents) = jax.lax.scan(
            step, (deter, stoch), keys
        )
        last_feat = jnp.concatenate([deter_f, stoch_f], -1)
        feats = jnp.concatenate([feats, last_feat[None]], 0)
        return feats, actions, logps, ents


# ---------------------------------------------------------------------------
# sequence replay buffer (remote actor)


class SequenceReplayBuffer:
    """Per-env-slot ring buffers of transitions; samples contiguous [B, L]
    subsequences (reference role: dreamerv3/utils/episode_replay_buffer).

    Stored fields follow the ARRIVAL convention — step t describes arriving
    at obs_t: ``action[t]`` is a_{t-1} (the action that led INTO obs_t,
    matching what ``DreamerNets.observe`` and the runner's online filter
    feed the RSSM), ``reward[t]`` the reward collected on that transition,
    ``is_terminal[t]`` whether obs_t is a true terminal state (the runner
    records the pre-auto-reset observation so the continue head sees real
    terminals), ``is_first[t]`` whether obs_t starts a fresh episode."""

    def __init__(self, capacity: int, num_slots: int, obs_dim: int,
                 act_dim: int):
        per = max(capacity // max(num_slots, 1), 1)
        self._per = per
        self._obs = np.zeros((num_slots, per, obs_dim), np.float32)
        self._act = np.zeros((num_slots, per, act_dim), np.float32)
        self._rew = np.zeros((num_slots, per), np.float32)
        self._first = np.zeros((num_slots, per), bool)
        self._term = np.zeros((num_slots, per), bool)
        self._pos = np.zeros(num_slots, np.int64)  # total appended per slot

    def add(self, slot_ids, sequences) -> int:
        """Append per-lane step sequences (dicts of [T_i, ...] arrays —
        lanes differ in length because terminal arrivals add a record);
        slot_ids maps each sequence to its buffer slot."""
        for slot, seq in zip(slot_ids, sequences):
            T = len(seq["reward"])
            if T == 0:
                continue
            # if a single append exceeds the ring, only the tail survives
            if T > self._per:
                seq = {k: v[-self._per:] for k, v in seq.items()}
                self._pos[slot] += T - self._per
                T = self._per
            idx = (self._pos[slot] + np.arange(T)) % self._per
            self._obs[slot, idx] = seq["obs"]
            self._act[slot, idx] = seq["action"]
            self._rew[slot, idx] = seq["reward"]
            self._first[slot, idx] = seq["is_first"]
            self._term[slot, idx] = seq["is_terminal"]
            self._pos[slot] += T
        return int(self.size())

    def size(self) -> int:
        return int(np.minimum(self._pos, self._per).sum())

    def sample(self, batch_size: int, seq_len: int, seed: int):
        """[B, L] contiguous subsequences; a sampled window may cross an
        episode boundary — is_first flags let the RSSM reset mid-window."""
        rng = np.random.default_rng(seed)
        fill = np.minimum(self._pos, self._per)
        ok = np.nonzero(fill >= seq_len)[0]
        if len(ok) == 0:
            return None
        out = {k: [] for k in ("obs", "action", "reward", "is_first",
                               "is_terminal")}
        for _ in range(batch_size):
            slot = int(rng.choice(ok))
            n = int(fill[slot])
            start = int(rng.integers(0, n - seq_len + 1))
            # oldest valid index in ring order
            base = self._pos[slot] % self._per if self._pos[slot] >= self._per else 0
            idx = (base + start + np.arange(seq_len)) % self._per
            out["obs"].append(self._obs[slot, idx])
            out["action"].append(self._act[slot, idx])
            out["reward"].append(self._rew[slot, idx])
            out["is_first"].append(self._first[slot, idx])
            out["is_terminal"].append(self._term[slot, idx])
        return {k: np.stack(v) for k, v in out.items()}


# ---------------------------------------------------------------------------
# rollout runner


class DreamerRunner:
    """Env-runner actor that acts from the RSSM posterior, carrying the
    latent state (deter, stoch, prev action) across steps."""

    def __init__(self, env_spec, env_config, num_envs, rollout_len, seed,
                 net_kwargs):
        factory = make_env(env_spec, env_config)
        self._vec = VectorEnv([factory for _ in range(num_envs)])
        obs_dim, act_dim, discrete = space_dims(
            self._vec.observation_space, self._vec.action_space
        )
        cfg = DreamerV3Config()
        for k, v in net_kwargs.items():
            setattr(cfg, k, v)
        self._nets = DreamerNets(cfg, obs_dim, act_dim, discrete)
        self._rollout_len = rollout_len
        self._key = jax.random.PRNGKey(seed)
        self._encode = lambda o: encode_obs(self._vec.observation_space, o)
        self._obs = self._encode(self._vec.reset(seed=seed))
        n = num_envs
        self._deter = np.zeros((n, cfg.deter_dim), np.float32)
        self._stoch = np.zeros((n, self._nets.stoch_dim), np.float32)
        self._prev_act = np.zeros((n, act_dim), np.float32)
        self._is_first = np.ones(n, bool)
        if not discrete:
            space = self._vec.action_space
            self._act_low = np.asarray(space.low, np.float32)
            self._act_high = np.asarray(space.high, np.float32)
        self._prev_rew = np.zeros(n, np.float32)
        self._ep_ret = np.zeros(n, np.float32)
        self._ep_len = np.zeros(n, np.int64)

        nets = self._nets

        def _step(params, deter, stoch, prev_act, obs, is_first, key):
            mask = (1.0 - is_first.astype(jnp.float32))[:, None]
            deter, stoch, prev_act = deter * mask, stoch * mask, prev_act * mask
            wm = params["wm"]
            deter = nets._seq_step(wm, deter, stoch, prev_act)
            embed = nets.encoder.apply(
                {"params": wm["encoder"]}, symlog(obs)
            )
            kz, ka = jax.random.split(key)
            post = nets._logits(
                wm, "post", jnp.concatenate([deter, embed], -1)
            )
            stoch = latent_sample(post, kz)
            feat = jnp.concatenate([deter, stoch], -1)
            action, _, _ = nets.actor_sample(params["actor"], feat, ka)
            return deter, stoch, action

        self._step_fn = jax.jit(_step)

    def sample(self, params) -> Dict[str, Any]:
        """Roll ``rollout_len`` steps; emit per-lane ARRIVAL-convention
        sequences (see SequenceReplayBuffer). Each env step appends one
        arrival record per lane; episode ends append a second record for
        the terminal arrival (the pre-auto-reset observation), so lane
        sequence lengths differ."""
        from .weight_sync import resolve_params

        params = resolve_params(params)
        T, n = self._rollout_len, self._vec.num_envs
        lanes: List[Dict[str, List]] = [
            {k: [] for k in ("obs", "action", "reward", "is_first",
                             "is_terminal")}
            for _ in range(n)
        ]

        def record(i, obs, action, reward, first, terminal):
            lanes[i]["obs"].append(np.asarray(obs, np.float32))
            lanes[i]["action"].append(np.asarray(action, np.float32))
            lanes[i]["reward"].append(np.float32(reward))
            lanes[i]["is_first"].append(bool(first))
            lanes[i]["is_terminal"].append(bool(terminal))

        ep_returns, ep_lengths = [], []
        for t in range(T):
            for i in range(n):  # arriving at obs_t via prev action/reward
                record(i, self._obs[i], self._prev_act[i],
                       self._prev_rew[i], self._is_first[i], False)
            self._key, sub = jax.random.split(self._key)
            deter, stoch, action = self._step_fn(
                params, self._deter, self._stoch, self._prev_act,
                self._obs.astype(np.float32), self._is_first, sub,
            )
            a = np.asarray(action)
            if self._nets.discrete:
                env_a = np.argmax(a, -1)
            else:
                env_a = self._act_low + (a + 1.0) * 0.5 * (
                    self._act_high - self._act_low
                )
            next_obs, rewards, terms, truncs = self._vec.step(env_a)
            raw = self._encode(self._vec.last_raw_obs)  # pre-reset arrivals
            dones = terms | truncs
            self._ep_ret += rewards
            self._ep_len += 1
            for i in np.nonzero(dones)[0]:
                # terminal/truncation arrival: the obs auto-reset discarded
                record(i, raw[i], a[i], rewards[i], False, terms[i])
                ep_returns.append(float(self._ep_ret[i]))
                ep_lengths.append(int(self._ep_len[i]))
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0
            self._deter, self._stoch = np.asarray(deter), np.asarray(stoch)
            self._prev_act = np.where(dones[:, None], 0.0, a).astype(
                np.float32
            )
            self._prev_rew = np.where(dones, 0.0, rewards).astype(np.float32)
            self._is_first = dones  # VectorEnv auto-resets
            self._obs = self._encode(next_obs)
        return {
            "sequences": [
                {k: np.asarray(v) for k, v in lane.items()}
                for lane in lanes
            ],
            "episode_returns": ep_returns,
            "episode_lengths": ep_lengths,
        }

    def ping(self):
        return True


# ---------------------------------------------------------------------------
# config + algorithm


class DreamerV3Config(AlgorithmConfig):
    """Builder config (reference: dreamerv3/dreamerv3.py DreamerV3Config)."""

    def __init__(self):
        super().__init__()
        self.num_env_runners = 1
        self.num_envs_per_runner = 1
        self.rollout_len = 64
        # world model
        self.deter_dim = 256
        self.stoch_groups = 16
        self.stoch_classes = 16
        self.hidden_units = 256
        self.n_bins = 41
        # training
        self.seq_len = 16
        self.batch_size = 8
        self.buffer_capacity = 100_000
        self.learning_starts = 256
        self.horizon = 15
        self.gamma = 0.997
        self.gae_lambda = 0.95
        self.world_lr = 4e-4
        self.actor_lr = 1e-4
        self.critic_lr = 1e-4
        self.entropy_coef = 3e-4
        self.free_nats = 1.0
        self.dyn_scale = 0.5
        self.rep_scale = 0.1
        self.slow_critic_decay = 0.98
        self.slow_reg_coef = 1.0
        self.retnorm_decay = 0.99
        self.grad_clip = 100.0

    def _net_kwargs(self) -> Dict[str, Any]:
        return {
            k: getattr(self, k)
            for k in ("deter_dim", "stoch_groups", "stoch_classes",
                      "hidden_units", "n_bins")
        }


class DreamerV3:
    def __init__(self, config: DreamerV3Config):
        if config.env_spec is None:
            raise ValueError("config.environment(...) is required")
        self.config = config
        self.iteration = 0
        probe = make_env(config.env_spec, config.env_config)()
        obs_dim, act_dim, discrete = space_dims(
            probe.observation_space, probe.action_space
        )
        self._obs_space = probe.observation_space
        if not discrete:
            self._act_low = np.asarray(probe.action_space.low, np.float32)
            self._act_high = np.asarray(probe.action_space.high, np.float32)
        try:
            probe.close()
        except Exception:
            pass
        self.nets = DreamerNets(config, obs_dim, act_dim, discrete)
        self.params = self.nets.init_params(jax.random.PRNGKey(config.seed))
        clip = optax.clip_by_global_norm(config.grad_clip)
        self.world_tx = optax.chain(clip, optax.adam(config.world_lr))
        self.actor_tx = optax.chain(clip, optax.adam(config.actor_lr))
        self.critic_tx = optax.chain(clip, optax.adam(config.critic_lr))
        self.opt = {
            "wm": self.world_tx.init(self.params["wm"]),
            "actor": self.actor_tx.init(self.params["actor"]),
            "critic": self.critic_tx.init(self.params["critic"]),
        }
        # EMA of the imagined-return percentile range (actor normalizer)
        self.retnorm = jnp.asarray(1.0, jnp.float32)
        self._update = jax.jit(self._update_impl)

        Buffer = api.remote(num_cpus=0)(SequenceReplayBuffer)
        total_slots = config.num_env_runners * config.num_envs_per_runner
        self.buffer = Buffer.remote(
            config.buffer_capacity, total_slots, obs_dim, act_dim
        )
        from .weight_sync import broadcaster_for

        self._broadcaster = broadcaster_for(config)
        Runner = api.remote(num_cpus=config.num_cpus_per_runner)(
            DreamerRunner
        )
        self.runners = [
            Runner.remote(
                config.env_spec, config.env_config,
                config.num_envs_per_runner, config.rollout_len,
                config.seed + 1000 * (i + 1), config._net_kwargs(),
            )
            for i in range(config.num_env_runners)
        ]
        api.get([r.ping.remote() for r in self.runners])
        self._ep_return_window: List[float] = []

    # -- the one-program update ---------------------------------------------

    def _world_loss(self, wm, batch, key):
        cfg = self.config
        nets = self.nets
        deter, post, prior, stoch = nets.observe(
            wm, batch["obs"], batch["action"], batch["is_first"], key
        )
        feat = jnp.concatenate([deter, stoch], -1)
        # prediction losses
        obs_hat = nets.decoder.apply({"params": wm["decoder"]}, feat)
        recon = jnp.sum((obs_hat - symlog(batch["obs"])) ** 2, -1)
        rew_logits = nets.reward_head.apply({"params": wm["reward"]}, feat)
        rew_loss = twohot_loss(rew_logits, batch["reward"], nets.bins)
        cont_logit = nets.cont_head.apply(
            {"params": wm["cont"]}, feat
        )[..., 0]
        cont_target = 1.0 - batch["is_terminal"].astype(jnp.float32)
        cont_loss = optax.sigmoid_binary_cross_entropy(
            cont_logit, cont_target
        )
        # KL balancing with free bits
        dyn = jnp.maximum(
            latent_kl(jax.lax.stop_gradient(post), prior), cfg.free_nats
        )
        rep = jnp.maximum(
            latent_kl(post, jax.lax.stop_gradient(prior)), cfg.free_nats
        )
        loss = jnp.mean(
            recon + rew_loss + cont_loss
            + cfg.dyn_scale * dyn + cfg.rep_scale * rep
        )
        stats = {
            "wm_loss": loss, "recon_loss": jnp.mean(recon),
            "reward_loss": jnp.mean(rew_loss),
            "cont_loss": jnp.mean(cont_loss),
            "kl_dyn": jnp.mean(dyn), "kl_rep": jnp.mean(rep),
        }
        return loss, (deter, stoch, stats)

    def _lambda_returns(self, reward, cont, value):
        """reward/cont/value [H+1, N] (index 0 = imagination start); returns
        lambda-returns [H, N] for steps 0..H-1."""
        cfg = self.config
        disc = cont * cfg.gamma

        def step(next_ret, xs):
            r, d, v_next = xs
            ret = r + d * (
                (1.0 - cfg.gae_lambda) * v_next + cfg.gae_lambda * next_ret
            )
            return ret, ret

        xs = (reward[1:], disc[1:], value[1:])
        _, rets = jax.lax.scan(
            step, value[-1], jax.tree.map(lambda x: x[::-1], xs)
        )
        return rets[::-1]

    def _update_impl(self, params, opt, retnorm, batch, key):
        cfg = self.config
        nets = self.nets
        k_wm, k_im, k_crit = jax.random.split(key, 3)

        # 1) world model step
        (_, (deter, stoch, wm_stats)), wm_grads = jax.value_and_grad(
            self._world_loss, has_aux=True
        )(params["wm"], batch, k_wm)
        wm_up, opt_wm = self.world_tx.update(
            wm_grads, opt["wm"], params["wm"]
        )
        params = {**params, "wm": optax.apply_updates(params["wm"], wm_up)}

        # 2) imagination from every posterior state (gradients cut)
        flat = lambda x: x.reshape(-1, x.shape[-1])  # noqa: E731
        start_deter = jax.lax.stop_gradient(flat(deter))
        start_stoch = jax.lax.stop_gradient(flat(stoch))

        def actor_loss_fn(actor_params):
            p = {**params, "actor": actor_params}
            feats, actions, logps, ents = nets.imagine(
                p, start_deter, start_stoch, cfg.horizon, k_im
            )
            wm = params["wm"]
            reward = twohot_decode(
                nets.reward_head.apply({"params": wm["reward"]}, feats),
                nets.bins,
            )
            cont = jax.nn.sigmoid(
                nets.cont_head.apply({"params": wm["cont"]}, feats)[..., 0]
            )
            value = twohot_decode(
                nets.critic.apply({"params": params["critic"]}, feats),
                nets.bins,
            )
            rets = self._lambda_returns(reward, cont, value)  # [H, N]
            # imagined-trajectory weights: product of predicted continues
            weight = jnp.cumprod(
                jnp.concatenate([jnp.ones_like(cont[:1]), cont[:-1]], 0), 0
            )[: cfg.horizon]
            weight = jax.lax.stop_gradient(weight)
            # percentile return normalization: fold this batch's 5..95
            # range into the EMA, divide by the SMOOTHED scale (per-batch
            # percentiles alone are too noisy at small batch sizes)
            batch_range = jax.lax.stop_gradient(
                jnp.percentile(rets, 95) - jnp.percentile(rets, 5)
            )
            new_retnorm = (
                cfg.retnorm_decay * retnorm
                + (1.0 - cfg.retnorm_decay) * batch_range
            )
            scale = jnp.maximum(new_retnorm, 1.0)
            adv = (rets - value[: cfg.horizon]) / scale
            loss = -jnp.mean(
                weight * (
                    jax.lax.stop_gradient(adv) * logps
                    + cfg.entropy_coef * ents
                )
            )
            aux = (feats, rets, weight, new_retnorm,
                   jnp.mean(ents), jnp.mean(rets))
            return loss, aux

        (actor_loss, aux), actor_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(params["actor"])
        feats, rets, weight, retnorm, ent_mean, ret_mean = aux
        a_up, opt_actor = self.actor_tx.update(
            actor_grads, opt["actor"], params["actor"]
        )
        params = {
            **params, "actor": optax.apply_updates(params["actor"], a_up)
        }

        # 3) critic step: two-hot CE to lambda returns + slow-critic reg
        feats_sg = jax.lax.stop_gradient(feats[: cfg.horizon])
        rets_sg = jax.lax.stop_gradient(rets)

        def critic_loss_fn(critic_params):
            logits = nets.critic.apply({"params": critic_params}, feats_sg)
            ce = twohot_loss(logits, rets_sg, nets.bins)
            slow_logits = nets.critic.apply(
                {"params": params["slow_critic"]}, feats_sg
            )
            slow_probs = jax.lax.stop_gradient(jax.nn.softmax(slow_logits))
            reg = -jnp.sum(slow_probs * jax.nn.log_softmax(logits), -1)
            return jnp.mean(weight * (ce + cfg.slow_reg_coef * reg))

        critic_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic"]
        )
        c_up, opt_critic = self.critic_tx.update(
            critic_grads, opt["critic"], params["critic"]
        )
        params = {
            **params, "critic": optax.apply_updates(params["critic"], c_up)
        }
        d = cfg.slow_critic_decay
        params = {
            **params,
            "slow_critic": jax.tree.map(
                lambda s, c: d * s + (1.0 - d) * c,
                params["slow_critic"], params["critic"],
            ),
        }
        opt = {"wm": opt_wm, "actor": opt_actor, "critic": opt_critic}
        stats = {
            **wm_stats,
            "actor_loss": actor_loss, "critic_loss": critic_loss,
            "actor_entropy": ent_mean, "imagined_return_mean": ret_mean,
            "return_scale": retnorm,
        }
        return params, opt, retnorm, stats

    # -- training loop -------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        cfg = self.config
        # runners only act: ship wm + actor, not the critic heads
        host_params = jax.tree.map(
            np.asarray,
            {"wm": self.params["wm"], "actor": self.params["actor"]},
        )
        params_handle = self._broadcaster.handle(host_params)
        rollouts = api.get(
            [r.sample.remote(params_handle) for r in self.runners]
        )
        adds, ep_returns = [], []
        for i, ro in enumerate(rollouts):
            slots = list(range(
                i * cfg.num_envs_per_runner,
                (i + 1) * cfg.num_envs_per_runner,
            ))
            adds.append(self.buffer.add.remote(slots, ro["sequences"]))
            ep_returns.extend(ro["episode_returns"])
        buffer_size = api.get(adds)[-1]

        stats: Dict[str, float] = {}
        if buffer_size >= cfg.learning_starts:
            batch = api.get(self.buffer.sample.remote(
                cfg.batch_size, cfg.seq_len,
                seed=cfg.seed + self.iteration * 997,
            ))
            if batch is not None:
                jb = {k: jnp.asarray(v) for k, v in batch.items()}
                self.params, self.opt, self.retnorm, jstats = self._update(
                    self.params, self.opt, self.retnorm, jb,
                    jax.random.PRNGKey(cfg.seed + self.iteration),
                )
                stats = {k: float(v) for k, v in jstats.items()}

        self.iteration += 1
        self._ep_return_window.extend(ep_returns)
        self._ep_return_window = self._ep_return_window[-100:]
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(self._ep_return_window))
                if self._ep_return_window else float("nan")
            ),
            "num_episodes": len(ep_returns),
            "buffer_size": buffer_size,
            "num_env_steps_sampled": sum(
                len(seq["reward"])
                for ro in rollouts for seq in ro["sequences"]
            ),
            "time_this_iter_s": time.time() - t0,
            **stats,
        }

    # -- checkpointing / inference ------------------------------------------

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        with open(
            os.path.join(checkpoint_dir, "dreamer_state.pkl"), "wb"
        ) as f:
            pickle.dump({
                "params": jax.tree.map(np.asarray, self.params),
                "retnorm": float(self.retnorm),
                "iteration": self.iteration,
            }, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        with open(
            os.path.join(checkpoint_dir, "dreamer_state.pkl"), "rb"
        ) as f:
            saved = pickle.load(f)
        self.params = jax.tree.map(jnp.asarray, saved["params"])
        self.retnorm = jnp.asarray(saved["retnorm"], jnp.float32)
        self.opt = {
            "wm": self.world_tx.init(self.params["wm"]),
            "actor": self.actor_tx.init(self.params["actor"]),
            "critic": self.critic_tx.init(self.params["critic"]),
        }
        self.iteration = saved["iteration"]

    def compute_single_action(self, obs):
        """One-step filter from an empty latent state (no carried context;
        for sustained rollouts use a DreamerRunner, which carries state)."""
        nets = self.nets
        obs = encode_obs(self._obs_space, np.asarray(obs)[None])
        wm = self.params["wm"]
        deter = jnp.zeros((1, self.config.deter_dim), jnp.float32)
        stoch = jnp.zeros((1, nets.stoch_dim), jnp.float32)
        act0 = jnp.zeros((1, nets.act_dim), jnp.float32)
        deter = nets._seq_step(wm, deter, stoch, act0)
        embed = nets.encoder.apply(
            {"params": wm["encoder"]}, symlog(jnp.asarray(obs))
        )
        post = nets._logits(
            wm, "post", jnp.concatenate([deter, embed], -1)
        )
        stoch = latent_sample(post, jax.random.PRNGKey(0))
        feat = jnp.concatenate([deter, stoch], -1)
        out = nets.actor.apply({"params": self.params["actor"]}, feat)
        if nets.discrete:
            return int(jnp.argmax(out, -1)[0])
        mean, _ = out
        a = np.asarray(jnp.tanh(mean))[0]
        # same [-1,1] -> Box rescaling the rollout runners apply
        return self._act_low + (a + 1.0) * 0.5 * (
            self._act_high - self._act_low
        )

    def stop(self):
        for r in self.runners:
            try:
                api.kill(r)
            except Exception:
                pass
        try:
            api.kill(self.buffer)
        except Exception:
            pass
        self.runners = []


DreamerV3Config.algo_class = DreamerV3
