"""Learner→env-runner weight sync.

Every algorithm's train loop used to pass the raw params pytree inline to
``runner.sample.remote(params)`` — re-serializing the full model once PER
RUNNER per iteration, so publisher-side work scaled O(runners × model
size). ``ParamsBroadcaster`` collapses that to once per iteration:

- default mode: ``api.put`` the params once and hand every runner the
  ObjectRef (executors resolve top-level refs through the object plane, so
  runner code is unchanged);
- weight-plane mode (``config.use_weight_plane``): publish one version via
  ``ray_tpu.weights`` and hand runners a tiny ``WeightHandle`` — runners
  fetch over the binomial broadcast tree (publisher upload O(1) in
  subscriber-node count) with per-node chunk dedup; ``resolve_params`` at
  the top of each runner's ``sample`` turns the handle back into the tree.

The cache key is object identity: learners produce a fresh params object
per update (jit outputs), so an unchanged policy between iterations reuses
the previous ref/version and a changed one re-broadcasts exactly once.
"""

from __future__ import annotations

from typing import Any, Optional


class ParamsBroadcaster:
    def __init__(
        self,
        use_weight_plane: bool = False,
        name: Optional[str] = None,
        quantized: bool = False,
    ):
        self._use_weight_plane = use_weight_plane
        self._name = name or "rllib/params"
        # int8 chunk codec on weight-plane publishes — the broadcast tree
        # moves the compressed form; no effect in ObjectRef mode
        self._quantized = quantized
        self._cached: Any = None
        self._handle: Any = None

    def handle(self, params: Any):
        """The task-arg stand-in for ``params``: ObjectRef or WeightHandle,
        minted at most once per distinct params object."""
        if params is self._cached and self._handle is not None:
            return self._handle
        if self._use_weight_plane:
            from .. import weights

            self._handle = weights.publish(
                self._name, params, quantized=self._quantized
            )
        else:
            from .. import api

            self._handle = api.put(params)
        self._cached = params
        return self._handle

    def invalidate(self):
        """Forget the cache (e.g. params mutated in place)."""
        self._cached = None
        self._handle = None


def broadcaster_for(config) -> ParamsBroadcaster:
    """Build from an AlgorithmConfig's weight-sync fields."""
    return ParamsBroadcaster(
        use_weight_plane=getattr(config, "use_weight_plane", False),
        name=getattr(config, "weight_plane_name", None)
        or f"rllib/{type(config).__name__.removesuffix('Config').lower()}",
        quantized=getattr(config, "quantized_weight_sync", False),
    )


def grad_scheduler_for(config, group):
    """Learner-side gradient scheduler from an AlgorithmConfig's
    weight-sync fields, mirroring ``broadcaster_for``: ``group`` is the
    learner gang's collective group (any BaseGroup backend). With
    ``overlap_grad_sync`` off the scheduler still bucketizes but blocks
    per bucket — call surface identical, A/B by config alone."""
    from ..collective.bucketizer import DEFAULT_BUCKET_BYTES
    from ..collective.scheduler import GradientReduceScheduler

    return GradientReduceScheduler(
        group,
        bucket_bytes=getattr(config, "grad_sync_bucket_bytes", None)
        or DEFAULT_BUCKET_BYTES,
        overlap=getattr(config, "overlap_grad_sync", False),
    )


def resolve_params(params: Any) -> Any:
    """Runner-side inverse of ``ParamsBroadcaster.handle`` for the
    weight-plane mode: a WeightHandle fetches its pinned version over the
    broadcast tree; anything else (resolved ObjectRef values arrive as the
    plain pytree) passes through."""
    from ..weights import WeightHandle, resolve

    if isinstance(params, WeightHandle):
        return resolve(params)
    return params
