"""Environment interface + vectorization.

Role-equivalent of the reference's env layer (rllib/env/ — gymnasium-based
single-agent envs wrapped for vector rollout, env/single_agent_env_runner.py
builds a gymnasium vector env). Envs follow the gymnasium 5-tuple step API;
``make_env`` accepts a gymnasium id string or an env-factory callable.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import numpy as np


class VectorEnv:
    """N independent env copies stepped together (autoreset on episode end,
    matching gymnasium's vector semantics)."""

    def __init__(self, env_fns: List[Callable[[], Any]]):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        first = self.envs[0]
        self.observation_space = first.observation_space
        self.action_space = first.action_space

    def reset(self, seed: Optional[int] = None):
        obs = []
        for i, env in enumerate(self.envs):
            o, _ = env.reset(seed=None if seed is None else seed + i)
            obs.append(o)
        return np.stack(obs)

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Returns (obs, rewards, terminateds, truncateds); terminated/
        truncated envs are reset and their next obs replaces the terminal
        one (the terminal obs is not needed by PPO's bootstrap because
        value targets cut at dones)."""
        obs, rewards, terms, truncs, raw = [], [], [], [], []
        for env, a in zip(self.envs, actions):
            o, r, term, trunc, _ = env.step(a)
            raw.append(o)  # pre-reset: the TRUE arrival obs, terminal or not
            if term or trunc:
                o, _ = env.reset()
            obs.append(o)
            rewards.append(r)
            terms.append(term)
            truncs.append(trunc)
        # model-based learners (DreamerV3's continue head) need the terminal
        # observation that auto-reset otherwise discards
        self.last_raw_obs = np.stack(raw)
        return (
            np.stack(obs),
            np.asarray(rewards, np.float32),
            np.asarray(terms),
            np.asarray(truncs),
        )

    def close(self):
        for env in self.envs:
            try:
                env.close()
            except Exception:
                pass


def make_env(env: Union[str, Callable[[], Any]], env_config: Optional[dict] = None):
    """Factory-of-factories: returns a zero-arg callable building one env."""
    if callable(env):
        cfg = dict(env_config or {})
        return lambda: env(cfg) if _wants_config(env) else env()
    if isinstance(env, str):
        def _make():
            import gymnasium as gym

            return gym.make(env, **(env_config or {}))

        return _make
    raise TypeError(f"env must be a gymnasium id or callable, got {type(env)}")


def _wants_config(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) >= 1


def encode_obs(observation_space, obs: np.ndarray) -> np.ndarray:
    """Batch of raw observations -> float32 feature matrix [N, obs_dim]
    (Discrete obs are one-hot encoded to match space_dims' obs_dim=n)."""
    import gymnasium as gym

    if isinstance(observation_space, gym.spaces.Discrete):
        n = int(observation_space.n)
        idx = np.asarray(obs).astype(np.int64).reshape(-1)
        out = np.zeros((len(idx), n), np.float32)
        out[np.arange(len(idx)), idx] = 1.0
        return out
    return np.asarray(obs, np.float32).reshape(len(obs), -1)


def space_dims(observation_space, action_space) -> Tuple[int, int, bool]:
    """(obs_dim, action_dim, discrete) from gymnasium spaces."""
    import gymnasium as gym

    if isinstance(observation_space, gym.spaces.Box):
        obs_dim = int(np.prod(observation_space.shape))
    elif isinstance(observation_space, gym.spaces.Discrete):
        obs_dim = int(observation_space.n)
    else:
        raise ValueError(f"unsupported obs space {observation_space}")
    if isinstance(action_space, gym.spaces.Discrete):
        return obs_dim, int(action_space.n), True
    if isinstance(action_space, gym.spaces.Box):
        return obs_dim, int(np.prod(action_space.shape)), False
    raise ValueError(f"unsupported action space {action_space}")
