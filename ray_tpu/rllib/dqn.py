"""DQN: off-policy Q-learning with a replay-buffer actor.

Role-equivalent of the reference's DQN family (rllib/algorithms/dqn/ —
DQNConfig, EpisodeReplayBuffer, target network): epsilon-greedy rollout
actors feed a replay-buffer actor; the driver-side learner runs jitted
double-DQN updates (one ``lax.scan`` over the whole train batch of
minibatches per iteration — a single compiled program on the MXU) and
periodically syncs the target network.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import api
from .config_base import AlgorithmConfig
from .env import VectorEnv, encode_obs, make_env, space_dims
from .models import MLP_HIDDEN, QNetwork


class ReplayBuffer:
    """Uniform ring-buffer replay (reference:
    rllib/utils/replay_buffers/replay_buffer.py). Runs as an actor so many
    runners share one buffer. Actions default to discrete scalars; pass
    ``act_shape``/``act_dtype`` for continuous vectors (SAC)."""

    def __init__(self, capacity: int, obs_dim: int, act_shape: tuple = (),
                 act_dtype=np.int64):
        self._capacity = capacity
        self._obs = np.zeros((capacity, obs_dim), np.float32)
        self._next_obs = np.zeros((capacity, obs_dim), np.float32)
        self._actions = np.zeros((capacity,) + tuple(act_shape), act_dtype)
        self._rewards = np.zeros((capacity,), np.float32)
        self._dones = np.zeros((capacity,), np.float32)
        self._idx = 0
        self._size = 0

    def add(self, obs, actions, rewards, next_obs, dones):
        n = len(rewards)
        # vectorized ring write: at most two contiguous slices
        pos = (self._idx + np.arange(n)) % self._capacity
        self._obs[pos] = obs[:n]
        self._next_obs[pos] = next_obs[:n]
        self._actions[pos] = actions[:n]
        self._rewards[pos] = rewards[:n]
        self._dones[pos] = dones[:n]
        self._idx = int((self._idx + n) % self._capacity)
        self._size = int(min(self._size + n, self._capacity))
        return self._size

    def _gather(self, idx):
        return {
            "obs": self._obs[idx],
            "actions": self._actions[idx],
            "rewards": self._rewards[idx],
            "next_obs": self._next_obs[idx],
            "dones": self._dones[idx],
        }

    def sample(self, batch_size: int, seed: int = 0):
        idx = np.random.default_rng(seed).integers(0, self._size, batch_size)
        return self._gather(idx)

    def sample_many(self, batch_size: int, n_batches: int, seed: int = 0):
        """n_batches stacked minibatches in one RPC — feeds a jitted
        lax.scan over updates without per-batch object-store round trips."""
        rng = np.random.default_rng(seed)
        idx = rng.integers(0, self._size, (n_batches, batch_size))
        return self._gather(idx)

    def size(self) -> int:
        return self._size


class DQNRunner:
    """Epsilon-greedy rollout actor (reference: DQN EnvRunner with
    EpsilonGreedy exploration)."""

    def __init__(self, env_spec, env_config, num_envs, rollout_len, seed):
        env_fn = make_env(env_spec, env_config)
        self._env = VectorEnv([env_fn for _ in range(num_envs)])
        self._obs_space = self._env.envs[0].observation_space
        self._rollout_len = rollout_len
        self._rng = np.random.default_rng(seed)
        self._obs = self._env.reset(seed=seed)
        self._model: Optional[QNetwork] = None
        self._ep_ret = np.zeros(num_envs)
        self._ep_len = np.zeros(num_envs, np.int64)

    def sample(self, params, epsilon: float) -> Dict[str, Any]:
        import jax.numpy as jnp

        from .weight_sync import resolve_params

        params = resolve_params(params)
        if self._model is None:
            obs_dim, act_dim, _ = space_dims(
                self._obs_space, self._env.envs[0].action_space
            )
            self._model = QNetwork(act_dim)
        out: Dict[str, List] = {
            "obs": [], "actions": [], "rewards": [], "next_obs": [],
            "dones": [],
        }
        ep_returns, ep_lengths = [], []
        for _ in range(self._rollout_len):
            enc = encode_obs(self._obs_space, self._obs)
            q = np.asarray(
                self._model.apply({"params": params}, jnp.asarray(enc))
            )
            greedy = q.argmax(axis=-1)
            random_a = self._rng.integers(0, q.shape[-1], len(greedy))
            explore = self._rng.random(len(greedy)) < epsilon
            actions = np.where(explore, random_a, greedy)
            next_obs, rewards, dones, _infos = self._env.step(actions)
            next_enc = encode_obs(self._obs_space, next_obs)
            out["obs"].append(enc)
            out["actions"].append(actions)
            out["rewards"].append(rewards)
            out["next_obs"].append(next_enc)
            out["dones"].append(dones.astype(np.float32))
            self._ep_ret += rewards
            self._ep_len += 1
            for i, d in enumerate(dones):
                if d:
                    ep_returns.append(float(self._ep_ret[i]))
                    ep_lengths.append(int(self._ep_len[i]))
                    self._ep_ret[i] = 0.0
                    self._ep_len[i] = 0
            self._obs = next_obs
        return {
            "obs": np.concatenate(out["obs"]),
            "actions": np.concatenate(out["actions"]),
            "rewards": np.concatenate(out["rewards"]),
            "next_obs": np.concatenate(out["next_obs"]),
            "dones": np.concatenate(out["dones"]),
            "episode_returns": ep_returns,
            "episode_lengths": ep_lengths,
        }

    def ping(self):
        return True


class DQNConfig(AlgorithmConfig):
    """Builder config (reference: dqn/dqn.py DQNConfig)."""

    def __init__(self):
        super().__init__()
        self.gamma = 0.99
        self.lr = 1e-3
        self.buffer_capacity = 100_000
        self.learning_starts = 500
        self.train_batch_size = 64
        self.num_updates_per_iter = 16
        self.target_update_freq = 4  # iterations between target syncs
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_iters = 50
        self.double_q = True


class DQN:
    def __init__(self, config: DQNConfig):
        if config.env_spec is None:
            raise ValueError("config.environment(...) is required")
        self.config = config
        self.iteration = 0
        probe = make_env(config.env_spec, config.env_config)()
        obs_dim, act_dim, discrete = space_dims(
            probe.observation_space, probe.action_space
        )
        if not discrete:
            raise ValueError("DQN requires a discrete action space")
        try:
            probe.close()
        except Exception:
            pass
        self._obs_dim, self._act_dim = obs_dim, act_dim

        self.model = QNetwork(act_dim)
        key = jax.random.PRNGKey(config.seed)
        self.params = self.model.init(key, jnp.zeros((1, obs_dim)))["params"]
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self._update = jax.jit(self._update_impl)

        Buffer = api.remote(num_cpus=0)(ReplayBuffer)
        self.buffer = Buffer.remote(config.buffer_capacity, obs_dim)
        from .weight_sync import broadcaster_for

        self._broadcaster = broadcaster_for(config)
        Runner = api.remote(num_cpus=config.num_cpus_per_runner)(DQNRunner)
        self.runners = [
            Runner.remote(
                config.env_spec, config.env_config,
                config.num_envs_per_runner, config.rollout_len,
                config.seed + 1000 * (i + 1),
            )
            for i in range(config.num_env_runners)
        ]
        api.get([r.ping.remote() for r in self.runners])
        self._ep_return_window: List[float] = []

    # -- jitted learner ------------------------------------------------------

    def _update_impl(self, params, target_params, opt_state, batch):
        cfg = self.config

        def loss_fn(p):
            q = self.model.apply({"params": p}, batch["obs"])
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=-1
            )[:, 0]
            q_next_target = self.model.apply(
                {"params": target_params}, batch["next_obs"]
            )
            if cfg.double_q:
                q_next_online = self.model.apply(
                    {"params": p}, batch["next_obs"]
                )
                best = jnp.argmax(q_next_online, axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_target, best[:, None], axis=-1
                )[:, 0]
            else:
                q_next = jnp.max(q_next_target, axis=-1)
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) * (
                jax.lax.stop_gradient(q_next)
            )
            td = q_sel - target
            return jnp.mean(td * td)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(self.iteration / max(cfg.epsilon_decay_iters, 1), 1.0)
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial
        )

    # -- training loop -------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        cfg = self.config
        eps = self._epsilon()
        params_handle = self._broadcaster.handle(self.params)
        rollouts = api.get(
            [r.sample.remote(params_handle, eps) for r in self.runners]
        )
        adds = []
        ep_returns, ep_lengths = [], []
        for ro in rollouts:
            adds.append(
                self.buffer.add.remote(
                    ro["obs"], ro["actions"], ro["rewards"],
                    ro["next_obs"], ro["dones"],
                )
            )
            ep_returns.extend(ro["episode_returns"])
            ep_lengths.extend(ro["episode_lengths"])
        buffer_size = api.get(adds)[-1]

        losses = []
        if buffer_size >= cfg.learning_starts:
            batches = api.get(
                [
                    self.buffer.sample.remote(
                        cfg.train_batch_size,
                        seed=cfg.seed + self.iteration * 997 + u,
                    )
                    for u in range(cfg.num_updates_per_iter)
                ]
            )
            for b in batches:
                jb = {k: jnp.asarray(v) for k, v in b.items()}
                self.params, self.opt_state, loss = self._update(
                    self.params, self.target_params, self.opt_state, jb
                )
                losses.append(float(loss))
        if self.iteration % max(cfg.target_update_freq, 1) == 0:
            self.target_params = jax.tree.map(jnp.copy, self.params)

        self.iteration += 1
        self._ep_return_window.extend(ep_returns)
        self._ep_return_window = self._ep_return_window[-100:]
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(self._ep_return_window))
                if self._ep_return_window else float("nan")
            ),
            "num_episodes": len(ep_returns),
            "buffer_size": buffer_size,
            "epsilon": eps,
            "loss_mean": float(np.mean(losses)) if losses else float("nan"),
            "num_env_steps_sampled": sum(
                len(ro["rewards"]) for ro in rollouts
            ),
            "time_this_iter_s": time.time() - t0,
        }

    # -- checkpointing -------------------------------------------------------

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        with open(os.path.join(checkpoint_dir, "dqn_state.pkl"), "wb") as f:
            pickle.dump(
                {
                    "params": jax.tree.map(np.asarray, self.params),
                    "target_params": jax.tree.map(
                        np.asarray, self.target_params
                    ),
                    "iteration": self.iteration,
                },
                f,
            )
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        with open(os.path.join(checkpoint_dir, "dqn_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.target_params = jax.tree.map(
            jnp.asarray, state["target_params"]
        )
        self.opt_state = self.tx.init(self.params)
        self.iteration = state["iteration"]

    def compute_single_action(self, obs):
        from .env import encode_obs as enc

        probe_space = None
        q = self.model.apply(
            {"params": self.params},
            jnp.asarray(np.asarray(obs, np.float32)[None]),
        )
        return int(np.asarray(jnp.argmax(q, axis=-1))[0])

    def stop(self):
        for r in self.runners:
            try:
                api.kill(r)
            except Exception:
                pass
        try:
            api.kill(self.buffer)
        except Exception:
            pass
        self.runners = []


DQNConfig.algo_class = DQN
