"""EnvRunner actor: collects rollouts with the current policy.

Role-equivalent of the reference's SingleAgentEnvRunner
(rllib/env/single_agent_env_runner.py:68) inside an EnvRunnerGroup
(env/env_runner_group.py:70): each runner holds a vector of env copies and
a CPU copy of the policy; ``sample(params)`` steps ``rollout_len`` times
and returns [T, N] trajectory arrays. Runners are plain actors, so CPU
rollout actors coexist with TPU learners in one cluster — the split the
reference achieves with CPU workers + GPU learner group.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class EnvRunner:
    def __init__(
        self,
        env_spec,
        env_config: Optional[dict],
        num_envs: int,
        rollout_len: int,
        seed: int,
        env_to_module_connector=None,
        module_to_env_connector=None,
    ):
        import jax

        from .connectors import (
            ConnectorContext,
            default_env_to_module,
            default_module_to_env,
        )
        from .env import VectorEnv, make_env, space_dims
        from .models import init_actor_critic, sample_actions

        factory = make_env(env_spec, env_config)
        self._vec = VectorEnv([factory for _ in range(num_envs)])
        self._rollout_len = rollout_len
        obs_dim, act_dim, discrete = space_dims(
            self._vec.observation_space, self._vec.action_space
        )
        self._model, _ = init_actor_critic(obs_dim, act_dim, discrete, seed)
        self._key = jax.random.PRNGKey(seed)
        # connector pipelines (reference: connector_pipeline_v2): factories
        # so per-runner stateful connectors (e.g. running normalizers) are
        # never shared across processes
        self._ctx = ConnectorContext(
            self._vec.observation_space, self._vec.action_space
        )
        self._env_to_module = (
            env_to_module_connector() if env_to_module_connector
            else default_env_to_module()
        )
        self._module_to_env = (
            module_to_env_connector() if module_to_env_connector
            else default_module_to_env()
        )
        self._encode = lambda o: np.asarray(
            self._env_to_module(o, self._ctx), np.float32
        )
        self._obs = self._encode(self._vec.reset(seed=seed))
        self._discrete = discrete
        # episode-return bookkeeping
        self._ep_returns = np.zeros(num_envs, np.float32)
        self._ep_lengths = np.zeros(num_envs, np.int64)
        self._completed: list = []
        self._sample_fn = jax.jit(
            lambda params, obs, key: sample_actions(
                self._model, params, obs, key
            )
        )

    def sample(self, params) -> Dict[str, Any]:
        """Roll ``rollout_len`` steps; returns [T, N] arrays + last values
        for bootstrap + episode stats. ``params`` may be the pytree itself
        (inline or via ObjectRef) or a weight-plane WeightHandle — resolved
        here so the learner chooses the sync transport, not the runner."""
        import jax

        from .weight_sync import resolve_params

        params = resolve_params(params)
        T, N = self._rollout_len, self._vec.num_envs
        obs_buf = np.zeros((T, N) + self._obs.shape[1:], np.float32)
        act_buf = None
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), bool)
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            actions, logp, values = self._sample_fn(
                params, self._obs.astype(np.float32), sub
            )
            actions = np.asarray(actions)
            obs_buf[t] = self._obs
            if act_buf is None:
                act_buf = np.zeros((T, N) + actions.shape[1:], actions.dtype)
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(values)
            env_actions = self._module_to_env(actions, self._ctx)
            next_obs, rewards, terms, truncs = self._vec.step(env_actions)
            next_obs = self._encode(next_obs)
            dones = terms | truncs
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._ep_returns += rewards
            self._ep_lengths += 1
            for i in np.nonzero(dones)[0]:
                self._completed.append(
                    (float(self._ep_returns[i]), int(self._ep_lengths[i]))
                )
                self._ep_returns[i] = 0.0
                self._ep_lengths[i] = 0
            self._obs = next_obs
        _, _, last_values = self._sample_fn(
            params, self._obs.astype(np.float32), self._key
        )
        completed, self._completed = self._completed, []
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "last_values": np.asarray(last_values),
            "episode_returns": [r for r, _ in completed],
            "episode_lengths": [l for _, l in completed],
        }

    def ping(self):
        return True

    def stop(self):
        self._vec.close()
        return True
