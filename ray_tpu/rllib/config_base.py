"""Shared AlgorithmConfig builder base.

Role-equivalent of the reference's AlgorithmConfig
(rllib/algorithms/algorithm_config.py): the fluent builder surface
(environment / env_runners / training / resources / debugging / build)
shared by every algorithm config, with per-algorithm defaults and algo
classes supplied by subclasses.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Union


class AlgorithmConfig:
    #: subclass hook: the Algorithm class ``build()`` instantiates
    algo_class: Any = None

    def __init__(self):
        self.env_spec: Union[str, Callable, None] = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 2
        self.num_envs_per_runner = 2
        self.rollout_len = 32
        self.seed = 0
        self.num_cpus_per_runner = 1.0
        self.num_tpus_for_learner = 0.0
        self.env_to_module_connector = None
        self.module_to_env_connector = None
        # learner→env-runner weight sync (see rllib/weight_sync.py): False =
        # api.put once per iteration + ObjectRef task args; True = publish
        # through ray_tpu.weights and hand runners a WeightHandle (binomial
        # broadcast tree, per-node chunk dedup, versioned registry)
        self.use_weight_plane = False
        self.weight_plane_name: Optional[str] = None
        # int8 chunk codec for weight-plane publishes: every broadcast-tree
        # hop carries ~4x (f32) / ~2x (bf16) fewer bytes; runners dequantize
        # at assembly. Policy weights tolerate the ~0.4% per-block rounding
        # (acting is already stochastic); only meaningful with
        # use_weight_plane=True
        self.quantized_weight_sync = False
        # overlapped learner-group gradient sync (collective/scheduler.py):
        # multi-learner setups reduce gradients through the bucketized
        # async path so the reduce hides under remaining backward compute;
        # bucket_bytes tunes the dispatch-overhead/overlap tradeoff
        self.overlap_grad_sync = False
        self.grad_sync_bucket_bytes: Optional[int] = None

    def environment(self, env, env_config: Optional[dict] = None):
        self.env_spec = env
        self.env_config = dict(env_config or {})
        return self

    def env_runners(
        self,
        num_env_runners: Optional[int] = None,
        num_envs_per_env_runner: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
        num_cpus_per_env_runner: Optional[float] = None,
        env_to_module_connector=None,
        module_to_env_connector=None,
    ):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_len = rollout_fragment_length
        if num_cpus_per_env_runner is not None:
            self.num_cpus_per_runner = num_cpus_per_env_runner
        # zero-arg factories returning a Connector/ConnectorPipeline
        # (reference: config.env_runners(env_to_module_connector=...)) —
        # factories, not instances, so stateful connectors stay per-runner
        if env_to_module_connector is not None or module_to_env_connector is not None:
            if not getattr(self, "supports_connectors", False):
                raise NotImplementedError(
                    f"{type(self).__name__} runners do not consume connector "
                    "pipelines yet (PPO/MultiAgentPPO do); configuring one "
                    "here would be silently dropped"
                )
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def resources(self, num_tpus_for_learner: float = 0):
        self.num_tpus_for_learner = num_tpus_for_learner
        return self

    def weight_sync(
        self,
        use_weight_plane: Optional[bool] = None,
        weight_plane_name: Optional[str] = None,
        quantized: Optional[bool] = None,
        overlap: Optional[bool] = None,
        bucket_bytes: Optional[int] = None,
    ):
        """Configure how fresh params reach env-runners each iteration.
        ``quantized=True`` publishes versions with the int8 chunk codec
        (compressed broadcast; see weights/manifest.py). ``overlap=True``
        routes multi-learner gradient reduction through the bucketized
        async scheduler (``bucket_bytes`` sizes the buckets; see
        rllib/weight_sync.py grad_scheduler_for)."""
        if use_weight_plane is not None:
            self.use_weight_plane = use_weight_plane
        if weight_plane_name is not None:
            self.weight_plane_name = weight_plane_name
        if quantized is not None:
            self.quantized_weight_sync = quantized
        if overlap is not None:
            self.overlap_grad_sync = overlap
        if bucket_bytes is not None:
            self.grad_sync_bucket_bytes = bucket_bytes
        return self

    def debugging(self, seed: Optional[int] = None):
        if seed is not None:
            self.seed = seed
        return self

    def build(self):
        if self.algo_class is None:
            raise NotImplementedError(f"{type(self).__name__}.algo_class unset")
        return self.algo_class(copy.deepcopy(self))

    # legacy alias used by reference examples
    build_algo = build
