"""Multi-agent PPO: per-policy module dict over a MultiAgentEnv.

Role-equivalent of the reference's multi-agent stack (MultiAgentEnv +
MultiAgentRLModuleSpec + per-module learner updates): agents map to policies
via ``policy_mapping_fn``; each policy owns one ActorCritic module + one
optimizer state; agents sharing a policy train it with their pooled
experience (parameter sharing), separate policies update independently —
each policy's epoch loop is the same single jitted lax.scan program the
single-agent learner runs.

Rollout layout: simultaneous-move envs (multi_agent_env.py contract) give
rectangular per-policy arrays [T, n_agents_of_policy], which reuse the
single-agent GAE and minibatch machinery unchanged.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import api
from .algorithm import PPOConfig, gae_batch
from .connectors import (
    ConnectorContext,
    default_env_to_module,
    default_module_to_env,
)
from .env import space_dims
from .learner import PPOLearner
from .multi_agent_env import episode_done


class MultiAgentPPOConfig(PPOConfig):
    """PPOConfig + .multi_agent(policies, policy_mapping_fn)."""

    def __init__(self):
        super().__init__()
        self.policies: List[str] = []
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None

    def multi_agent(
        self,
        policies: List[str],
        policy_mapping_fn: Callable[[str], str],
    ):
        """``policies``: policy ids; ``policy_mapping_fn(agent_id) ->
        policy_id`` (reference: AlgorithmConfig.multi_agent)."""
        self.policies = list(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentEnvRunner:
    """Rollout actor over one MultiAgentEnv instance (reference:
    MultiAgentEnvRunner, rllib/env/multi_agent_env_runner.py): steps the env
    dict-wise, batching each policy's agents through that policy's module."""

    def __init__(
        self,
        env_spec,
        env_config: Optional[dict],
        policies: List[str],
        mapping_items: List,
        rollout_len: int,
        seed: int,
        env_to_module_connector=None,
        module_to_env_connector=None,
    ):
        import jax

        from .env import make_env
        from .models import init_actor_critic, sample_actions

        self._env = make_env(env_spec, env_config)()
        self._agents = list(self._env.possible_agents)
        mapping = dict(mapping_items)
        self._policy_of = {a: mapping[a] for a in self._agents}
        # stable per-policy agent ordering -> rectangular [T, nA] buffers
        self._agents_of = {
            pid: [a for a in self._agents if self._policy_of[a] == pid]
            for pid in policies
        }
        self._rollout_len = rollout_len
        self._key = jax.random.PRNGKey(seed)
        self._models = {}
        self._ctxs = {}
        self._sample_fns = {}
        self._e2m = {}
        self._m2e = {}
        for pid in policies:
            agents = self._agents_of[pid]
            if not agents:
                continue
            obs_space = self._env.observation_space(agents[0])
            act_space = self._env.action_space(agents[0])
            obs_dim, act_dim, discrete = space_dims(obs_space, act_space)
            model, _ = init_actor_critic(obs_dim, act_dim, discrete, seed)
            self._models[pid] = model
            self._ctxs[pid] = ConnectorContext(obs_space, act_space)
            self._e2m[pid] = (
                env_to_module_connector() if env_to_module_connector
                else default_env_to_module()
            )
            self._m2e[pid] = (
                module_to_env_connector() if module_to_env_connector
                else default_module_to_env()
            )
            self._sample_fns[pid] = jax.jit(
                lambda params, obs, key, _m=model: sample_actions(
                    _m, params, obs, key
                )
            )
        obs, _ = self._env.reset(seed=seed)
        self._obs = obs
        self._ep_return = 0.0
        self._ep_len = 0
        self._completed: List = []

    def _encode(self, pid: str, obs_rows: List) -> np.ndarray:
        return np.asarray(
            self._e2m[pid](np.stack(obs_rows), self._ctxs[pid]), np.float32
        )

    def sample(self, params_by_policy: Dict[str, Any]) -> Dict[str, Any]:
        """Roll ``rollout_len`` env steps; returns per-policy [T, nA]
        trajectory arrays + episode stats (episode return = the TEAM sum
        over all agents, the cooperative objective)."""
        import jax

        from .weight_sync import resolve_params

        params_by_policy = resolve_params(params_by_policy)

        T = self._rollout_len
        buffers: Dict[str, Dict[str, list]] = {
            pid: {k: [] for k in ("obs", "actions", "logp", "values", "rewards", "dones")}
            for pid in self._models
        }
        for _ in range(T):
            action_dict = {}
            step_cache = {}
            for pid, agents in self._agents_of.items():
                if not agents:
                    continue
                self._key, sub = jax.random.split(self._key)
                encoded = self._encode(pid, [self._obs[a] for a in agents])
                actions, logp, values = self._sample_fns[pid](
                    params_by_policy[pid], encoded, sub
                )
                actions = np.asarray(actions)
                env_actions = self._m2e[pid](actions, self._ctxs[pid])
                for i, agent in enumerate(agents):
                    action_dict[agent] = env_actions[i]
                step_cache[pid] = (encoded, actions, np.asarray(logp),
                                   np.asarray(values))
            obs, rewards, terms, truncs, _ = self._env.step(action_dict)
            done = episode_done(terms, truncs)
            self._ep_return += float(
                sum(rewards.get(a, 0.0) for a in self._agents)
            )
            self._ep_len += 1
            for pid, agents in self._agents_of.items():
                if not agents:
                    continue
                encoded, actions, logp, values = step_cache[pid]
                buf = buffers[pid]
                buf["obs"].append(encoded)
                buf["actions"].append(actions)
                buf["logp"].append(logp)
                buf["values"].append(values)
                buf["rewards"].append(
                    np.asarray([rewards.get(a, 0.0) for a in agents], np.float32)
                )
                buf["dones"].append(np.full(len(agents), done))
            if done:
                self._completed.append((self._ep_return, self._ep_len))
                self._ep_return, self._ep_len = 0.0, 0
                obs, _ = self._env.reset()
            self._obs = obs
        out: Dict[str, Any] = {}
        for pid, agents in self._agents_of.items():
            if not agents:
                continue
            buf = buffers[pid]
            self._key, sub = jax.random.split(self._key)
            encoded = self._encode(pid, [self._obs[a] for a in agents])
            _, _, last_values = self._sample_fns[pid](
                params_by_policy[pid], encoded, sub
            )
            out[pid] = {
                "obs": np.stack(buf["obs"]),
                "actions": np.stack(buf["actions"]),
                "logp": np.stack(buf["logp"]),
                "values": np.stack(buf["values"]),
                "rewards": np.stack(buf["rewards"]),
                "dones": np.stack(buf["dones"]),
                "last_values": np.asarray(last_values),
            }
        completed, self._completed = self._completed, []
        return {
            "policies": out,
            "episode_returns": [r for r, _ in completed],
            "episode_lengths": [l for _, l in completed],
        }

    def ping(self):
        return True

    def stop(self):
        try:
            self._env.close()
        except Exception:
            pass
        return True


class MultiAgentPPO:
    """Per-policy PPO learners over MultiAgentEnvRunner actors (reference:
    Algorithm with a MultiAgent module dict; rollouts on CPU actors, every
    policy's update is the jitted single-agent program)."""

    def __init__(self, config: MultiAgentPPOConfig):
        if config.env_spec is None:
            raise ValueError("config.environment(...) is required")
        if not config.policies or config.policy_mapping_fn is None:
            raise ValueError("config.multi_agent(policies, mapping_fn) is required")
        self.config = config
        self.iteration = 0
        from .env import make_env

        probe = make_env(config.env_spec, config.env_config)()
        agents = list(probe.possible_agents)
        if not agents:
            raise ValueError("MultiAgentEnv.possible_agents is empty")
        mapping_items = [(a, config.policy_mapping_fn(a)) for a in agents]
        unknown = {p for _, p in mapping_items} - set(config.policies)
        if unknown:
            raise ValueError(f"mapping_fn produced unknown policies {unknown}")
        self.learners: Dict[str, PPOLearner] = {}
        for pid in config.policies:
            pid_agents = [a for a, p in mapping_items if p == pid]
            if not pid_agents:
                continue
            obs_dim, act_dim, discrete = space_dims(
                probe.observation_space(pid_agents[0]),
                probe.action_space(pid_agents[0]),
            )
            self.learners[pid] = PPOLearner(
                obs_dim, act_dim, discrete,
                lr=config.lr, clip_param=config.clip_param,
                vf_coeff=config.vf_coeff, entropy_coeff=config.entropy_coeff,
                num_epochs=config.num_epochs,
                minibatch_size=config.minibatch_size,
                max_grad_norm=config.max_grad_norm, seed=config.seed,
            )
        try:
            probe.close()
        except Exception:
            pass
        from .weight_sync import broadcaster_for

        self._broadcaster = broadcaster_for(config)
        Runner = api.remote(num_cpus=config.num_cpus_per_runner)(
            MultiAgentEnvRunner
        )
        self.runners = [
            Runner.remote(
                config.env_spec,
                config.env_config,
                list(self.learners.keys()),
                mapping_items,
                config.rollout_len,
                config.seed + 1000 * (i + 1),
                config.env_to_module_connector,
                config.module_to_env_connector,
            )
            for i in range(config.num_env_runners)
        ]
        api.get([r.ping.remote() for r in self.runners])
        self._ep_return_window: List[float] = []

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        params = {pid: l.get_params() for pid, l in self.learners.items()}
        params_handle = self._broadcaster.handle(params)
        rollouts = api.get(
            [r.sample.remote(params_handle) for r in self.runners]
        )
        stats: Dict[str, Any] = {}
        steps = 0
        ep_returns: List[float] = []
        ep_lengths: List[int] = []
        for pid, learner in self.learners.items():
            policy_rollouts = [
                ro["policies"][pid] for ro in rollouts
                if pid in ro["policies"]
            ]
            batch = gae_batch(
                policy_rollouts, self.config.gamma, self.config.lam
            )
            steps += batch["obs"].shape[0]
            pid_stats = learner.update(batch)
            stats.update({f"{pid}/{k}": v for k, v in pid_stats.items()})
        for ro in rollouts:
            ep_returns.extend(ro["episode_returns"])
            ep_lengths.extend(ro["episode_lengths"])
        self.iteration += 1
        self._ep_return_window.extend(ep_returns)
        self._ep_return_window = self._ep_return_window[-100:]
        mean_return = (
            float(np.mean(self._ep_return_window))
            if self._ep_return_window else float("nan")
        )
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "num_episodes": len(ep_returns),
            "episode_len_mean": float(np.mean(ep_lengths))
            if ep_lengths else float("nan"),
            "num_env_steps_sampled": steps,
            "time_this_iter_s": time.time() - t0,
            **stats,
        }

    # -- checkpointing ------------------------------------------------------

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(
                {
                    "learners": {
                        pid: l.state_dict() for pid, l in self.learners.items()
                    },
                    "iteration": self.iteration,
                },
                f,
            )
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        for pid, learner_state in state["learners"].items():
            self.learners[pid].load_state_dict(learner_state)
        self.iteration = state["iteration"]

    def stop(self):
        for r in self.runners:
            try:
                api.kill(r)
            except Exception:
                pass
        self.runners = []


MultiAgentPPOConfig.algo_class = MultiAgentPPO
