"""PPOLearner: jitted PPO updates in JAX/optax.

Role-equivalent of the reference's Learner (rllib/core/learner/learner.py:112
— torch SGD with DDP). TPU-first: the whole epoch of minibatch updates runs
inside ONE jitted ``lax.scan`` (shuffle + clipped-surrogate + value + entropy
loss + adamw), so the MXU sees a single compiled program per train step
instead of a Python minibatch loop; under a device mesh the same function
pjit-shards over the batch axis, which is the Learner-group DP the reference
gets from DDP.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .models import init_actor_critic, log_prob_entropy


class PPOLearner:
    def __init__(
        self,
        obs_dim: int,
        action_dim: int,
        discrete: bool,
        *,
        lr: float = 3e-4,
        clip_param: float = 0.2,
        vf_coeff: float = 0.5,
        entropy_coeff: float = 0.01,
        num_epochs: int = 4,
        minibatch_size: int = 128,
        max_grad_norm: float = 0.5,
        seed: int = 0,
    ):
        self.model, self.params = init_actor_critic(
            obs_dim, action_dim, discrete, seed
        )
        self.tx = optax.chain(
            optax.clip_by_global_norm(max_grad_norm),
            optax.adam(lr),
        )
        self.opt_state = self.tx.init(self.params)
        self.clip_param = clip_param
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.num_epochs = num_epochs
        self.minibatch_size = minibatch_size
        self.discrete = discrete
        self._key = jax.random.PRNGKey(seed + 1)
        self._update_fn = jax.jit(self._update_epochs)

    # -- loss ---------------------------------------------------------------

    def _loss(self, params, batch):
        out, values = self.model.apply({"params": params}, batch["obs"])
        logp, entropy = log_prob_entropy(self.discrete, out, batch["actions"])
        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        pg1 = ratio * adv
        pg2 = jnp.clip(ratio, 1 - self.clip_param, 1 + self.clip_param) * adv
        pg_loss = -jnp.mean(jnp.minimum(pg1, pg2))
        vf_loss = jnp.mean((values - batch["returns"]) ** 2)
        ent = jnp.mean(entropy)
        total = pg_loss + self.vf_coeff * vf_loss - self.entropy_coeff * ent
        stats = {
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": ent,
            "total_loss": total,
        }
        return total, stats

    # -- one jitted train step (all epochs + minibatches) --------------------

    def _update_epochs(self, params, opt_state, key, batch):
        B = batch["obs"].shape[0]
        mb = min(self.minibatch_size, B)
        n_mb = B // mb

        def minibatch_step(carry, idx):
            params, opt_state = carry
            mb_batch = jax.tree.map(lambda x: x[idx], batch)
            (_, stats), grads = jax.value_and_grad(self._loss, has_aux=True)(
                params, mb_batch
            )
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), stats

        def epoch_step(carry, key):
            perm = jax.random.permutation(key, B)[: n_mb * mb].reshape(
                n_mb, mb
            )
            carry, stats = jax.lax.scan(minibatch_step, carry, perm)
            return carry, jax.tree.map(jnp.mean, stats)

        keys = jax.random.split(key, self.num_epochs)
        (params, opt_state), stats = jax.lax.scan(
            epoch_step, (params, opt_state), keys
        )
        return params, opt_state, jax.tree.map(jnp.mean, stats)

    # -- public -------------------------------------------------------------

    def update(self, train_batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """train_batch: flat [B, ...] arrays (obs, actions, logp_old,
        advantages, returns); advantages standardized here."""
        adv = train_batch["advantages"]
        train_batch = dict(train_batch)
        train_batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        batch = {
            k: jnp.asarray(v)
            for k, v in train_batch.items()
        }
        self._key, sub = jax.random.split(self._key)
        self.params, self.opt_state, stats = self._update_fn(
            self.params, self.opt_state, sub, batch
        )
        return {k: float(v) for k, v in stats.items()}

    def get_params(self):
        return jax.device_get(self.params)

    def set_params(self, params):
        self.params = jax.device_put(params)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def load_state_dict(self, state: Dict[str, Any]):
        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])
