"""Connector pipelines: composable transforms between env and module.

Role-equivalent of the reference's connector V2 stack
(rllib/connectors/connector_pipeline_v2.py + env_to_module/, module_to_env/):
an **env-to-module** pipeline turns raw env observations into the model's
input batch; a **module-to-env** pipeline turns model outputs into actions
the env accepts. Users compose transforms by prepending/appending pieces
instead of forking the runner; the runner owns nothing but the call.

Data contract (kept deliberately array-shaped for the TPU path): a connector
is ``__call__(data, ctx) -> data`` where data is a numpy batch ([N, ...]
observations or [N, ...] actions) and ctx carries the gym spaces.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class ConnectorContext:
    """Spaces (and room for future fields) visible to every connector."""

    def __init__(self, observation_space=None, action_space=None):
        self.observation_space = observation_space
        self.action_space = action_space


class Connector:
    """One transform stage (reference: ConnectorV2.__call__)."""

    def __call__(self, data, ctx: ConnectorContext):
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class ConnectorPipeline(Connector):
    """Ordered chain of connectors (reference: ConnectorPipelineV2):
    ``pipeline(data)`` pushes the batch through every stage in order.
    Mutate with prepend/append/insert_after — the composition surface the
    reference exposes for custom obs/action transforms."""

    def __init__(self, connectors: Optional[Sequence[Connector]] = None):
        self.connectors: List[Connector] = list(connectors or [])

    def __call__(self, data, ctx: ConnectorContext):
        for connector in self.connectors:
            data = connector(data, ctx)
        return data

    def prepend(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def insert_after(self, anchor_type, connector: Connector) -> "ConnectorPipeline":
        for i, existing in enumerate(self.connectors):
            if isinstance(existing, anchor_type):
                self.connectors.insert(i + 1, connector)
                return self
        raise ValueError(f"no connector of type {anchor_type.__name__} in pipeline")

    def __repr__(self):
        inner = " -> ".join(repr(c) for c in self.connectors)
        return f"ConnectorPipeline[{inner}]"


class FlattenObservations(Connector):
    """Raw obs batch -> float32 [N, obs_dim]; Discrete obs one-hot encode
    (reference: env_to_module/flatten_observations.py)."""

    def __call__(self, data, ctx: ConnectorContext):
        from .env import encode_obs

        return encode_obs(ctx.observation_space, np.asarray(data))


class NormalizeObservations(Connector):
    """Running mean/std normalization (reference:
    env_to_module/mean_std_filter.py), updated on every batch."""

    def __init__(self, epsilon: float = 1e-8):
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None
        self._eps = epsilon

    def __call__(self, data, ctx: ConnectorContext):
        batch = np.asarray(data, np.float32)
        if self._mean is None:
            self._mean = np.zeros(batch.shape[1:], np.float32)
            self._m2 = np.ones(batch.shape[1:], np.float32)
        for row in batch:  # Welford; batches are small on the rollout path
            self._count += 1.0
            delta = row - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (row - self._mean)
        var = self._m2 / max(self._count, 1.0)
        return (batch - self._mean) / np.sqrt(var + self._eps)


class ClipActions(Connector):
    """Clip continuous actions into the Box bounds; pass-through for
    Discrete (reference: module_to_env/clip_actions? — the unsquash/clip
    tail of the module-to-env pipeline)."""

    def __call__(self, data, ctx: ConnectorContext):
        import gymnasium as gym

        space = ctx.action_space
        if isinstance(space, gym.spaces.Box):
            return np.clip(np.asarray(data), space.low, space.high)
        return data


class Lambda(Connector):
    """Wrap a plain function as a connector stage."""

    def __init__(self, fn: Callable[[Any, ConnectorContext], Any], name: str = ""):
        self._fn = fn
        self._name = name or getattr(fn, "__name__", "lambda")

    def __call__(self, data, ctx: ConnectorContext):
        return self._fn(data, ctx)

    def __repr__(self):
        return f"Lambda({self._name})"


def default_env_to_module() -> ConnectorPipeline:
    """The default obs pipeline (what the runner did inline before)."""
    return ConnectorPipeline([FlattenObservations()])


def default_module_to_env() -> ConnectorPipeline:
    return ConnectorPipeline([ClipActions()])
