"""ray_tpu.rllib: reinforcement learning (reference: python/ray/rllib).

PPO with CPU env-runner actors and a JAX learner whose whole update epoch is
one jitted lax.scan — the rollout/learner split the reference implements as
EnvRunnerGroup (env_runner_group.py:70) + LearnerGroup (learner_group.py:101),
with the learner compiling to the TPU instead of torch DDP.
"""

from .algorithm import PPO, PPOConfig, as_trainable
from .bc import BC, BCConfig
from .connectors import (
    ClipActions,
    Connector,
    ConnectorContext,
    ConnectorPipeline,
    FlattenObservations,
    Lambda,
    NormalizeObservations,
)
from .dqn import DQN, DQNConfig, ReplayBuffer
from .dreamer import DreamerV3, DreamerV3Config
from .env import VectorEnv, make_env
from .env_runner import EnvRunner
from .impala import APPOConfig, IMPALA, IMPALAConfig
from .learner import PPOLearner
from .multi_agent import (
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from .multi_agent_env import MultiAgentEnv
from .offline import CQL, CQLConfig, IQL, IQLConfig, MARWIL, MARWILConfig
from .sac import SAC, SACConfig

__all__ = [
    "PPO",
    "PPOConfig",
    "DQN",
    "DQNConfig",
    "IMPALA",
    "IMPALAConfig",
    "APPOConfig",
    "SAC",
    "SACConfig",
    "BC",
    "BCConfig",
    "MARWIL",
    "MARWILConfig",
    "CQL",
    "CQLConfig",
    "IQL",
    "IQLConfig",
    "DreamerV3",
    "DreamerV3Config",
    "ReplayBuffer",
    "as_trainable",
    "PPOLearner",
    "EnvRunner",
    "VectorEnv",
    "make_env",
    "MultiAgentEnv",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "MultiAgentEnvRunner",
    "Connector",
    "ConnectorContext",
    "ConnectorPipeline",
    "FlattenObservations",
    "NormalizeObservations",
    "ClipActions",
    "Lambda",
]
