"""Offline RL algorithms: MARWIL, CQL, IQL.

Role-equivalents of the reference's offline family
(rllib/algorithms/marwil/ — advantage-re-weighted imitation;
rllib/algorithms/cql/ — conservative Q-learning penalizing out-of-dataset
actions; rllib/algorithms/iql/ — implicit Q-learning via expectile
regression). TPU-first like the rest of this rllib: every update epoch is
one jitted ``lax.scan`` over shuffled minibatches, the offline dataset is a
single host->device transfer, and no environment interaction happens during
training (evaluate() rolls out greedily for reporting only).

Dataset schema: {"obs": [N,D], "actions": [N] or [N,A], "rewards": [N],
"dones": [N]} as arrays or a ray_tpu.data.Dataset of such rows ("next_obs"
additionally for CQL/IQL; MARWIL derives returns from episode boundaries).
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .config_base import AlgorithmConfig
from .env import encode_obs, make_env, space_dims
from .models import (
    ActorCritic,
    QNetwork,
    SquashedGaussianActor,
    TwinQ,
    log_prob_entropy,
    squashed_sample_logp,
)


def _materialize_offline(data, obs_space, obs_dim, discrete, need_next=False):
    """Normalize an offline dataset to device-ready arrays."""
    from ..data.dataset import Dataset

    if isinstance(data, Dataset):
        rows = data.take_all()
        cols: Dict[str, np.ndarray] = {}
        for key in rows[0]:
            cols[key] = np.stack([np.asarray(r[key]) for r in rows])
        data = cols
    out = {
        "obs": encode_obs(obs_space, np.asarray(data["obs"], np.float32)),
        "rewards": np.asarray(data.get("rewards", np.zeros(len(data["obs"]))),
                              np.float32).reshape(-1),
        "dones": np.asarray(data.get("dones", np.zeros(len(data["obs"]))),
                            np.float32).reshape(-1),
    }
    actions = np.asarray(data["actions"])
    if discrete:
        out["actions"] = actions.astype(np.int64).reshape(len(actions))
    else:
        out["actions"] = actions.astype(np.float32).reshape(len(actions), -1)
    if need_next:
        if "next_obs" not in data:
            raise ValueError("CQL/IQL offline data requires 'next_obs'")
        out["next_obs"] = encode_obs(
            obs_space, np.asarray(data["next_obs"], np.float32)
        )
    assert out["obs"].shape[1] == obs_dim
    return out


def _discounted_returns(rewards: np.ndarray, dones: np.ndarray, gamma: float):
    """Reward-to-go within episodes (episode boundaries = dones)."""
    returns = np.zeros_like(rewards)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        acc = rewards[i] + gamma * acc * (1.0 - dones[i])
        returns[i] = acc
    return returns


class _OfflineBase:
    """Shared surface: env probing, minibatch scan driver, evaluation,
    checkpointing (mirrors the BC implementation this family extends)."""

    def __init__(self, config):
        if config.env_spec is None:
            raise ValueError("config.environment(...) is required")
        if config.input_data is None:
            raise ValueError("config.offline_data(...) is required")
        self.config = config
        self.iteration = 0
        probe = make_env(config.env_spec, config.env_config)()
        self._obs_space = probe.observation_space
        self._act_space = probe.action_space
        self.obs_dim, self.act_dim, self.discrete = space_dims(
            probe.observation_space, probe.action_space
        )
        try:
            probe.close()
        except Exception:
            pass
        self._key = jax.random.PRNGKey(config.seed)

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _minibatch_perm(self, key, n_rows):
        mb = min(self.config.train_batch_size, n_rows)
        n_mb = max(n_rows // mb, 1)
        return jax.random.permutation(key, n_rows)[: n_mb * mb].reshape(
            n_mb, mb
        )

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        env = make_env(self.config.env_spec, self.config.env_config)()
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=self.config.seed + ep)
            done, total, steps = False, 0.0, 0
            while not done and steps < 1000:
                action = self.compute_single_action(obs)
                obs, r, term, trunc, _ = env.step(action)
                total += float(r)
                done = term or trunc
                steps += 1
            returns.append(total)
        try:
            env.close()
        except Exception:
            pass
        return {
            "episode_return_mean": float(np.mean(returns)),
            "num_episodes": num_episodes,
        }

    # -- checkpointing ------------------------------------------------------

    def _state_dict(self) -> dict:
        raise NotImplementedError

    def _load_state_dict(self, state: dict):
        raise NotImplementedError

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        name = type(self).__name__.lower()
        with open(os.path.join(checkpoint_dir, f"{name}_state.pkl"), "wb") as f:
            pickle.dump(
                jax.tree.map(np.asarray, self._state_dict()), f
            )
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        name = type(self).__name__.lower()
        with open(os.path.join(checkpoint_dir, f"{name}_state.pkl"), "rb") as f:
            self._load_state_dict(pickle.load(f))


# ---------------------------------------------------------------------------
# MARWIL
# ---------------------------------------------------------------------------


class MARWILConfig(AlgorithmConfig):
    """reference: marwil/marwil.py MARWILConfig. beta=0 degrades to BC."""

    def __init__(self):
        super().__init__()
        self.input_data: Any = None
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_epochs_per_iter = 1
        self.beta = 1.0  # advantage exponent temperature
        self.vf_coeff = 1.0
        self.gamma = 0.99
        # exp(beta * A) is clipped here for stability (reference: MARWIL's
        # moving-average advantage normalizer serves the same purpose)
        self.max_advantage_weight = 20.0

    def offline_data(self, input_data) -> "MARWILConfig":
        self.input_data = input_data
        return self

    def training(self, **kwargs) -> "MARWILConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown MARWIL option {k!r}")
            setattr(self, k, v)
        return self


class MARWIL(_OfflineBase):
    """Monotonic advantage re-weighted imitation learning: supervised policy
    learning where each (s, a) is weighted exp(beta * advantage), with the
    baseline V learned jointly (reference: marwil/marwil.py:24)."""

    def __init__(self, config: MARWILConfig):
        super().__init__(config)
        self.model = ActorCritic(action_dim=self.act_dim, discrete=self.discrete)
        self.params = self.model.init(
            self._next_key(), jnp.zeros((1, self.obs_dim))
        )["params"]
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        data = _materialize_offline(
            config.input_data, self._obs_space, self.obs_dim, self.discrete
        )
        data["returns"] = _discounted_returns(
            data["rewards"], data["dones"], config.gamma
        )
        self._data = jax.tree.map(jnp.asarray, data)
        self._epoch_fn = jax.jit(self._epoch_impl)

    def _loss(self, params, batch):
        out, values = self.model.apply({"params": params}, batch["obs"])
        logp, _ = log_prob_entropy(self.discrete, out, batch["actions"])
        advantage = batch["returns"] - values
        weight = jnp.minimum(
            jnp.exp(self.config.beta * jax.lax.stop_gradient(advantage)),
            self.config.max_advantage_weight,
        )
        policy_loss = -jnp.mean(weight * logp)
        vf_loss = jnp.mean(advantage**2)
        return policy_loss + self.config.vf_coeff * vf_loss, (
            policy_loss, vf_loss,
        )

    def _epoch_impl(self, params, opt_state, key, data):
        def step(carry, idx):
            params, opt_state = carry
            batch = jax.tree.map(lambda x: x[idx], data)
            (loss, aux), grads = jax.value_and_grad(self._loss, has_aux=True)(
                params, batch
            )
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        perm = self._minibatch_perm(key, data["obs"].shape[0])
        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), perm
        )
        return params, opt_state, jnp.mean(losses)

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        losses = []
        for _ in range(self.config.num_epochs_per_iter):
            self.params, self.opt_state, loss = self._epoch_fn(
                self.params, self.opt_state, self._next_key(), self._data
            )
            losses.append(float(loss))
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "marwil_loss": float(np.mean(losses)),
            "num_samples": int(self._data["obs"].shape[0]),
            "time_this_iter_s": time.time() - t0,
        }

    def compute_single_action(self, obs):
        enc = encode_obs(self._obs_space, np.asarray(obs)[None])
        out, _ = self.model.apply({"params": self.params}, jnp.asarray(enc))
        if self.discrete:
            return int(np.asarray(jnp.argmax(out, axis=-1))[0])
        mean, _ = out
        return np.asarray(mean)[0]

    def _state_dict(self):
        return {"params": self.params, "iteration": self.iteration}

    def _load_state_dict(self, state):
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.iteration = state["iteration"]


# ---------------------------------------------------------------------------
# CQL (discrete)
# ---------------------------------------------------------------------------


class CQLConfig(AlgorithmConfig):
    """reference: cql/cql.py CQLConfig (the conservative penalty on top of
    a Q-learner; discrete action spaces here — the logsumexp is exact)."""

    def __init__(self):
        super().__init__()
        self.input_data: Any = None
        self.lr = 3e-4
        self.train_batch_size = 256
        self.num_epochs_per_iter = 1
        self.gamma = 0.99
        self.tau = 0.005  # polyak for the target network
        self.cql_alpha = 1.0  # weight of the conservative penalty

    def offline_data(self, input_data) -> "CQLConfig":
        self.input_data = input_data
        return self

    def training(self, **kwargs) -> "CQLConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown CQL option {k!r}")
            setattr(self, k, v)
        return self


class CQL(_OfflineBase):
    """Conservative Q-learning: a double-DQN-style backup (argmax from the
    online network, value from the target network — removing the max-
    operator overestimation bias) plus the CQL regularizer
    alpha * (logsumexp_a Q(s,a) - Q(s, a_data)) that pushes down
    out-of-dataset action values (reference: cql/cql.py:34,
    cql/torch/cql_torch_learner.py)."""

    def __init__(self, config: CQLConfig):
        super().__init__(config)
        if not self.discrete:
            raise ValueError(
                "this CQL implements discrete action spaces (exact "
                "logsumexp); use IQL for continuous offline control"
            )
        self.model = QNetwork(action_dim=self.act_dim)
        self.params = self.model.init(
            self._next_key(), jnp.zeros((1, self.obs_dim))
        )["params"]
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self._data = jax.tree.map(
            jnp.asarray,
            _materialize_offline(
                config.input_data, self._obs_space, self.obs_dim,
                self.discrete, need_next=True,
            ),
        )
        self._epoch_fn = jax.jit(self._epoch_impl)

    def _loss(self, params, target_params, batch):
        q = self.model.apply({"params": params}, batch["obs"])
        q_taken = jnp.take_along_axis(
            q, batch["actions"][:, None], axis=1
        )[:, 0]
        # decoupled selection/evaluation (double DQN): the online net picks
        # the action, the target net scores it
        q_next_online = self.model.apply({"params": params}, batch["next_obs"])
        best = jnp.argmax(q_next_online, axis=1)
        q_next_target = self.model.apply(
            {"params": target_params}, batch["next_obs"]
        )
        target = batch["rewards"] + self.config.gamma * (
            1.0 - batch["dones"]
        ) * jnp.take_along_axis(q_next_target, best[:, None], axis=1)[:, 0]
        bellman = jnp.mean((q_taken - jax.lax.stop_gradient(target)) ** 2)
        conservative = jnp.mean(
            jax.scipy.special.logsumexp(q, axis=1) - q_taken
        )
        return bellman + self.config.cql_alpha * conservative, (
            bellman, conservative,
        )

    def _epoch_impl(self, params, target_params, opt_state, key, data):
        def step(carry, idx):
            params, target_params, opt_state = carry
            batch = jax.tree.map(lambda x: x[idx], data)
            (loss, _aux), grads = jax.value_and_grad(
                self._loss, has_aux=True
            )(params, target_params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_params = jax.tree.map(
                lambda t, p: (1 - self.config.tau) * t + self.config.tau * p,
                target_params, params,
            )
            return (params, target_params, opt_state), loss

        perm = self._minibatch_perm(key, data["obs"].shape[0])
        (params, target_params, opt_state), losses = jax.lax.scan(
            step, (params, target_params, opt_state), perm
        )
        return params, target_params, opt_state, jnp.mean(losses)

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        losses = []
        for _ in range(self.config.num_epochs_per_iter):
            (
                self.params, self.target_params, self.opt_state, loss,
            ) = self._epoch_fn(
                self.params, self.target_params, self.opt_state,
                self._next_key(), self._data,
            )
            losses.append(float(loss))
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "cql_loss": float(np.mean(losses)),
            "num_samples": int(self._data["obs"].shape[0]),
            "time_this_iter_s": time.time() - t0,
        }

    def compute_single_action(self, obs):
        enc = encode_obs(self._obs_space, np.asarray(obs)[None])
        q = self.model.apply({"params": self.params}, jnp.asarray(enc))
        return int(np.asarray(jnp.argmax(q, axis=-1))[0])

    def _state_dict(self):
        return {
            "params": self.params,
            "target_params": self.target_params,
            "iteration": self.iteration,
        }

    def _load_state_dict(self, state):
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.target_params = jax.tree.map(jnp.asarray, state["target_params"])
        self.iteration = state["iteration"]


# ---------------------------------------------------------------------------
# IQL
# ---------------------------------------------------------------------------


class IQLConfig(AlgorithmConfig):
    """reference: the IQL family (implicit Q-learning; expectile value
    regression + advantage-weighted policy extraction)."""

    def __init__(self):
        super().__init__()
        self.input_data: Any = None
        self.lr = 3e-4
        self.train_batch_size = 256
        self.num_epochs_per_iter = 1
        self.gamma = 0.99
        self.tau = 0.005
        self.expectile = 0.7  # tau in the expectile loss
        self.awr_beta = 3.0  # advantage-weighted regression temperature
        self.max_advantage_weight = 100.0

    def offline_data(self, input_data) -> "IQLConfig":
        self.input_data = input_data
        return self

    def training(self, **kwargs) -> "IQLConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IQL option {k!r}")
            setattr(self, k, v)
        return self


class IQL(_OfflineBase):
    """Implicit Q-learning: V learned by expectile regression against Q
    (never queries out-of-dataset actions), Q by bellman against V(s'),
    policy by advantage-weighted regression — discrete (QNetwork) and
    continuous (TwinQ + squashed Gaussian actor; Box bounds respected:
    Q consumes raw env actions, the policy normalizes through [-1, 1])
    action spaces."""

    def __init__(self, config: IQLConfig):
        super().__init__(config)
        key_q, key_v, key_pi = jax.random.split(self._next_key(), 3)
        zo = jnp.zeros((1, self.obs_dim))
        self.vf = QNetwork(action_dim=1)  # scalar V head
        if self.discrete:
            self.qf = QNetwork(action_dim=self.act_dim)
            q_params = self.qf.init(key_q, zo)["params"]
            self.actor = ActorCritic(action_dim=self.act_dim, discrete=True)
            pi_params = self.actor.init(key_pi, zo)["params"]
        else:
            self.qf = TwinQ()
            q_params = self.qf.init(
                key_q, zo, jnp.zeros((1, self.act_dim))
            )["params"]
            self.actor = SquashedGaussianActor(action_dim=self.act_dim)
            pi_params = self.actor.init(key_pi, zo)["params"]
            # Box bounds: the squashed policy lives in [-1, 1]; dataset
            # actions normalize into that range for the AWR log-prob and
            # emitted actions rescale back (same mapping as sac.py)
            low = np.asarray(self._act_space.low, np.float32).reshape(-1)
            high = np.asarray(self._act_space.high, np.float32).reshape(-1)
            self._act_mid = jnp.asarray((low + high) / 2.0)
            self._act_half = jnp.asarray((high - low) / 2.0)
        self.state = {
            "q": q_params,
            "target_q": jax.tree.map(jnp.copy, q_params),
            "v": self.vf.init(key_v, zo)["params"],
            "pi": pi_params,
        }
        self.tx = optax.adam(config.lr)
        self.opt_state = {
            name: self.tx.init(self.state[name]) for name in ("q", "v", "pi")
        }
        self._data = jax.tree.map(
            jnp.asarray,
            _materialize_offline(
                config.input_data, self._obs_space, self.obs_dim,
                self.discrete, need_next=True,
            ),
        )
        self._epoch_fn = jax.jit(self._epoch_impl)

    # -- per-network losses -------------------------------------------------

    def _q_of(self, q_params, obs, actions):
        if self.discrete:
            q = self.qf.apply({"params": q_params}, obs)
            return jnp.take_along_axis(q, actions[:, None], axis=1)[:, 0]
        q1, q2 = self.qf.apply({"params": q_params}, obs, actions)
        return jnp.minimum(q1, q2)

    def _v_loss(self, v_params, state, batch):
        q = jax.lax.stop_gradient(
            self._q_of(state["target_q"], batch["obs"], batch["actions"])
        )
        v = self.vf.apply({"params": v_params}, batch["obs"])[:, 0]
        diff = q - v
        weight = jnp.where(diff > 0, self.config.expectile,
                           1 - self.config.expectile)
        return jnp.mean(weight * diff**2)

    def _q_loss(self, q_params, state, batch):
        next_v = jax.lax.stop_gradient(
            self.vf.apply({"params": state["v"]}, batch["next_obs"])[:, 0]
        )
        target = batch["rewards"] + self.config.gamma * (
            1.0 - batch["dones"]
        ) * next_v
        if self.discrete:
            q = self.qf.apply({"params": q_params}, batch["obs"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1
            )[:, 0]
            return jnp.mean((q_taken - target) ** 2)
        q1, q2 = self.qf.apply(
            {"params": q_params}, batch["obs"], batch["actions"]
        )
        return jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)

    def _pi_loss(self, pi_params, state, batch):
        q = self._q_of(state["target_q"], batch["obs"], batch["actions"])
        v = self.vf.apply({"params": state["v"]}, batch["obs"])[:, 0]
        weight = jnp.minimum(
            jnp.exp(self.config.awr_beta * jax.lax.stop_gradient(q - v)),
            self.config.max_advantage_weight,
        )
        if self.discrete:
            out, _ = self.actor.apply({"params": pi_params}, batch["obs"])
            logp, _ = log_prob_entropy(True, out, batch["actions"])
        else:
            mean, log_std = self.actor.apply(
                {"params": pi_params}, batch["obs"]
            )
            # log-prob of the DATASET action under the squashed Gaussian,
            # normalized from env bounds into the policy's [-1, 1] range
            eps = 1e-6
            normed = (batch["actions"] - self._act_mid) / self._act_half
            pre = jnp.arctanh(jnp.clip(normed, -1 + eps, 1 - eps))
            var = jnp.exp(2 * log_std)
            base = -0.5 * ((pre - mean) ** 2 / var + 2 * log_std
                           + jnp.log(2 * jnp.pi))
            correction = jnp.log(1 - jnp.tanh(pre) ** 2 + eps)
            logp = jnp.sum(base - correction, axis=-1)
        return -jnp.mean(weight * logp)

    def _epoch_impl(self, state, opt_state, key, data):
        def step(carry, idx):
            state, opt_state = carry
            batch = jax.tree.map(lambda x: x[idx], data)
            losses = {}
            for name, loss_fn in (
                ("v", self._v_loss), ("q", self._q_loss), ("pi", self._pi_loss),
            ):
                loss, grads = jax.value_and_grad(loss_fn)(
                    state[name], state, batch
                )
                updates, opt_state[name] = self.tx.update(
                    grads, opt_state[name], state[name]
                )
                state[name] = optax.apply_updates(state[name], updates)
                losses[name] = loss
            state["target_q"] = jax.tree.map(
                lambda t, p: (1 - self.config.tau) * t + self.config.tau * p,
                state["target_q"], state["q"],
            )
            return (state, opt_state), losses["v"] + losses["q"] + losses["pi"]

        perm = self._minibatch_perm(key, data["obs"].shape[0])
        (state, opt_state), losses = jax.lax.scan(
            step, (state, opt_state), perm
        )
        return state, opt_state, jnp.mean(losses)

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        losses = []
        for _ in range(self.config.num_epochs_per_iter):
            self.state, self.opt_state, loss = self._epoch_fn(
                self.state, self.opt_state, self._next_key(), self._data
            )
            losses.append(float(loss))
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "iql_loss": float(np.mean(losses)),
            "num_samples": int(self._data["obs"].shape[0]),
            "time_this_iter_s": time.time() - t0,
        }

    def compute_single_action(self, obs):
        enc = jnp.asarray(encode_obs(self._obs_space, np.asarray(obs)[None]))
        if self.discrete:
            out, _ = self.actor.apply({"params": self.state["pi"]}, enc)
            return int(np.asarray(jnp.argmax(out, axis=-1))[0])
        mean, _ = self.actor.apply({"params": self.state["pi"]}, enc)
        action = self._act_mid + self._act_half * jnp.tanh(mean)
        return np.asarray(action)[0]

    def _state_dict(self):
        return {"state": self.state, "iteration": self.iteration}

    def _load_state_dict(self, state):
        self.state = jax.tree.map(jnp.asarray, state["state"])
        self.iteration = state["iteration"]


MARWILConfig.algo_class = MARWIL
CQLConfig.algo_class = CQL
IQLConfig.algo_class = IQL
