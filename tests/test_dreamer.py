"""Tests for ray_tpu.rllib DreamerV3 (reference: rllib/algorithms/dreamerv3)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


def _tiny_config():
    from ray_tpu.rllib.dreamer import DreamerV3Config

    return (
        DreamerV3Config()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                     rollout_fragment_length=32)
        .training(
            deter_dim=32, stoch_groups=4, stoch_classes=4, hidden_units=32,
            n_bins=21, seq_len=8, batch_size=4, horizon=5,
            learning_starts=32, buffer_capacity=2048,
        )
        .debugging(seed=0)
    )


def test_symlog_twohot_roundtrip():
    import jax.numpy as jnp

    from ray_tpu.rllib.dreamer import (
        symexp, symlog, twohot_bins, twohot_decode, twohot_encode,
    )

    x = jnp.asarray([-100.0, -1.5, 0.0, 0.3, 42.0])
    np.testing.assert_allclose(symexp(symlog(x)), x, rtol=1e-5, atol=1e-5)
    bins = twohot_bins(255)
    enc = twohot_encode(symlog(x), bins)
    # two-hot: at most two nonzero weights summing to 1
    assert np.all(np.asarray((enc > 0).sum(-1)) <= 2)
    np.testing.assert_allclose(np.asarray(enc.sum(-1)), 1.0, rtol=1e-5)
    # softmax(log p) == p, so decoding the encoding recovers the value
    # exactly (two-hot interpolation is linear in symlog space)
    logits = jnp.log(enc + 1e-9)
    np.testing.assert_allclose(
        np.asarray(twohot_decode(logits, bins)), np.asarray(x),
        rtol=0.01, atol=0.01,
    )


def test_latent_kl_zero_for_identical():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.dreamer import latent_kl, latent_sample

    logits = jax.random.normal(jax.random.PRNGKey(0), (3, 4, 8))
    kl = np.asarray(latent_kl(logits, logits))
    np.testing.assert_allclose(kl, 0.0, atol=1e-6)
    s = latent_sample(logits, jax.random.PRNGKey(1))
    assert s.shape == (3, 32)
    # straight-through sample decodes to one-hot-ish rows per group
    rows = np.asarray(s).reshape(3, 4, 8)
    assert np.all(np.abs(rows.sum(-1) - 1.0) < 1e-5)


def test_lambda_returns_match_reference_recursion():
    import jax.numpy as jnp

    from ray_tpu.rllib.dreamer import DreamerV3, DreamerV3Config

    cfg = _tiny_config()
    rng = np.random.default_rng(0)
    H, N = 6, 3
    reward = rng.normal(size=(H + 1, N)).astype(np.float32)
    cont = rng.uniform(0.5, 1.0, size=(H + 1, N)).astype(np.float32)
    value = rng.normal(size=(H + 1, N)).astype(np.float32)
    rets = np.asarray(DreamerV3._lambda_returns(
        type("S", (), {"config": cfg})(), jnp.asarray(reward),
        jnp.asarray(cont), jnp.asarray(value),
    ))
    g, lam = cfg.gamma, cfg.gae_lambda
    expect = np.zeros((H, N), np.float32)
    nxt = value[-1]
    for t in range(H - 1, -1, -1):
        d = cont[t + 1] * g
        nxt = reward[t + 1] + d * ((1 - lam) * value[t + 1] + lam * nxt)
        expect[t] = nxt
    np.testing.assert_allclose(rets, expect, rtol=1e-4, atol=1e-4)


def test_runner_arrival_alignment():
    """Rollout records follow the arrival convention: records with
    is_first carry zero in-action/reward, episode ends append a terminal
    arrival record (the pre-auto-reset obs), and action[t] is the action
    that led INTO obs_t — matching what observe() feeds the RSSM."""
    from ray_tpu.rllib.dreamer import DreamerRunner, DreamerV3Config

    cfg = _tiny_config()
    runner = DreamerRunner(
        "CartPole-v1", {}, 2, 64, seed=0, net_kwargs=cfg._net_kwargs()
    )
    nets_kw = cfg._net_kwargs()
    from ray_tpu.rllib.dreamer import DreamerNets

    c2 = DreamerV3Config()
    for k, v in nets_kw.items():
        setattr(c2, k, v)
    params = DreamerNets(c2, 4, 2, True).init_params(
        __import__("jax").random.PRNGKey(0)
    )
    out = runner.sample(params)
    assert len(out["sequences"]) == 2
    saw_terminal = False
    for seq in out["sequences"]:
        T = len(seq["reward"])
        assert T >= 64  # one arrival per step + terminal extras
        assert seq["is_first"][0]
        np.testing.assert_array_equal(seq["action"][0], 0.0)
        assert seq["reward"][0] == 0.0
        for t in range(T):
            if seq["is_first"][t]:
                # fresh episode: nothing led into this obs
                np.testing.assert_array_equal(seq["action"][t], 0.0)
                assert seq["reward"][t] == 0.0
                assert not seq["is_terminal"][t]
            if seq["is_terminal"][t]:
                saw_terminal = True
                # a terminal arrival was led into by a real action
                assert np.abs(seq["action"][t]).sum() > 0
                # and the following record (if any) starts a new episode
                if t + 1 < T:
                    assert seq["is_first"][t + 1]
    # CartPole under a random policy terminates well within 64 steps
    assert saw_terminal
    assert len(out["episode_returns"]) > 0


@pytest.mark.slow
def test_dreamer_trains_cartpole(cluster):
    algo = _tiny_config().build()
    try:
        learned = None
        for _ in range(6):
            result = algo.train()
            if "wm_loss" in result:
                learned = result
        assert learned is not None, "learner never engaged (buffer too small)"
        for k in ("wm_loss", "actor_loss", "critic_loss", "kl_dyn"):
            assert np.isfinite(learned[k]), (k, learned[k])
        assert learned["kl_dyn"] >= 1.0 - 1e-5  # free bits floor
        assert learned["buffer_size"] > 0
        a = algo.compute_single_action(np.zeros(4, np.float32))
        assert a in (0, 1)
    finally:
        algo.stop()


@pytest.mark.slow
def test_dreamer_continuous_actions(cluster):
    """Pendulum (Box actions): tanh-gaussian actor trains and the deployed
    action is rescaled into the env's bounds like the rollout runners do.

    slow: ~18s of training on the 1-core CI box; the discrete cartpole
    train/checkpoint/runner tests keep dreamer covered in tier-1."""
    from ray_tpu.rllib.dreamer import DreamerV3Config

    cfg = (
        DreamerV3Config()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=1,
                     rollout_fragment_length=24)
        .training(
            deter_dim=32, stoch_groups=4, stoch_classes=4, hidden_units=32,
            n_bins=21, seq_len=8, batch_size=2, horizon=4,
            learning_starts=24, buffer_capacity=1024,
        )
        .debugging(seed=3)
    )
    algo = cfg.build()
    try:
        result = None
        for _ in range(3):
            result = algo.train()
        assert "wm_loss" in result and np.isfinite(result["wm_loss"])
        a = algo.compute_single_action(np.zeros(3, np.float32))
        assert a.shape == (1,)
        assert -2.0 <= float(a[0]) <= 2.0  # Pendulum torque bounds
    finally:
        algo.stop()


@pytest.mark.slow  # 17s: heaviest dreamer path; math/runner tests stay tier-1
def test_dreamer_checkpoint_roundtrip(cluster, tmp_path):
    import jax

    algo = _tiny_config().build()
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ckpt"))
        algo2 = _tiny_config().build()
        try:
            algo2.restore(path)
            assert algo2.iteration == algo.iteration
            for a, b in zip(
                jax.tree.leaves(algo.params), jax.tree.leaves(algo2.params)
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        finally:
            algo2.stop()
    finally:
        algo.stop()
