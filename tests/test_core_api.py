"""Core public API: tasks, objects, actors (reference test model:
python/ray/tests/test_basic.py, test_actor.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import TaskError


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


def test_task_roundtrip(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2), timeout=120) == 3


def test_task_graph_by_ref(cluster):
    @ray_tpu.remote
    def sq(x):
        return x * x

    @ray_tpu.remote
    def total(*xs):
        return sum(xs)

    refs = [sq.remote(i) for i in range(8)]
    assert ray_tpu.get(total.remote(*refs), timeout=120) == sum(i * i for i in range(8))


def test_put_get_small_and_large(cluster):
    small = ray_tpu.put({"a": 1})
    assert ray_tpu.get(small, timeout=60) == {"a": 1}
    arr = np.arange(300_000, dtype=np.float32)  # > 100KB -> plasma
    big = ray_tpu.put(arr)
    np.testing.assert_array_equal(ray_tpu.get(big, timeout=60), arr)


def test_plasma_task_returns(cluster):
    @ray_tpu.remote
    def make():
        return np.ones((512, 512))

    @ray_tpu.remote
    def consume(a):
        return float(a.sum())

    ref = make.remote()
    assert ray_tpu.get(consume.remote(ref), timeout=120) == 512 * 512


def test_error_propagation(cluster):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError) as exc_info:
        ray_tpu.get(boom.remote(), timeout=120)
    assert isinstance(exc_info.value.cause, ValueError)
    assert "kaboom" in str(exc_info.value)


def test_num_returns(cluster):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c], timeout=120) == [1, 2, 3]


def test_wait(cluster):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        import time

        time.sleep(30)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=60)
    assert ready == [f] and not_ready == [s]


def test_nested_tasks(cluster):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) * 10

    assert ray_tpu.get(outer.remote(3), timeout=120) == 40


def test_actor_basic_and_ordering(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.v = start

        def inc(self, n=1):
            self.v += n
            return self.v

    c = Counter.remote(100)
    vals = ray_tpu.get([c.inc.remote() for _ in range(10)], timeout=120)
    assert vals == list(range(101, 111))


def test_actor_exceptions(cluster):
    @ray_tpu.remote
    class Crashy:
        def fail(self):
            raise RuntimeError("actor method failed")

        def ok(self):
            return "fine"

    a = Crashy.remote()
    with pytest.raises(TaskError):
        ray_tpu.get(a.fail.remote(), timeout=120)
    # actor survives its own exceptions
    assert ray_tpu.get(a.ok.remote(), timeout=120) == "fine"


def test_named_actor_get_actor(cluster):
    @ray_tpu.remote
    class Registry:
        def whoami(self):
            return "registry"

    original = Registry.options(name="reg1").remote()
    handle = ray_tpu.get_actor("reg1")
    assert ray_tpu.get(handle.whoami.remote(), timeout=120) == "registry"
    del original  # handle GC terminates the non-detached actor


def test_actor_handle_passing(cluster):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.v = {}

        def set(self, k, v):
            self.v[k] = v
            return True

        def get(self, k):
            return self.v.get(k)

    @ray_tpu.remote
    def writer(store):
        return ray_tpu.get(store.set.remote("x", 42))

    s = Store.remote()
    assert ray_tpu.get(writer.remote(s), timeout=120)
    assert ray_tpu.get(s.get.remote("x"), timeout=120) == 42


def test_kill_actor(cluster):
    from ray_tpu.exceptions import ActorDiedError

    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=120) == "pong"
    ray_tpu.kill(v)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(v.ping.remote(), timeout=120)


def test_cluster_resources(cluster):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 4.0
    assert total["TPU"] == 4.0
    assert len(ray_tpu.nodes()) == 1
