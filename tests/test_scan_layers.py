"""Scanned (stacked-layer) Llama path: param structure, loss parity with
the python-loop form, LoRA split compatibility."""

import pytest
import jax
import jax.numpy as jnp


def _cfg(**kw):
    from ray_tpu.models.llama import LlamaConfig

    return LlamaConfig.tiny(lora_rank=4, **kw)


@pytest.mark.slow  # 7s: scan-vs-loop agreement stays tier-1 via test_scan_and_loop_agree_with_same_params
def test_scan_layers_params_stacked_and_loss_runs():
    from ray_tpu.models.llama import init_params, next_token_loss
    from ray_tpu.parallel.sharding import unbox_params

    cfg = _cfg(scan_layers=True, remat=True)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    # stacked: one "layers" subtree with a leading n_layers axis
    kernel = params["layers"]["block"]["attn"]["wq"]["base"]["kernel"]
    assert kernel.shape[0] == cfg.n_layers
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)
    loss = next_token_loss(cfg, None, params, tokens)
    assert jnp.isfinite(loss)


@pytest.mark.slow
def test_scan_layers_grads_flow_and_lora_split():
    from ray_tpu.models.llama import init_params, next_token_loss
    from ray_tpu.parallel.sharding import unbox_params
    from ray_tpu.train.lora import merge_lora, split_lora

    cfg = _cfg(scan_layers=True)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    base, lora = split_lora(params)
    assert lora, "stacked tree must still expose lora_a/lora_b leaves"
    assert all(k[-1] in ("lora_a", "lora_b") for k in lora)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)

    def loss_fn(lora_p):
        return next_token_loss(cfg, None, merge_lora(base, lora_p), tokens)

    grads = jax.grad(loss_fn)(lora)
    flat = jax.tree_util.tree_leaves(grads)
    assert flat and all(jnp.all(jnp.isfinite(g)) for g in flat)
    # lora_b initializes to zero, so d(loss)/d(lora_a) is zero at init but
    # d(loss)/d(lora_b) must be nonzero (signal actually flows)
    b_grads = [v for k, v in grads.items() if k[-1] == "lora_b"]
    assert any(float(jnp.abs(g).max()) > 0 for g in b_grads)


def test_scan_and_loop_agree_with_same_params():
    """Restacking the loop form's per-layer params must reproduce the scan
    form's logits exactly — same math, different program structure."""
    from ray_tpu.models.llama import Llama, init_params
    from ray_tpu.parallel.sharding import unbox_params

    cfg_loop = _cfg(scan_layers=False)
    cfg_scan = _cfg(scan_layers=True)
    params = unbox_params(init_params(cfg_loop, jax.random.PRNGKey(0)))
    # restack layer_i subtrees into the scan layout
    layer_trees = [params[f"layer_{i}"] for i in range(cfg_loop.n_layers)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *layer_trees
    )
    scan_params = {
        "embed": params["embed"],
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
        "layers": {"block": stacked},
    }
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 16), 0, cfg_loop.vocab_size)
    out_loop = Llama(cfg_loop).apply({"params": params}, tokens)
    out_scan = Llama(cfg_scan).apply({"params": scan_params}, tokens)
    assert jnp.allclose(out_loop, out_scan, atol=1e-5), (
        float(jnp.abs(out_loop - out_scan).max())
    )
