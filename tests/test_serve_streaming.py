"""Serve streaming + ASGI ingress tests (reference: serve/handle.py:557
DeploymentResponseGenerator; serve/_private/proxy.py:805 ASGI protocol;
serve/api.py:181 @serve.ingress)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve import DeploymentResponseGenerator


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=6, resources={"TPU": 4})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_apps():
    yield
    try:
        for app in list(serve.status().keys()):
            serve.delete(app)
    except Exception:
        pass


def test_handle_streaming_first_item_before_completion(cluster):
    """The defining property of streaming: the first chunk is consumable
    while the replica is still generating."""

    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                if i > 0:
                    time.sleep(1.5)
                yield {"chunk": i}

    handle = serve.run(Streamer.bind(), name="stream1", _proxy=False)
    gen = handle.options(stream=True).remote(3)
    assert isinstance(gen, DeploymentResponseGenerator)
    t0 = time.time()
    first = next(gen)
    first_latency = time.time() - t0
    assert first == {"chunk": 0}
    # producer sleeps 1.5s before chunk 1 and again before chunk 2; getting
    # chunk 0 in well under that proves item-level delivery
    assert first_latency < 1.4, f"first chunk took {first_latency:.2f}s"
    assert list(gen) == [{"chunk": 1}, {"chunk": 2}]


def test_handle_streaming_async_generator(cluster):
    @serve.deployment
    class AsyncStreamer:
        async def __call__(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * 10

    handle = serve.run(AsyncStreamer.bind(), name="stream2", _proxy=False)
    out = list(handle.options(stream=True).remote(4))
    assert out == [0, 10, 20, 30]


def test_handle_streaming_non_generator_errors(cluster):
    @serve.deployment
    class NotAGen:
        def __call__(self, x):
            return x

    handle = serve.run(NotAGen.bind(), name="stream3", _proxy=False)
    gen = handle.options(stream=True).remote(1)
    with pytest.raises(Exception, match="generator"):
        list(gen)


def test_http_streaming_ndjson(cluster):
    """Generator ingress streams chunked NDJSON through the proxy; the first
    chunk arrives before the generator finishes."""

    @serve.deployment
    class SlowTokens:
        def __call__(self, body):
            for i in range(3):
                if i > 0:
                    time.sleep(1.5)
                yield {"token": i}

    serve.run(SlowTokens.bind(), name="htstream")
    # streaming flag must have reached the controller via auto-detection
    url = "http://127.0.0.1:8000/htstream"
    req = urllib.request.Request(
        url, data=json.dumps({}).encode(), method="POST"
    )
    t0 = time.time()
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers.get("Content-Type", "").startswith(
            "application/x-ndjson"
        )
        first_line = resp.readline()
        first_latency = time.time() - t0
        rest = [ln for ln in resp.read().splitlines() if ln.strip()]
    assert json.loads(first_line) == {"token": 0}
    assert first_latency < 1.4, f"first chunk took {first_latency:.2f}s"
    assert [json.loads(ln) for ln in rest] == [{"token": 1}, {"token": 2}]


def test_http_streaming_sse(cluster):
    @serve.deployment
    class SSEGen:
        def __call__(self, body):
            yield {"a": 1}
            yield {"a": 2}

    serve.run(SSEGen.bind(), name="ssestream")
    req = urllib.request.Request(
        "http://127.0.0.1:8000/ssestream",
        data=b"{}",
        method="POST",
        headers={"Accept": "text/event-stream"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers.get("Content-Type", "").startswith(
            "text/event-stream"
        )
        payload = resp.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in payload.splitlines()
        if line.startswith("data: ")
    ]
    assert events == [{"a": 1}, {"a": 2}]


# -- ASGI ingress -------------------------------------------------------------


async def _toy_asgi_app(scope, receive, send):
    """Hand-written ASGI-3 app (no fastapi in the image): routes /hello and
    a /stream endpoint that sends body chunks incrementally."""
    assert scope["type"] == "http"
    path = scope["path"]
    if path == "/hello":
        msg = await receive()
        body = msg.get("body", b"")
        replica = scope.get("ray_tpu.replica")
        await send({
            "type": "http.response.start",
            "status": 200,
            "headers": [(b"content-type", b"application/json"),
                        (b"x-served-by", b"asgi")],
        })
        await send({
            "type": "http.response.body",
            "body": json.dumps({
                "echo": body.decode() if body else "",
                "method": scope["method"],
                "has_replica": replica is not None,
            }).encode(),
        })
    elif path == "/stream":
        import asyncio

        await send({
            "type": "http.response.start",
            "status": 200,
            "headers": [(b"content-type", b"text/plain")],
        })
        for i in range(3):
            await send({
                "type": "http.response.body",
                "body": f"part{i};".encode(),
                "more_body": True,
            })
            await asyncio.sleep(0.01)
        await send({"type": "http.response.body", "body": b"done"})
    else:
        await send({"type": "http.response.start", "status": 404,
                    "headers": []})
        await send({"type": "http.response.body", "body": b"nope"})


def test_asgi_ingress_end_to_end(cluster):
    @serve.deployment
    @serve.ingress(_toy_asgi_app)
    class ASGIApp:
        pass

    serve.run(ASGIApp.bind(), name="asgiapp")
    req = urllib.request.Request(
        "http://127.0.0.1:8000/asgiapp/hello",
        data=b"ping",
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
        assert resp.headers["x-served-by"] == "asgi"
        data = json.loads(resp.read())
    assert data == {"echo": "ping", "method": "POST", "has_replica": True}

    with urllib.request.urlopen(
        "http://127.0.0.1:8000/asgiapp/stream", timeout=30
    ) as resp:
        body = resp.read().decode()
    assert body == "part0;part1;part2;done"

    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(
            "http://127.0.0.1:8000/asgiapp/missing", timeout=30
        )
    assert err.value.code == 404


def test_local_mode_streaming():
    @serve.deployment
    class LocalGen:
        def __call__(self, n):
            for i in range(n):
                yield i + 100

    handle = serve.run(LocalGen.bind(), name="lm", _local_testing_mode=True)
    out = list(handle.options(stream=True).remote(3))
    assert out == [100, 101, 102]
