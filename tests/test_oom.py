"""OOM defense: memory monitor + worker-killing policy (reference:
common/memory_monitor.h, raylet/worker_killing_policy*.h and
python/ray/tests/test_memory_pressure.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import WorkerCrashedError
from ray_tpu.runtime.raylet.memory_monitor import (
    GroupByOwnerWorkerKillingPolicy,
    KillCandidate,
    MemoryMonitor,
    RetriableLIFOWorkerKillingPolicy,
)


class TestMemoryMonitor:
    def test_system_memory_reads(self):
        used, total = MemoryMonitor.system_memory()
        assert 0 < used <= total

    def test_threshold_with_injected_usage(self):
        m = MemoryMonitor(usage_threshold=0.9, usage_fn=lambda: (80, 100))
        assert not m.is_over_threshold()
        m._usage_fn = lambda: (95, 100)
        assert m.is_over_threshold()

    def test_min_free_bytes_overrides_fraction(self):
        # 95% threshold would fire at 95; min-free 20 bytes fires at 80
        m = MemoryMonitor(
            usage_threshold=0.95,
            min_memory_free_bytes=20,
            usage_fn=lambda: (85, 100),
        )
        assert m.is_over_threshold()


def _cand(lease, owner, retriable, t):
    return KillCandidate(
        lease_id=lease, worker_id=f"w{lease}", pid=0,
        owner_id=owner, retriable=retriable, started_at=t,
    )


class TestKillingPolicies:
    def test_retriable_preferred(self):
        policy = GroupByOwnerWorkerKillingPolicy()
        cands = [
            _cand(1, "a", False, 100.0),
            _cand(2, "b", True, 1.0),
        ]
        assert policy.select(cands).lease_id == 2

    def test_largest_owner_group_preferred(self):
        policy = GroupByOwnerWorkerKillingPolicy()
        # owner "fanout" has 3 retriable tasks, owner "solo" has 1
        cands = [
            _cand(1, "fanout", True, 1.0),
            _cand(2, "fanout", True, 2.0),
            _cand(3, "fanout", True, 3.0),
            _cand(4, "solo", True, 99.0),
        ]
        v = policy.select(cands)
        assert v.owner_id == "fanout"
        assert v.lease_id == 3  # newest within the group

    def test_lifo_policy_newest_retriable(self):
        policy = RetriableLIFOWorkerKillingPolicy()
        cands = [
            _cand(1, "a", True, 1.0),
            _cand(2, "b", True, 5.0),
            _cand(3, "c", False, 9.0),
        ]
        assert policy.select(cands).lease_id == 2

    def test_empty(self):
        assert GroupByOwnerWorkerKillingPolicy().select([]) is None


class TestOOMKillIntegration:
    def test_kill_under_pressure_then_recover(self, shutdown_only):
        node = ray_tpu.init(num_cpus=2)
        monitor = node.raylet.memory_monitor
        # pressure off: normal task runs fine
        monitor._usage_fn = lambda: (10, 100)

        @ray_tpu.remote(max_retries=0)
        def quick():
            return 7

        assert ray_tpu.get(quick.remote(), timeout=60) == 7

        @ray_tpu.remote(max_retries=0)
        def sleeper():
            time.sleep(60)
            return "survived"

        ref = sleeper.remote()
        time.sleep(0.5)  # let the lease land
        monitor._usage_fn = lambda: (99, 100)  # now over threshold
        with pytest.raises(WorkerCrashedError):
            ray_tpu.get(ref, timeout=60)
        assert node.raylet._oom_kills >= 1

        # pressure clears: cluster keeps working
        monitor._usage_fn = lambda: (10, 100)
        assert ray_tpu.get(quick.remote(), timeout=60) == 7

    def test_retriable_task_retries_after_oom_kill(self, shutdown_only):
        node = ray_tpu.init(num_cpus=2)
        monitor = node.raylet.memory_monitor
        monitor._usage_fn = lambda: (10, 100)

        @ray_tpu.remote(max_retries=2)
        def slow_then_ok():
            time.sleep(2.0)
            return "done"

        ref = slow_then_ok.remote()
        time.sleep(0.5)
        monitor._usage_fn = lambda: (99, 100)
        # wait for the first kill, then lift the pressure so the retry runs
        deadline = time.time() + 30
        while node.raylet._oom_kills == 0 and time.time() < deadline:
            time.sleep(0.1)
        assert node.raylet._oom_kills >= 1
        monitor._usage_fn = lambda: (10, 100)
        assert ray_tpu.get(ref, timeout=90) == "done"
