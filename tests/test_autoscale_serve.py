"""Closed-loop SLO autoscaling against a live cluster: the loadgen plane
drives a deployment governed by an AutoscalePolicy, and the controller
must scale up under pressure, drain back down after decay (picking the
replica with the fewest prefix-affinity hits), and warm cold replicas
through the weight plane before they report RUNNING."""

import time

import pytest

import ray_tpu
from ray_tpu import loadgen, serve, testing
from ray_tpu.util import state as rt_state


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=8, resources={"TPU": 4})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_apps():
    yield
    try:
        for app in list(serve.status().keys()):
            serve.delete(app)
    except Exception:
        pass


def _running(app):
    return [r for r in testing.list_serve_replicas(app)
            if r["state"] == "RUNNING" and r["pid"]]


def _wait_replicas(app, n, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        rows = _running(app)
        if len(rows) == n:
            return rows
        time.sleep(0.1)
    raise TimeoutError(f"{app}: never reached {n} RUNNING replicas")


_POLICY = {
    "min_replicas": 1, "max_replicas": 3, "interval_s": 0.5,
    "target_queue_per_replica": 2.0, "up_hysteresis": 1,
    "down_hysteresis": 2, "idle_queue_per_replica": 0.5,
    "cooldown_up_s": 1.0, "cooldown_down_s": 1.5,
    "scale_up_step": 2, "scale_down_step": 2,
}


def test_closed_loop_scale_up_then_drain_down(cluster):
    """The PR's acceptance demo: sustained open-loop pressure scales the
    deployment up within ~one evaluation interval, the load decays, the
    autoscaler drains back to min via the graceful path, and not one
    caller request is dropped along the way. Both transitions land in the
    decision log (actor + KV mirror) and the autoscale_* metrics."""

    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=64,
                      graceful_shutdown_timeout_s=10.0,
                      autoscale_policy=dict(_POLICY))
    class Work:
        def __call__(self, payload):
            time.sleep(0.15)
            return len(payload.get("token_ids", []))

    handle = serve.run(Work.bind(), name="slo", _proxy=False)
    _wait_replicas("slo", 1)

    # ~14 rps against a 6.7 rps single replica: queue pressure within one
    # 0.5s evaluation interval, then nothing — the decay phase
    trace = loadgen.synthesize(
        loadgen.PoissonArrivals(14.0, 3.0, seed=5).times(),
        [loadgen.RequestClass("short", prompt_tokens=8,
                              max_new_tokens=2, deadline_s=60.0)],
        loadgen.ZipfPrefixes(num_prefixes=4, prefix_tokens=4, seed=5),
        seed=5,
    )
    gen = loadgen.LoadGenerator(
        loadgen.HandleTarget(handle), max_inflight=64
    )
    result = gen.run(trace)

    # zero dropped: the open-loop burst all completed (queue + scale-out)
    assert [r.outcome for r in result.records].count("ok") == len(
        trace.requests
    ), result.summary()

    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    events = ray_tpu.get(controller.autoscale_log.remote(), timeout=10)
    ups = [e for e in events if e["direction"] == "up"]
    assert ups, f"no scale-up decision: {events}"
    # pressure was acted on within one evaluation interval of onset
    assert ups[0]["breach_age_s"] <= _POLICY["interval_s"] + 0.3
    assert ups[0]["deployment"].endswith("Work")
    assert ups[0]["to"] > ups[0]["from"]
    assert ups[0]["signals"]["queue_per_replica"] > 2.0
    assert _POLICY["max_replicas"] >= max(
        len(_running("slo")), ups[0]["to"]
    )

    # decay: drain back down to min via graceful scale-down
    deadline = time.time() + 40
    while time.time() < deadline and len(_running("slo")) > 1:
        time.sleep(0.2)
    assert len(_running("slo")) == 1, "never drained back to min_replicas"
    events = ray_tpu.get(controller.autoscale_log.remote(), timeout=10)
    downs = [e for e in events if e["direction"] == "down"]
    assert downs, f"no scale-down decision: {events}"
    assert downs[-1]["to"] < downs[-1]["from"]

    # the KV mirror serves the same log handle-free (CLI + dashboard path)
    mirrored = rt_state.autoscale_log()
    assert [e["direction"] for e in mirrored] == [
        e["direction"] for e in events
    ]

    # the decision metrics reach the cluster rollup within a push interval
    deadline = time.time() + 15
    rollup = {}
    while time.time() < deadline:
        rollup = rt_state.metrics_summary()["autoscale"]
        if rollup["scale_ups"] >= 1 and rollup["scale_downs"] >= 1:
            break
        time.sleep(0.5)
    assert rollup["scale_ups"] >= 1 and rollup["scale_downs"] >= 1, rollup
    assert rollup["decision_p99_s"] is not None


def test_scale_down_victim_has_fewest_affinity_hits(cluster):
    """Scale-down victim selection: the replica holding the most live
    prefix-affinity keys (hence the warmest KV blocks) survives; traffic
    for the drained replica's prefixes re-biases to the survivor."""
    import random

    from ray_tpu.serve.handle import _prefix_affinity_key
    from ray_tpu.serve.hash_ring import ReplicaRing

    @serve.deployment(num_replicas=2)
    class Which:
        def __call__(self, payload):
            import os

            return os.getpid()

    handle = serve.run(Which.bind(), name="aff", _proxy=False)
    rows = _wait_replicas("aff", 2)
    ordered = sorted(r["replica_id"] for r in rows)

    # craft prompts whose affinity keys map to a chosen replica via the
    # rendezvous ring over the replica ids (the router invariant)
    ring = ReplicaRing(ordered)
    rng = random.Random(0)
    hot_idx = 0
    hot_prompts, cold_prompt = [], None
    while len(hot_prompts) < 6 or cold_prompt is None:
        toks = [rng.randrange(1000) for _ in range(6)]
        payload = {"token_ids": toks, "max_new_tokens": 1}
        rid = ring.lookup(_prefix_affinity_key((payload,), {}, 4))
        if rid == ordered[hot_idx] and len(hot_prompts) < 6:
            hot_prompts.append(payload)
        elif rid != ordered[hot_idx] and cold_prompt is None:
            cold_prompt = payload

    affine = handle.options(prefix_affinity_tokens=4)
    for p in hot_prompts:
        affine.remote(dict(p)).result(timeout_s=30)
    cold_pid = affine.remote(dict(cold_prompt)).result(timeout_s=30)

    hot_rid, cold_rid = ordered[hot_idx], ordered[1 - hot_idx]
    # the controller's replica polls pick up the per-replica live-key
    # counts (6 distinct keys on hot, 1 on cold)
    deadline = time.time() + 15
    counts = {}
    while time.time() < deadline:
        counts = {r["replica_id"]: r["affinity_keys"]
                  for r in _running("aff")}
        if counts.get(hot_rid, 0) >= 6 and counts.get(cold_rid, 0) >= 1:
            break
        time.sleep(0.2)
    assert counts.get(hot_rid, 0) >= 6, counts
    assert counts.get(hot_rid, 0) > counts.get(cold_rid, 0), counts

    serve.run(Which.options(num_replicas=1).bind(), name="aff",
              _proxy=False, _blocking=False)
    survivor = _wait_replicas("aff", 1, timeout=40)[0]["replica_id"]
    assert survivor == hot_rid, (
        f"drained the affinity-hot replica: kept {survivor}, "
        f"counts were {counts}"
    )

    # the cold prefix re-biases to the survivor and still completes
    pid_after = affine.remote(dict(cold_prompt)).result(timeout_s=30)
    hot_pid = affine.remote(dict(hot_prompts[0])).result(timeout_s=30)
    assert pid_after == hot_pid
    assert pid_after != cold_pid


def test_cold_replica_resolves_weights_before_running(cluster):
    """A STARTING replica with a weights_name resolves the published
    version inside __init__ (before the controller can see it healthy),
    so RUNNING always implies warmed; the warmup duration is recorded
    per replica and rolls up into serve_replica_warmup_seconds."""
    import numpy as np

    from ray_tpu import weights as rt_weights

    version = rt_weights.WeightPublisher("srvmodel").publish(
        {"w": np.ones(4, dtype=np.float32)}
    )

    @serve.deployment(num_replicas=1)
    class Warmed:
        def __init__(self):
            from ray_tpu.weights import WeightSubscriber

            self._version, params = WeightSubscriber("srvmodel").get(
                timeout=30.0
            )
            self._w_sum = float(params["w"].sum())
            time.sleep(0.05)  # make the warmup window measurable

        def warmup(self):
            # replica.py runs this before reporting ready
            if self._version is None:
                raise RuntimeError("serving before weights resolved")

        def __call__(self, _):
            return {"version": self._version, "w_sum": self._w_sum}

    handle = serve.run(Warmed.bind(), name="warm", _proxy=False)
    rows = _wait_replicas("warm", 1)
    # RUNNING implies the weights already resolved — first request needs
    # no lazy load
    out = handle.remote(None).result(timeout_s=30)
    assert out == {"version": version, "w_sum": 4.0}
    # warmup duration captured by the controller's polls (>= the 50ms nap)
    deadline = time.time() + 15
    warm_s = 0.0
    while time.time() < deadline:
        rows = _running("warm")
        warm_s = rows[0]["warmup_s"] if rows else 0.0
        if warm_s >= 0.05:
            break
        time.sleep(0.2)
    assert warm_s >= 0.05, rows

    # and the histogram reaches the cluster rollup within a push interval
    deadline = time.time() + 15
    summary = {}
    while time.time() < deadline:
        summary = rt_state.metrics_summary()["serve_latency"]["warmup_s"]
        if any(k.endswith("Warmed") for k in summary):
            break
        time.sleep(0.5)
    row = next(v for k, v in summary.items() if k.endswith("Warmed"))
    assert row["count"] >= 1
    assert row["p99"] is not None and row["p99"] >= 0.05


@pytest.mark.slow
def test_bundled_trace_replay_full(cluster):
    """Heavy variant of the bench: the full bundled ramp-burst-decay trace
    at real time against an autoscaled deployment; replica count must rise
    and fall with the load and every request completes."""
    import threading

    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=256,
                      graceful_shutdown_timeout_s=15.0,
                      autoscale_policy={**_POLICY, "scale_up_step": 1,
                                        "scale_down_step": 1})
    class Work:
        def __call__(self, payload):
            time.sleep(0.15)
            return len(payload.get("token_ids", []))

    handle = serve.run(Work.bind(), name="replay", _proxy=False)
    _wait_replicas("replay", 1)
    trace = loadgen.bundled_trace("ramp_burst_decay")

    stop = threading.Event()
    path = []

    def sampler():
        while not stop.wait(0.25):
            path.append(len(_running("replay")))

    t = threading.Thread(target=sampler, daemon=True)
    t.start()
    result = loadgen.LoadGenerator(
        loadgen.HandleTarget(handle), max_inflight=128
    ).run(trace)
    deadline = time.time() + 40
    while time.time() < deadline and len(_running("replay")) > 1:
        time.sleep(0.25)
    stop.set()
    t.join(timeout=2)

    assert not result.failures, result.summary()
    assert max(path) > 1, "burst never scaled up"
    assert len(_running("replay")) == 1, "decay never drained down"
    events = rt_state.autoscale_log()
    assert {"up", "down"} <= {e["direction"] for e in events}
