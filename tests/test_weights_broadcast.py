"""Multi-node weight broadcast: publisher upload is O(1) in subscriber-node
count. Four nodes (head publisher + 3 subscriber nodes) with the python
transfer path (native plane disabled for deterministic serve accounting):
each chunk must leave the publisher node exactly once — relayed peer-to-peer
down the binomial tree — and co-located subscribers must dedupe through
their node's store."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.weights import WeightPublisher

N_SUB_NODES = 3
MODEL = "bcast/model"


@pytest.fixture
def bcast_cluster():
    cluster = Cluster(
        head_node_args=dict(num_cpus=2),
        _system_config={"object_transfer_native_enabled": False},
    )
    for i in range(N_SUB_NODES):
        cluster.add_node(num_cpus=1, resources={f"sub{i}": 4.0})
    cluster.connect()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def _transfer_stats(node):
    return node.loop_thread.run(node.raylet.handle_transfer_stats())


def test_publisher_upload_is_o1_in_subscriber_nodes(bcast_cluster):
    cluster = bcast_cluster

    @ray_tpu.remote(num_cpus=0)
    class Sub:
        def fetch(self, name):
            from ray_tpu.weights import WeightSubscriber

            sub = WeightSubscriber(name)
            version, value = sub.get(timeout=60)
            checksum = float(sum(value[k].sum() for k in value))
            staleness = sub.staleness()
            sub.release()
            return version, checksum, staleness

    # two actors on sub0 (co-location dedup) + one each on sub1/sub2
    actors = [
        Sub.options(resources={"sub0": 1.0}).remote(),
        Sub.options(resources={"sub0": 1.0}).remote(),
        Sub.options(resources={"sub1": 1.0}).remote(),
        Sub.options(resources={"sub2": 1.0}).remote(),
    ]
    # four 1 MB leaves at a 1 MB chunk size -> 4 chunks (arrays never split)
    params = {
        f"l{i}": np.arange(125_000, dtype=np.float64) + i for i in range(4)
    }
    pub = WeightPublisher(MODEL, chunk_size=1 << 20)
    version = pub.publish(params)
    chunk_ids = pub._held_ids[version]
    assert len(chunk_ids) >= 2

    expected_sum = float(sum(params[k].sum() for k in params))
    results = ray_tpu.get(
        [a.fetch.remote(MODEL) for a in actors], timeout=300
    )
    for got_version, checksum, staleness in results:
        assert got_version == version
        assert checksum == expected_sum
        assert staleness == 0

    head_stats = _transfer_stats(cluster.head_node)
    serves = head_stats["fetch_serves"]
    for oid in chunk_ids:
        # THE acceptance property: each shard left the publisher node at
        # most once, regardless of 3 subscriber nodes / 4 subscribers
        assert serves.get(oid.hex(), 0) <= 1, (
            f"chunk {oid.hex()} served {serves[oid.hex()]}x from publisher"
        )
    # and at least one chunk actually was relayed from the publisher
    assert any(serves.get(oid.hex(), 0) == 1 for oid in chunk_ids)

    # every subscriber NODE pulled each chunk exactly once in total (the
    # relays happened peer-to-peer, co-located subscribers deduped)
    total_serves = {}
    for node in cluster.list_nodes():
        for hex_id, n in _transfer_stats(node)["fetch_serves"].items():
            total_serves[hex_id] = total_serves.get(hex_id, 0) + n
    for oid in chunk_ids:
        assert total_serves.get(oid.hex(), 0) == N_SUB_NODES, (
            oid.hex(), total_serves
        )


def test_broadcast_repair_under_directional_partition():
    """Tree repair under a directional partition: the child node's route TO
    its broadcast parent drops (parent->child still flows). The child's
    parent-wait fails fast, falls back to an unconstrained pull (weights
    still delivered, each chunk once per node — no retry storm), and
    reports the fallback; two reports prune the parent from the tree and
    the child is promoted to seed on its next plan."""
    from ray_tpu import testing
    from ray_tpu.util.state import _gcs_call

    model = "repair/model"
    cluster = Cluster(
        head_node_args=dict(num_cpus=2),
        _system_config={
            "object_transfer_native_enabled": False,
            "chaos_poll_period_s": 0.2,
        },
    )
    try:
        sub_nodes = [
            cluster.add_node(num_cpus=1, resources={f"sub{i}": 4.0})
            for i in range(2)
        ]
        cluster.connect()

        @ray_tpu.remote(num_cpus=0)
        class Sub:
            def fetch(self, name):
                from ray_tpu.weights import WeightSubscriber

                sub = WeightSubscriber(name)
                version, value = sub.get(timeout=60)
                checksum = float(sum(value[k].sum() for k in value))
                sub.release()
                return version, checksum

        seed_node, child_node = sub_nodes
        seed_addr = tuple(seed_node.raylet.address)
        child_addr = tuple(child_node.raylet.address)
        # register positions in a known order: sub0 = seed, sub1 = child
        assert _gcs_call("weights_plan", model, seed_addr)["position"] == 0
        child_plan = _gcs_call("weights_plan", model, child_addr)
        assert child_plan["position"] == 1
        assert tuple(child_plan["parent"]) == seed_addr

        actors = [
            Sub.options(resources={"sub0": 1.0}).remote(),
            Sub.options(resources={"sub1": 1.0}).remote(),
        ]

        # child -> parent drops; parent -> child (and everything else) flows
        testing.set_network_chaos({
            "seed": 3,
            "rules": [{
                "src": child_node.node_id.hex()[:12],
                "dst": f"{seed_addr[0]}:{seed_addr[1]}",
                "fail": 1.0,
            }],
        })
        time.sleep(0.8)  # let every process poll the spec

        pub = WeightPublisher(model, chunk_size=1 << 20)
        params = {
            f"l{i}": np.arange(125_000, dtype=np.float64) + i
            for i in range(2)
        }
        v1 = pub.publish(params)
        expected = float(sum(params[k].sum() for k in params))
        results = ray_tpu.get(
            [a.fetch.remote(model) for a in actors], timeout=300
        )
        assert results == [(v1, expected), (v1, expected)]

        # one fallback report so far: the parent is not yet pruned
        plan = _gcs_call("weights_plan", model, child_addr)
        assert tuple(plan["parent"] or ()) == seed_addr

        # each chunk moved exactly once per subscriber node (the child's
        # fallback pulled from another holder, it did not retry-storm)
        chunk_ids = pub._held_ids[v1]
        total_serves = {}
        for node in cluster.list_nodes():
            for hex_id, n in _transfer_stats(node)["fetch_serves"].items():
                total_serves[hex_id] = total_serves.get(hex_id, 0) + n
        for oid in chunk_ids:
            assert total_serves.get(oid.hex(), 0) == len(sub_nodes), (
                oid.hex(), total_serves
            )

        # a second faulted fetch produces the second report -> prune
        v2 = pub.publish({k: v + 1 for k, v in params.items()})
        results = ray_tpu.get(
            [a.fetch.remote(model) for a in actors], timeout=300
        )
        assert [r[0] for r in results] == [v2, v2]

        plan = _gcs_call("weights_plan", model, child_addr)
        assert plan["position"] == 0 and plan["parent"] is None, (
            f"tree not repaired: {plan}"
        )
    finally:
        try:
            testing.clear_network_chaos()
        except Exception:
            pass
        ray_tpu.shutdown()
        cluster.shutdown()


def test_tree_positions_span_nodes(bcast_cluster):
    """The registry assigns distinct positions per subscriber node and the
    advertised depth matches the binomial shape."""
    from ray_tpu.util.state import _gcs_call

    node_addrs = [
        tuple(n.raylet.address) for n in bcast_cluster.list_nodes()[1:]
    ]
    plans = [_gcs_call("weights_plan", "plan/model", a) for a in node_addrs]
    assert sorted(p["position"] for p in plans) == [0, 1, 2]
    by_pos = {p["position"]: p for p in plans}
    assert by_pos[0]["parent"] is None  # seed pulls from the publisher
    seed_addr = node_addrs[
        [p["position"] for p in plans].index(0)
    ]
    assert tuple(by_pos[1]["parent"]) == seed_addr
    assert tuple(by_pos[2]["parent"]) == seed_addr
    # re-planning the same node is stable
    again = _gcs_call("weights_plan", "plan/model", node_addrs[0])
    assert again["position"] == plans[0]["position"]
    assert again["depth"] == 2  # 3 nodes -> pub -> seed -> {1, 2}
