"""Multi-node weight broadcast: publisher upload is O(1) in subscriber-node
count. Four nodes (head publisher + 3 subscriber nodes) with the python
transfer path (native plane disabled for deterministic serve accounting):
each chunk must leave the publisher node exactly once — relayed peer-to-peer
down the binomial tree — and co-located subscribers must dedupe through
their node's store."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.weights import WeightPublisher

N_SUB_NODES = 3
MODEL = "bcast/model"


@pytest.fixture
def bcast_cluster():
    cluster = Cluster(
        head_node_args=dict(num_cpus=2),
        _system_config={"object_transfer_native_enabled": False},
    )
    for i in range(N_SUB_NODES):
        cluster.add_node(num_cpus=1, resources={f"sub{i}": 4.0})
    cluster.connect()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def _transfer_stats(node):
    return node.loop_thread.run(node.raylet.handle_transfer_stats())


def test_publisher_upload_is_o1_in_subscriber_nodes(bcast_cluster):
    cluster = bcast_cluster

    @ray_tpu.remote(num_cpus=0)
    class Sub:
        def fetch(self, name):
            from ray_tpu.weights import WeightSubscriber

            sub = WeightSubscriber(name)
            version, value = sub.get(timeout=60)
            checksum = float(sum(value[k].sum() for k in value))
            staleness = sub.staleness()
            sub.release()
            return version, checksum, staleness

    # two actors on sub0 (co-location dedup) + one each on sub1/sub2
    actors = [
        Sub.options(resources={"sub0": 1.0}).remote(),
        Sub.options(resources={"sub0": 1.0}).remote(),
        Sub.options(resources={"sub1": 1.0}).remote(),
        Sub.options(resources={"sub2": 1.0}).remote(),
    ]
    # four 1 MB leaves at a 1 MB chunk size -> 4 chunks (arrays never split)
    params = {
        f"l{i}": np.arange(125_000, dtype=np.float64) + i for i in range(4)
    }
    pub = WeightPublisher(MODEL, chunk_size=1 << 20)
    version = pub.publish(params)
    chunk_ids = pub._held_ids[version]
    assert len(chunk_ids) >= 2

    expected_sum = float(sum(params[k].sum() for k in params))
    results = ray_tpu.get(
        [a.fetch.remote(MODEL) for a in actors], timeout=300
    )
    for got_version, checksum, staleness in results:
        assert got_version == version
        assert checksum == expected_sum
        assert staleness == 0

    head_stats = _transfer_stats(cluster.head_node)
    serves = head_stats["fetch_serves"]
    for oid in chunk_ids:
        # THE acceptance property: each shard left the publisher node at
        # most once, regardless of 3 subscriber nodes / 4 subscribers
        assert serves.get(oid.hex(), 0) <= 1, (
            f"chunk {oid.hex()} served {serves[oid.hex()]}x from publisher"
        )
    # and at least one chunk actually was relayed from the publisher
    assert any(serves.get(oid.hex(), 0) == 1 for oid in chunk_ids)

    # every subscriber NODE pulled each chunk exactly once in total (the
    # relays happened peer-to-peer, co-located subscribers deduped)
    total_serves = {}
    for node in cluster.list_nodes():
        for hex_id, n in _transfer_stats(node)["fetch_serves"].items():
            total_serves[hex_id] = total_serves.get(hex_id, 0) + n
    for oid in chunk_ids:
        assert total_serves.get(oid.hex(), 0) == N_SUB_NODES, (
            oid.hex(), total_serves
        )


def test_tree_positions_span_nodes(bcast_cluster):
    """The registry assigns distinct positions per subscriber node and the
    advertised depth matches the binomial shape."""
    from ray_tpu.util.state import _gcs_call

    node_addrs = [
        tuple(n.raylet.address) for n in bcast_cluster.list_nodes()[1:]
    ]
    plans = [_gcs_call("weights_plan", "plan/model", a) for a in node_addrs]
    assert sorted(p["position"] for p in plans) == [0, 1, 2]
    by_pos = {p["position"]: p for p in plans}
    assert by_pos[0]["parent"] is None  # seed pulls from the publisher
    seed_addr = node_addrs[
        [p["position"] for p in plans].index(0)
    ]
    assert tuple(by_pos[1]["parent"]) == seed_addr
    assert tuple(by_pos[2]["parent"]) == seed_addr
    # re-planning the same node is stable
    again = _gcs_call("weights_plan", "plan/model", node_addrs[0])
    assert again["position"] == plans[0]["position"]
    assert again["depth"] == 2  # 3 nodes -> pub -> seed -> {1, 2}
