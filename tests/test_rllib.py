"""Tests for ray_tpu.rllib (reference model: rllib/algorithms/ppo tests)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


def test_gae_matches_reference_recursion():
    from ray_tpu.rllib.models import compute_gae

    T, N = 5, 2
    rng = np.random.default_rng(0)
    rewards = rng.normal(size=(T, N)).astype(np.float32)
    values = rng.normal(size=(T, N)).astype(np.float32)
    dones = np.zeros((T, N), bool)
    dones[2, 0] = True
    last_values = rng.normal(size=N).astype(np.float32)
    adv, ret = compute_gae(rewards, values, dones, last_values, 0.9, 0.8)
    # brute-force single env check
    for n in range(N):
        v_next = last_values[n]
        last = 0.0
        expect = np.zeros(T)
        for t in range(T - 1, -1, -1):
            nonterm = 0.0 if dones[t, n] else 1.0
            delta = rewards[t, n] + 0.9 * v_next * nonterm - values[t, n]
            last = delta + 0.9 * 0.8 * nonterm * last
            expect[t] = last
            v_next = values[t, n]
        np.testing.assert_allclose(adv[:, n], expect, rtol=1e-5)
    np.testing.assert_allclose(ret, adv + values, rtol=1e-6)


def test_learner_update_reduces_loss():
    from ray_tpu.rllib.learner import PPOLearner

    rng = np.random.default_rng(1)
    B, D, A = 256, 6, 3
    learner = PPOLearner(D, A, True, lr=1e-2, num_epochs=2, minibatch_size=64)
    obs = rng.normal(size=(B, D)).astype(np.float32)
    batch = {
        "obs": obs,
        "actions": rng.integers(0, A, size=B),
        "logp_old": np.full(B, -np.log(A), np.float32),
        "advantages": rng.normal(size=B).astype(np.float32),
        "returns": rng.normal(size=B).astype(np.float32),
    }
    first = learner.update(batch)
    for _ in range(5):
        last = learner.update(batch)
    assert last["vf_loss"] < first["vf_loss"]


def test_vector_env_autoreset():
    from ray_tpu.rllib.env import VectorEnv, make_env

    vec = VectorEnv([make_env("CartPole-v1") for _ in range(3)])
    obs = vec.reset(seed=0)
    assert obs.shape == (3, 4)
    for _ in range(50):
        obs, rew, term, trunc = vec.step(np.zeros(3, np.int64))
        assert obs.shape == (3, 4)  # autoreset keeps shapes stable
    vec.close()


@pytest.mark.slow
def test_ppo_cartpole_improves(cluster):
    from ray_tpu import rllib

    config = (
        rllib.PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=64)
        .training(lr=3e-3, num_epochs=4, minibatch_size=128,
                  entropy_coeff=0.01)
        .debugging(seed=3)
    )
    algo = config.build()
    first_returns = None
    best = -np.inf
    for i in range(25):
        result = algo.train()
        if not np.isnan(result["episode_return_mean"]):
            if first_returns is None:
                first_returns = result["episode_return_mean"]
            best = max(best, result["episode_return_mean"])
    algo.stop()
    assert first_returns is not None
    # CartPole random policy ~20; PPO should clearly improve within budget
    assert best > first_returns + 30, (first_returns, best)
    assert best > 60, best


@pytest.mark.slow  # 8s: checkpoint roundtrip stays tier-1 via test_dqn_checkpoint_roundtrip
def test_ppo_checkpoint_roundtrip(cluster, tmp_path):
    from ray_tpu import rllib

    config = (
        rllib.PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                     rollout_fragment_length=16)
        .debugging(seed=0)
    )
    algo = config.build()
    algo.train()
    ckpt = algo.save(str(tmp_path / "ckpt"))
    it = algo.iteration
    params_before = algo.get_policy_params()
    algo.stop()

    algo2 = config.build()
    algo2.restore(ckpt)
    assert algo2.iteration == it
    params_after = algo2.get_policy_params()
    import jax

    for a, b in zip(
        jax.tree.leaves(params_before), jax.tree.leaves(params_after)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    act = algo2.compute_single_action(np.zeros(4, np.float32))
    assert act in (0, 1)
    algo2.stop()


def test_dqn_update_reduces_td_loss(cluster):
    """Learner-only: repeated updates on a fixed batch drive TD loss down."""
    import jax.numpy as jnp
    from ray_tpu.rllib.dqn import DQN, DQNConfig

    config = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=1,
                     rollout_fragment_length=4)
        .training(lr=1e-2, learning_starts=1)
        .debugging(seed=0)
    )
    algo = config.build()
    rng = np.random.default_rng(0)
    batch = {
        "obs": jnp.asarray(rng.normal(size=(64, 4)).astype(np.float32)),
        "actions": jnp.asarray(rng.integers(0, 2, 64)),
        "rewards": jnp.asarray(rng.normal(size=64).astype(np.float32)),
        "next_obs": jnp.asarray(rng.normal(size=(64, 4)).astype(np.float32)),
        "dones": jnp.zeros(64, np.float32),
    }
    losses = []
    for _ in range(20):
        algo.params, algo.opt_state, loss = algo._update(
            algo.params, algo.target_params, algo.opt_state, batch
        )
        losses.append(float(loss))
    algo.stop()
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_dqn_cartpole_improves(cluster):
    from ray_tpu import rllib

    config = (
        rllib.DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=64)
        .training(
            lr=1e-3, learning_starts=256, train_batch_size=64,
            num_updates_per_iter=32, target_update_freq=2,
            epsilon_decay_iters=15,
        )
        .debugging(seed=1)
    )
    algo = config.build()
    first = None
    best = -np.inf
    for _ in range(30):
        result = algo.train()
        if not np.isnan(result["episode_return_mean"]):
            if first is None:
                first = result["episode_return_mean"]
            best = max(best, result["episode_return_mean"])
    algo.stop()
    assert first is not None
    assert best > first + 20, (first, best)


def test_dqn_checkpoint_roundtrip(cluster, tmp_path):
    import jax
    from ray_tpu import rllib

    config = (
        rllib.DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=1,
                     rollout_fragment_length=8)
        .debugging(seed=0)
    )
    algo = config.build()
    algo.train()
    ckpt = algo.save(str(tmp_path / "dqn_ckpt"))
    params_before = algo.params
    algo.stop()

    algo2 = config.build()
    algo2.restore(ckpt)
    for a, b in zip(jax.tree.leaves(params_before), jax.tree.leaves(algo2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert algo2.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
    algo2.stop()


def test_impala_vtrace_learner(cluster):
    """IMPALA trains CartPole a few async iterations; V-trace stats sane."""
    from ray_tpu import rllib

    config = (
        rllib.IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=16)
        .training(num_batches_per_iter=2, lr=5e-4)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        for _ in range(3):
            result = algo.train()
        assert result["training_iteration"] == 3
        assert result["num_env_steps_sampled"] > 0
        assert np.isfinite(result["total_loss"])
        assert 0.0 < result["mean_rho"] < 10.0  # importance ratios sane
    finally:
        algo.stop()


def test_appo_clipped_variant(cluster):
    from ray_tpu import rllib

    config = (
        rllib.APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                     rollout_fragment_length=16)
        .training(num_batches_per_iter=1)
        .debugging(seed=0)
    )
    assert config.use_clip
    algo = config.build()
    try:
        result = algo.train()
        assert np.isfinite(result["total_loss"])
    finally:
        algo.stop()


@pytest.mark.slow
def test_sac_pendulum_updates(cluster):
    """SAC on Pendulum: losses finite, alpha adapts, actions in bounds.

    slow: ~10s of training on the 1-core CI box; PPO/DQN/IMPALA keep the
    learner/checkpoint paths covered in tier-1."""
    from ray_tpu import rllib

    config = (
        rllib.SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=1,
                     rollout_fragment_length=64)
        .training(learning_starts=64, train_batch_size=32,
                  num_updates_per_iter=4)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        for _ in range(3):
            result = algo.train()
        assert result["buffer_size"] >= 128
        assert np.isfinite(result["critic_loss"])
        assert np.isfinite(result["actor_loss"])
        assert result["alpha"] > 0
        a = algo.compute_single_action(np.zeros(3, np.float32))
        # rescaled into Pendulum's Box bounds [-2, 2]
        assert a.shape == (1,) and -2.0 <= float(a[0]) <= 2.0
    finally:
        algo.stop()


def test_sac_requires_continuous(cluster):
    from ray_tpu import rllib

    with pytest.raises(ValueError, match="continuous"):
        rllib.SACConfig().environment("CartPole-v1").build()


def test_bc_clones_expert(cluster, tmp_path):
    """BC fits a synthetic expert (action = obs[0] > 0) and beats random."""
    from ray_tpu import rllib

    rng = np.random.default_rng(0)
    obs = rng.normal(size=(2000, 4)).astype(np.float32)
    actions = (obs[:, 0] > 0).astype(np.int64)
    config = (
        rllib.BCConfig()
        .environment("CartPole-v1")
        .offline_data({"obs": obs, "actions": actions})
        .training(lr=1e-2, num_epochs_per_iter=5)
        .debugging(seed=0)
    )
    algo = config.build()
    first = algo.train()
    for _ in range(4):
        last = algo.train()
    assert last["bc_loss"] < first["bc_loss"]
    assert last["bc_loss"] < 0.3  # near-perfect on a linearly separable task
    # greedy action matches the expert rule
    assert algo.compute_single_action(np.array([1.0, 0, 0, 0], np.float32)) == 1
    assert algo.compute_single_action(np.array([-1.0, 0, 0, 0], np.float32)) == 0
    # checkpoint round trip
    ckpt = algo.save(str(tmp_path / "bc_ckpt"))
    algo2 = config.build()
    algo2.restore(ckpt)
    assert algo2.compute_single_action(np.array([1.0, 0, 0, 0], np.float32)) == 1


def test_impala_compute_single_action_and_tune_adapter(cluster):
    from ray_tpu import rllib

    config = (
        rllib.IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=1,
                     rollout_fragment_length=8)
        .training(num_batches_per_iter=1)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        algo.train()
        assert algo.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
    finally:
        algo.stop()
    # generic as_trainable works for non-PPO configs
    trainable = rllib.as_trainable(config)
    assert callable(trainable)
