"""Collective library: GCS-KV backend across actors, XLA backend on the
device mesh (reference test model: util/collective tests)."""

import jax
import numpy as np
import pytest

import ray_tpu
from ray_tpu.collective import ReduceOp
from ray_tpu.collective.xla_group import XlaGroup
from ray_tpu._internal.jax_compat import shard_map


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_gcs_backend_across_actors(cluster):
    @ray_tpu.remote
    class Member:
        def __init__(self, rank, world):
            import ray_tpu.collective as col

            self.col = col
            self.group = col.init_collective_group(
                world, rank, backend="gcs", group_name="t1"
            )
            self.rank = rank

        def do_allreduce(self):
            return self.group.allreduce(np.full((4,), self.rank + 1.0))

        def do_allgather(self):
            return self.group.allgather(np.array([self.rank]))

        def do_broadcast(self):
            return self.group.broadcast(np.array([42.0 + self.rank]), src_rank=1)

        def do_barrier(self):
            self.group.barrier()
            return True

    members = [Member.remote(r, 3) for r in range(3)]
    out = ray_tpu.get([m.do_allreduce.remote() for m in members], timeout=180)
    for arr in out:
        np.testing.assert_allclose(arr, np.full((4,), 6.0))
    gathered = ray_tpu.get([m.do_allgather.remote() for m in members], timeout=180)
    for g in gathered:
        assert [int(x[0]) for x in g] == [0, 1, 2]
    bc = ray_tpu.get([m.do_broadcast.remote() for m in members], timeout=180)
    assert all(float(b[0]) == 43.0 for b in bc)
    assert all(ray_tpu.get([m.do_barrier.remote() for m in members], timeout=180))


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_xla_group_device_collectives():
    group = XlaGroup(1, 0, "xla-test", devices=jax.devices()[:4])
    x = np.arange(8, dtype=np.float32)  # 2 elements per device
    total = np.asarray(group.allreduce(x))
    # allreduce sums the per-device shards
    np.testing.assert_allclose(total, x.reshape(4, 2).sum(0))
    gathered = np.asarray(group.allgather(x))
    np.testing.assert_allclose(gathered, x)
    # single-process regime: input is the per-device contribution (replicated),
    # device i holds slice i of the sum; the global view concatenates shards
    rs = np.asarray(group.reducescatter(x))
    np.testing.assert_allclose(rs, 4 * x)
    group.barrier()


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_lax_helpers_in_shard_map():
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]), ("g",))

    def body(x):
        total = XlaGroup.lax_allreduce(x, "g")
        gathered = XlaGroup.lax_allgather(x, "g")
        return total, gathered

    f = jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=P("g"),
            out_specs=(P(), P()), check_vma=False,
        )
    )
    x = np.arange(4, dtype=np.float32)
    total, gathered = f(x)
    np.testing.assert_allclose(np.asarray(total), [6.0])
    np.testing.assert_allclose(np.asarray(gathered), x)
