"""Token RPC auth (reference: rpc/authentication/, enable_cluster_auth)."""

import pytest


@pytest.fixture
def reset_token():
    yield
    from ray_tpu._internal.rpc import set_auth_token

    set_auth_token(None)


def test_cluster_with_auth_token_works(shutdown_only, reset_token):
    import ray_tpu

    ray_tpu.init(
        num_cpus=2, _system_config={"cluster_auth_token": "s3cret"}
    )

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(41), timeout=60) == 42

    @ray_tpu.remote
    class A:
        def g(self):
            return "ok"

    a = A.remote()
    assert ray_tpu.get(a.g.remote(), timeout=60) == "ok"


def test_wrong_token_rejected(shutdown_only, reset_token):
    """The probe runs in a subprocess: the auth token is process-global, so
    an in-process probe would share the server's own token."""
    import os
    import subprocess
    import sys
    import textwrap

    import ray_tpu

    node = ray_tpu.init(
        num_cpus=2, _system_config={"cluster_auth_token": "s3cret"}
    )
    gcs_host, gcs_port = node.gcs_address
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def probe(token):
        script = textwrap.dedent(
            f"""
            import asyncio, sys
            sys.path.insert(0, {repo!r})
            from ray_tpu._internal.rpc import RpcClient, set_auth_token

            async def main():
                set_auth_token({token!r} or None)
                client = RpcClient({gcs_host!r}, {gcs_port}, name="probe")
                nodes = await client.call("get_all_nodes", timeout=5)
                await client.close()
                print("GOT", len(nodes))

            asyncio.run(main())
            """
        )
        return subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=60,
        )

    ok = probe("s3cret")
    assert ok.returncode == 0 and "GOT 1" in ok.stdout, (ok.stdout, ok.stderr)
    for bad in ("wrong", ""):
        denied = probe(bad)
        assert denied.returncode != 0, (bad, denied.stdout)
        assert "GOT" not in denied.stdout


def test_no_pickle_before_auth(shutdown_only, reset_token):
    """Auth gates DESERIALIZATION, not just dispatch: a crafted pickle frame
    from an unauthenticated peer must never be loads()-ed — pickle parsing is
    arbitrary code execution, so checking the token after parsing would make
    it decorative (the preamble handshake in _internal/rpc.py)."""
    import os
    import pickle
    import socket
    import struct
    import tempfile
    import time

    import ray_tpu

    node = ray_tpu.init(
        num_cpus=1, _system_config={"cluster_auth_token": "s3cret"}
    )
    gcs_host, gcs_port = node.gcs_address
    sentinel = os.path.join(
        tempfile.gettempdir(), f"ray_tpu_auth_rce_{os.getpid()}"
    )

    class Exploit:
        def __reduce__(self):
            return (open, (sentinel, "w"))

    payload = pickle.dumps((1, "get_all_nodes", (Exploit(),), {}))
    with socket.create_connection((gcs_host, gcs_port), timeout=10) as sock:
        # no preamble: the first bytes are a raw frame containing the exploit
        sock.sendall(struct.pack("<I", len(payload)) + payload)
        sock.settimeout(10)
        # server must drop the connection without ever parsing the frame
        assert sock.recv(1) == b""
    time.sleep(0.2)
    assert not os.path.exists(sentinel), "pre-auth pickle was deserialized!"
