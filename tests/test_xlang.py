"""C++ frontend / cross-language calls (reference: cpp/ API frontend +
ray.cross_language): a C++ client submits named Python functions with JSON
args through the client server and the cluster runs them as tasks."""

import ctypes
import json
import os
import subprocess

import pytest

import ray_tpu
from ray_tpu._native.build import build_xlang


@pytest.fixture(scope="module")
def xlang_binaries():
    return build_xlang()


@pytest.fixture
def cluster_with_client_server(shutdown_only):
    node = ray_tpu.init(
        num_cpus=4, _system_config={"client_server_port": 0}
    )
    yield node.client_server.address


def test_cpp_cli_calls_python_function(cluster_with_client_server, xlang_binaries):
    host, port = cluster_with_client_server
    binary, _lib = xlang_binaries
    out = subprocess.run(
        [binary, host, str(port), "math", "hypot", "[3, 4]"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    reply = json.loads(out.stdout)
    assert reply == {"ok": True, "value": 5.0}


def test_cpp_cli_error_envelope(cluster_with_client_server, xlang_binaries):
    host, port = cluster_with_client_server
    binary, _lib = xlang_binaries
    out = subprocess.run(
        [binary, host, str(port), "math", "no_such_fn", "[]"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0
    reply = json.loads(out.stdout)
    assert reply["ok"] is False
    assert "no_such_fn" in reply["error"]


def test_ctypes_lib_roundtrip(cluster_with_client_server, xlang_binaries):
    host, port = cluster_with_client_server
    _binary, libpath = xlang_binaries
    lib = ctypes.CDLL(libpath)
    lib.ray_tpu_xlang_connect.restype = ctypes.c_void_p
    lib.ray_tpu_xlang_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
    ]
    lib.ray_tpu_xlang_call.restype = ctypes.c_void_p  # manual free
    lib.ray_tpu_xlang_call.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.ray_tpu_xlang_disconnect.argtypes = [ctypes.c_void_p]

    client = lib.ray_tpu_xlang_connect(host.encode(), port, b"")
    assert client
    try:
        raw = lib.ray_tpu_xlang_call(
            client, b"json", b"dumps", json.dumps([[1, 2, 3]]).encode()
        )
        assert raw
        reply = json.loads(ctypes.string_at(raw).decode())
        libc = ctypes.CDLL(None)
        libc.free(ctypes.c_void_p(raw))
        assert reply["ok"] is True
        assert json.loads(reply["value"]) == [1, 2, 3]
    finally:
        lib.ray_tpu_xlang_disconnect(client)
