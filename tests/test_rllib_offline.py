"""Offline RL: MARWIL / CQL / IQL (reference: rllib/algorithms/{marwil,cql}
and the IQL family). Separate module from test_rllib so the offline suite
gets its own cluster lifecycle."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import rllib


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _expert_dataset(n=2000, seed=0):
    """Synthetic CartPole-shaped task: expert action = obs[0] > 0; reward 1
    for matching the expert, episodes of length 20."""
    rng = np.random.default_rng(seed)
    obs = rng.normal(size=(n, 4)).astype(np.float32)
    actions = (obs[:, 0] > 0).astype(np.int64)
    # corrupt 20% of actions with random ones, rewarded 0 — advantage
    # weighting must down-weight them (plain BC cannot)
    corrupt = rng.random(n) < 0.2
    actions[corrupt] = rng.integers(0, 2, corrupt.sum())
    rewards = (actions == (obs[:, 0] > 0)).astype(np.float32)
    dones = np.zeros(n, np.float32)
    dones[19::20] = 1.0
    next_obs = np.roll(obs, -1, axis=0)
    return {
        "obs": obs, "actions": actions, "rewards": rewards,
        "dones": dones, "next_obs": next_obs,
    }


def test_marwil_beats_corrupted_imitation(cluster, tmp_path):
    data = _expert_dataset()
    config = (
        rllib.MARWILConfig()
        .environment("CartPole-v1")
        .offline_data(data)
        .training(lr=1e-2, num_epochs_per_iter=5, beta=5.0)
        .debugging(seed=0)
    )
    algo = config.build()
    first = algo.train()
    for _ in range(4):
        last = algo.train()
    assert last["marwil_loss"] < first["marwil_loss"]
    # advantage weighting recovers the expert rule despite 20% corruption
    assert algo.compute_single_action(np.array([1.0, 0, 0, 0], np.float32)) == 1
    assert algo.compute_single_action(np.array([-1.0, 0, 0, 0], np.float32)) == 0
    ckpt = algo.save(str(tmp_path / "marwil"))
    algo2 = config.build()
    algo2.restore(ckpt)
    assert algo2.compute_single_action(np.array([1.0, 0, 0, 0], np.float32)) == 1


def test_cql_learns_conservative_q(cluster, tmp_path):
    data = _expert_dataset()
    config = (
        rllib.CQLConfig()
        .environment("CartPole-v1")
        .offline_data(data)
        .training(lr=1e-3, num_epochs_per_iter=5, cql_alpha=1.0)
        .debugging(seed=0)
    )
    algo = config.build()
    for _ in range(5):
        result = algo.train()
    assert result["training_iteration"] == 5
    # greedy Q-policy follows the rewarded (expert) action
    assert algo.compute_single_action(np.array([1.5, 0, 0, 0], np.float32)) == 1
    assert algo.compute_single_action(np.array([-1.5, 0, 0, 0], np.float32)) == 0
    ckpt = algo.save(str(tmp_path / "cql"))
    algo2 = config.build()
    algo2.restore(ckpt)
    assert algo2.compute_single_action(np.array([1.5, 0, 0, 0], np.float32)) == 1


def test_cql_rejects_continuous(cluster):
    with pytest.raises(ValueError, match="discrete"):
        rllib.CQLConfig().environment("Pendulum-v1").offline_data(
            {"obs": np.zeros((4, 3)), "actions": np.zeros((4, 1))}
        ).build()


def test_iql_discrete(cluster):
    data = _expert_dataset()
    config = (
        rllib.IQLConfig()
        .environment("CartPole-v1")
        .offline_data(data)
        .training(lr=1e-3, num_epochs_per_iter=5, awr_beta=5.0)
        .debugging(seed=0)
    )
    algo = config.build()
    for _ in range(5):
        result = algo.train()
    assert result["training_iteration"] == 5
    assert algo.compute_single_action(np.array([1.5, 0, 0, 0], np.float32)) == 1
    assert algo.compute_single_action(np.array([-1.5, 0, 0, 0], np.float32)) == 0


@pytest.mark.slow  # 9s: IQL stays tier-1 via test_iql_discrete
def test_iql_continuous(cluster, tmp_path):
    """Pendulum-shaped continuous control: expert action = -obs[0] (clipped);
    IQL's AWR extraction should recover its sign."""
    rng = np.random.default_rng(1)
    n = 2000
    obs = rng.normal(size=(n, 3)).astype(np.float32)
    expert = np.clip(-obs[:, :1], -0.99, 0.99).astype(np.float32)
    noise = rng.normal(scale=0.5, size=(n, 1)).astype(np.float32)
    actions = np.clip(expert + noise * (rng.random((n, 1)) < 0.5), -0.99, 0.99)
    rewards = -np.abs(actions - expert)[:, 0].astype(np.float32)
    dones = np.zeros(n, np.float32)
    dones[49::50] = 1.0
    data = {
        "obs": obs, "actions": actions, "rewards": rewards,
        "dones": dones, "next_obs": np.roll(obs, -1, axis=0),
    }
    config = (
        rllib.IQLConfig()
        .environment("Pendulum-v1")
        .offline_data(data)
        .training(lr=3e-3, num_epochs_per_iter=5, awr_beta=3.0)
        .debugging(seed=0)
    )
    algo = config.build()
    for _ in range(10):
        algo.train()
    a_pos = algo.compute_single_action(np.array([1.0, 0, 0], np.float32))
    a_neg = algo.compute_single_action(np.array([-1.0, 0, 0], np.float32))
    assert a_pos[0] < 0 < a_neg[0], (a_pos, a_neg)
    ckpt = algo.save(str(tmp_path / "iql"))
    algo2 = config.build()
    algo2.restore(ckpt)
    a2 = algo2.compute_single_action(np.array([1.0, 0, 0], np.float32))
    assert abs(a2[0] - a_pos[0]) < 1e-4
