"""Tests for state API, task events, metrics, CLI (reference model:
python/ray/util/state tests + tests/test_metrics_agent.py)."""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


def test_list_nodes(cluster):
    nodes = state.list_nodes()
    assert len(nodes) == 1
    assert nodes[0]["state"] == "ALIVE"
    assert nodes[0]["is_head_node"] is True
    assert nodes[0]["resources_total"]["CPU"] == 4.0


def test_task_events_flow(cluster):
    @ray_tpu.remote
    def tracked(x):
        return x + 1

    refs = [tracked.remote(i) for i in range(3)]
    assert ray_tpu.get(refs) == [1, 2, 3]

    @ray_tpu.remote
    def failing():
        raise ValueError("nope")

    with pytest.raises(Exception):
        ray_tpu.get(failing.options(max_retries=0).remote())

    deadline = time.time() + 10
    tasks = []
    while time.time() < deadline:
        tasks = state.list_tasks()
        finished = [t for t in tasks if t.get("state") == "FINISHED"]
        failed = [t for t in tasks if t.get("state") == "FAILED"]
        if len(finished) >= 3 and len(failed) >= 1:
            break
        time.sleep(0.5)
    names = {t.get("name") for t in tasks}
    assert "tracked" in names
    assert any(t.get("state") == "FAILED" for t in tasks)
    summary = state.summarize_tasks()
    assert summary.get("FINISHED", 0) >= 3


def test_list_actors_and_pgs(cluster):
    @ray_tpu.remote
    class Named:
        def ping(self):
            return 1

    a = Named.options(name="observable").remote()
    assert ray_tpu.get(a.ping.remote()) == 1
    actors = state.list_actors()
    assert any(x["name"] == "observable" for x in actors)

    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)
    pgs = state.list_placement_groups()
    assert len(pgs) >= 1
    remove_placement_group(pg)
    ray_tpu.kill(a)


def test_cluster_summary(cluster):
    summary = state.cluster_summary()
    assert summary["nodes"] == 1
    assert summary["alive_nodes"] == 1
    assert "tasks" in summary


def test_metrics_push_and_prometheus(cluster):
    from ray_tpu.util.metrics import Counter, Gauge, Histogram, prometheus_text

    c = Counter("test_requests_total", "reqs", tag_keys=("route",))
    c.inc(3, tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    g = Gauge("test_queue_len", "queue")
    g.set(7)
    h = Histogram("test_latency", "lat", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(5)

    deadline = time.time() + 15
    text = ""
    while time.time() < deadline:
        text = prometheus_text()
        if "test_requests_total" in text and "test_queue_len 7" in text:
            break
        time.sleep(1)
    assert 'test_requests_total{route="/a"} 3' in text
    assert "test_queue_len 7" in text


def test_metrics_from_workers(cluster):
    @ray_tpu.remote
    def record():
        from ray_tpu.util.metrics import Counter

        c = Counter("worker_side_counter", "from a task")
        c.inc(5)
        time.sleep(4)  # let the pusher flush
        return True

    assert ray_tpu.get(record.remote())
    from ray_tpu.util.metrics import prometheus_text

    deadline = time.time() + 10
    while time.time() < deadline:
        if "worker_side_counter" in prometheus_text():
            break
        time.sleep(1)
    assert "worker_side_counter 5" in prometheus_text()


def test_cli_status_and_list(cluster):
    node = ray_tpu._worker_api.get_node()
    host, port = node.gcs_address
    import subprocess
    import sys

    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "ray_tpu.scripts.cli",
            "status",
            "--address",
            f"{host}:{port}",
        ],
        capture_output=True,
        text=True,
        timeout=60,
        env={**__import__("os").environ, "RAY_TPU_JAX_PLATFORM": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout)
    assert summary["alive_nodes"] >= 1

    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "ray_tpu.scripts.cli",
            "list",
            "nodes",
            "--address",
            f"{host}:{port}",
        ],
        capture_output=True,
        text=True,
        timeout=60,
        env={**__import__("os").environ, "RAY_TPU_JAX_PLATFORM": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    nodes = json.loads(out.stdout)
    assert len(nodes) >= 1


def test_device_profile_writes_xplane(tmp_path):
    """jax.profiler wrapper produces an XPlane trace dir (SURVEY §5)."""
    import os

    import jax
    import jax.numpy as jnp

    from ray_tpu.util import tracing

    logdir = str(tmp_path / "prof")
    with tracing.device_profile(logdir):
        with tracing.annotate_device_trace("matmul_block"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(x @ x)
    found = []
    for root, _dirs, files in os.walk(logdir):
        found.extend(f for f in files if f.endswith((".pb", ".xplane.pb")))
    assert found, f"no profile artifacts under {logdir}"
