"""Tests for state API, task events, metrics, tracing, CLI (reference
model: python/ray/util/state tests + tests/test_metrics_agent.py +
tests/test_tracing.py)."""

import json
import os
import re
import time

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


def test_list_nodes(cluster):
    nodes = state.list_nodes()
    assert len(nodes) == 1
    assert nodes[0]["state"] == "ALIVE"
    assert nodes[0]["is_head_node"] is True
    assert nodes[0]["resources_total"]["CPU"] == 4.0


def test_task_events_flow(cluster):
    @ray_tpu.remote
    def tracked(x):
        return x + 1

    refs = [tracked.remote(i) for i in range(3)]
    assert ray_tpu.get(refs) == [1, 2, 3]

    @ray_tpu.remote
    def failing():
        raise ValueError("nope")

    with pytest.raises(Exception):
        ray_tpu.get(failing.options(max_retries=0).remote())

    deadline = time.time() + 10
    tasks = []
    while time.time() < deadline:
        tasks = state.list_tasks()
        finished = [t for t in tasks if t.get("state") == "FINISHED"]
        failed = [t for t in tasks if t.get("state") == "FAILED"]
        if len(finished) >= 3 and len(failed) >= 1:
            break
        time.sleep(0.5)
    names = {t.get("name") for t in tasks}
    assert "tracked" in names
    assert any(t.get("state") == "FAILED" for t in tasks)
    summary = state.summarize_tasks()
    assert summary.get("FINISHED", 0) >= 3


def test_list_actors_and_pgs(cluster):
    @ray_tpu.remote
    class Named:
        def ping(self):
            return 1

    a = Named.options(name="observable").remote()
    assert ray_tpu.get(a.ping.remote()) == 1
    actors = state.list_actors()
    assert any(x["name"] == "observable" for x in actors)

    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)
    pgs = state.list_placement_groups()
    assert len(pgs) >= 1
    remove_placement_group(pg)
    ray_tpu.kill(a)


def test_cluster_summary(cluster):
    summary = state.cluster_summary()
    assert summary["nodes"] == 1
    assert summary["alive_nodes"] == 1
    assert "tasks" in summary


def test_metrics_push_and_prometheus(cluster):
    from ray_tpu.util.metrics import Counter, Gauge, Histogram, prometheus_text

    c = Counter("test_requests_total", "reqs", tag_keys=("route",))
    c.inc(3, tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    g = Gauge("test_queue_len", "queue")
    g.set(7)
    h = Histogram("test_latency", "lat", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(5)

    deadline = time.time() + 15
    text = ""
    gauge_re = re.compile(r'test_queue_len\{worker="[0-9a-f]+"\} 7')
    while time.time() < deadline:
        text = prometheus_text()
        if "test_requests_total" in text and gauge_re.search(text):
            break
        time.sleep(1)
    assert 'test_requests_total{route="/a"} 3' in text
    # gauges are per-worker facts: each pushing worker renders its own
    # series under a ``worker`` label instead of a meaningless sum
    assert gauge_re.search(text), text


def test_metrics_from_workers(cluster):
    @ray_tpu.remote
    def record():
        from ray_tpu.util.metrics import Counter

        c = Counter("worker_side_counter", "from a task")
        c.inc(5)
        time.sleep(4)  # let the pusher flush
        return True

    assert ray_tpu.get(record.remote())
    from ray_tpu.util.metrics import prometheus_text

    deadline = time.time() + 10
    while time.time() < deadline:
        if "worker_side_counter" in prometheus_text():
            break
        time.sleep(1)
    assert "worker_side_counter 5" in prometheus_text()


def test_cli_status_and_list(cluster):
    node = ray_tpu._worker_api.get_node()
    host, port = node.gcs_address
    import subprocess
    import sys

    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "ray_tpu.scripts.cli",
            "status",
            "--address",
            f"{host}:{port}",
        ],
        capture_output=True,
        text=True,
        timeout=60,
        env={**__import__("os").environ, "RAY_TPU_JAX_PLATFORM": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout)
    assert summary["alive_nodes"] >= 1

    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "ray_tpu.scripts.cli",
            "list",
            "nodes",
            "--address",
            f"{host}:{port}",
        ],
        capture_output=True,
        text=True,
        timeout=60,
        env={**__import__("os").environ, "RAY_TPU_JAX_PLATFORM": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    nodes = json.loads(out.stdout)
    assert len(nodes) >= 1


# ---------------------------------------------------------------------------
# cluster-wide tracing: trace propagation + timeline merge
# ---------------------------------------------------------------------------


def test_trace_propagation_across_processes(cluster, tmp_path):
    """driver submit -> worker execute -> nested submit -> worker execute:
    all four spans share one trace_id and parent-link across >=2 processes,
    and a single `ray_tpu timeline` export carries task-state bars plus
    driver AND worker spans."""
    from ray_tpu.util import tracing

    tracing.enable_tracing()
    try:

        @ray_tpu.remote
        def obs_child():
            return os.getpid()

        @ray_tpu.remote
        def obs_parent():
            import os as _os

            child_pid = ray_tpu.get(obs_child.remote())
            return _os.getpid(), child_pid

        parent_pid, child_pid = ray_tpu.get(obs_parent.remote())
        driver_pid = os.getpid()
        assert len({driver_pid, parent_pid, child_pid}) >= 2

        def _find(spans, name, pid=None):
            return [
                s for s in spans
                if s.get("name") == name and (pid is None or s["pid"] == pid)
            ]

        # workers flush spans on a 1s cadence; poll the merged timeline
        deadline = time.time() + 20
        chain = None
        while time.time() < deadline and chain is None:
            trace = tracing.timeline()
            spans = [s for s in trace if s.get("span_id")]
            exec_children = _find(spans, "execute:obs_child", child_pid)
            exec_parents = _find(spans, "execute:obs_parent", parent_pid)
            submit_parents = _find(spans, "submit:obs_parent", driver_pid)
            sub_children = _find(spans, "submit:obs_child", parent_pid)
            for ec in exec_children:
                sc = [
                    s for s in sub_children
                    if s["span_id"] == ec["parent_id"]
                ]
                ep = [
                    s for s in exec_parents
                    if sc and s["span_id"] == sc[0]["parent_id"]
                ]
                sp = [
                    s for s in submit_parents
                    if ep and s["span_id"] == ep[0]["parent_id"]
                ]
                if sp:
                    chain = (sp[0], ep[0], sc[0], ec)
                    break
            if chain is None:
                time.sleep(0.5)
        assert chain is not None, "no linked span chain in timeline"
        trace_ids = {s["trace_id"] for s in chain}
        assert len(trace_ids) == 1  # one trace end to end
        # three processes in one chain: driver, parent worker, child worker
        assert {chain[0]["pid"], chain[1]["pid"], chain[3]["pid"]} == {
            driver_pid, parent_pid, child_pid,
        }

        # acceptance: ONE `ray_tpu timeline` export has task bars + both
        # driver and worker spans with the linkage intact
        node = ray_tpu._worker_api.get_node()
        host, port = node.gcs_address
        out_file = str(tmp_path / "timeline.json")
        import subprocess
        import sys

        proc = subprocess.run(
            [
                sys.executable, "-m", "ray_tpu.scripts.cli", "timeline",
                "--address", f"{host}:{port}", "-o", out_file,
            ],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "RAY_TPU_JAX_PLATFORM": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr
        doc = json.load(open(out_file))
        events = doc["traceEvents"]
        task_bars = [
            e for e in events
            if e.get("cat") == "NORMAL_TASK" and not e.get("span_id")
        ]
        assert task_bars, "no task-state bars in export"
        exported = {e.get("span_id") for e in events if e.get("span_id")}
        for span in chain:
            assert span["span_id"] in exported
        span_pids = {e["pid"] for e in events if e.get("span_id")}
        assert driver_pid in span_pids and parent_pid in span_pids
    finally:
        import ray_tpu.util.tracing as _t

        _t._enabled = os.environ.get("RAY_TPU_TRACE", "") not in ("", "0")


# ---------------------------------------------------------------------------
# metrics: collective/step/HBM exposure, exposition format, reaping
# ---------------------------------------------------------------------------


def test_collective_and_device_metrics_exposed(cluster):
    """Acceptance: prometheus_text carries collective bytes/latency,
    achieved-bandwidth, scaling-efficiency, and per-device HBM gauges."""
    import numpy as np

    from ray_tpu.collective.cpu_group import GcsStoreGroup
    from ray_tpu.util import metrics
    from ray_tpu.util.metrics import prometheus_text

    group = GcsStoreGroup(1, 0, "obs_group")
    out = group.allreduce(np.ones(1024, np.float32))
    assert float(out.sum()) == 1024.0
    group.barrier()

    sb = metrics.StepBreakdown(role="obs_test")
    with sb.step():
        time.sleep(0.01)
    with sb.step():
        time.sleep(0.01)
    assert metrics.scaling_efficiency("obs_test") is not None

    import jax  # noqa: F401 — make local devices visible to the sampler

    metrics.sample_device_memory()

    wanted = [
        'collective_bytes_total{op="allreduce",backend="gcs_store"',
        "collective_op_latency_ms_bucket",
        "collective_bandwidth_gb_s",
        'scaling_efficiency_ratio{role="obs_test"',
        "tpu_hbm_used_bytes",
        "tpu_hbm_limit_bytes",
    ]
    deadline = time.time() + 15
    text = ""
    while time.time() < deadline:
        text = prometheus_text()
        if all(w in text for w in wanted):
            break
        time.sleep(1)
    for w in wanted:
        assert w in text, f"missing {w}"
    summary = state.metrics_summary()
    assert summary["collective"]["allreduce"]["bytes"] >= 4096
    assert 0 < summary["scaling_efficiency"]["obs_test"] <= 1.0
    assert summary["devices"], "no device HBM rows"


def _parse_exposition(text):
    """Minimal Prometheus text-format parser: [(name, labels, value)]."""
    samples = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.fullmatch(
            r"([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (.+)", line
        )
        assert m, f"unparseable exposition line: {line!r}"
        name, labels_raw, value = m.groups()
        labels = {}
        if labels_raw:
            matched = 0
            for lm in re.finditer(r'([a-zA-Z_]\w*)="((?:[^"\\]|\\.)*)"',
                                  labels_raw):
                labels[lm.group(1)] = lm.group(2)
                matched += len(lm.group(0))
            # every byte of the label block must parse (catches raw quotes
            # and newlines leaking through)
            assert matched + labels_raw.count(",") == len(labels_raw), (
                f"malformed label block: {labels_raw!r}"
            )
        samples.append((name, labels, float(value)))
    return samples


def test_exposition_round_trip_and_bucket_monotonicity(cluster):
    from ray_tpu.util.metrics import Histogram, prometheus_text

    h = Histogram(
        "obs_roundtrip_ms", "round trip", boundaries=[1, 5, 25],
        tag_keys=("which",),
    )
    for v in (0.5, 3, 3, 10, 100):
        h.observe(v, tags={"which": "a"})
    deadline = time.time() + 15
    while time.time() < deadline:
        if "obs_roundtrip_ms_bucket" in prometheus_text():
            break
        time.sleep(1)
    samples = _parse_exposition(prometheus_text())
    by_series = {}
    counts = {}
    for name, labels, value in samples:
        if name.endswith("_bucket"):
            base = name[: -len("_bucket")]
            key = (base, tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            )))
            le = labels["le"]
            by_series.setdefault(key, []).append(
                (float("inf") if le == "+Inf" else float(le), value)
            )
        elif name.endswith("_count"):
            counts[(name[: -len("_count")], tuple(sorted(labels.items())))] = (
                value
            )
    assert by_series, "no histogram buckets in exposition output"
    for key, buckets in by_series.items():
        buckets.sort()
        values = [v for _, v in buckets]
        assert values == sorted(values), f"non-monotonic buckets: {key}"
        assert buckets[-1][0] == float("inf")
        total = counts.get(key)
        if total is not None:
            assert buckets[-1][1] == total
    ours = [
        b for (base, labels), b in by_series.items()
        if base == "obs_roundtrip_ms"
    ]
    assert ours and ours[0][-1][1] == 5


def test_label_values_escaped(cluster):
    """A label value with quote/backslash/newline must not corrupt the
    scrape (Prometheus exposition escaping)."""
    from ray_tpu.util.metrics import Counter, prometheus_text

    c = Counter("obs_escape_total", "escaping", tag_keys=("model",))
    c.inc(1, tags={"model": 'llama "7b"\\v1\nnightly'})
    deadline = time.time() + 15
    text = ""
    while time.time() < deadline:
        text = prometheus_text()
        if "obs_escape_total" in text:
            break
        time.sleep(1)
    assert '\\"7b\\"' in text and "\\\\v1" in text and "\\nnightly" in text
    line = next(
        ln for ln in text.splitlines() if ln.startswith("obs_escape_total")
    )
    assert "\n" not in line
    # the full scrape still parses sample-by-sample
    _parse_exposition(text)


def test_dead_worker_metrics_reaped(cluster):
    """The GCS drops ``metrics:<worker_id>`` KV entries when it observes
    that worker's death — dead workers' series must not outlive them."""
    from ray_tpu._internal.ids import WorkerID

    worker = ray_tpu._worker_api.get_core_worker()

    def _gcs(method, *args):
        return ray_tpu._worker_api.run_on_worker_loop(
            worker.client_pool.get(*worker.gcs_address).call(method, *args)
        )

    ghost = WorkerID.from_random()
    key = f"metrics:{ghost.hex()}"
    payload = {"worker_id": ghost.hex(), "node_id": "", "metrics": []}
    _gcs("kv_put", key, json.dumps(payload).encode(), True)
    assert _gcs("kv_get", key) is not None
    _gcs("report_worker_death", ghost, "test-kill")
    assert _gcs("kv_get", key) is None


def test_device_profile_writes_xplane(tmp_path):
    """jax.profiler wrapper produces an XPlane trace dir (SURVEY §5)."""
    import os

    import jax
    import jax.numpy as jnp

    from ray_tpu.util import tracing

    logdir = str(tmp_path / "prof")
    with tracing.device_profile(logdir):
        with tracing.annotate_device_trace("matmul_block"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(x @ x)
    found = []
    for root, _dirs, files in os.walk(logdir):
        found.extend(f for f in files if f.endswith((".pb", ".xplane.pb")))
    assert found, f"no profile artifacts under {logdir}"


def test_train_ft_metrics_units():
    """Train fault-tolerance metrics: counters, recovery histogram, and the
    exact-percentile sample path (process-local, no cluster needed)."""
    from ray_tpu.util import metrics

    before = metrics.train_ft_counters()
    metrics.record_train_resize("obs-run")
    metrics.record_train_restart("obs-run")
    metrics.record_collective_abort("obs-group")
    metrics.record_train_recovery("obs-run", 0.5, kind="resize")
    metrics.record_train_recovery("obs-run", 2.0, kind="restart")

    after = metrics.train_ft_counters()
    assert after["resizes"] == before["resizes"] + 1
    assert after["restarts"] == before["restarts"] + 1
    assert after["aborts"] == before["aborts"] + 1

    pct = metrics.train_recovery_percentiles()
    assert pct["count"] >= 2
    assert 0.0 < pct["p50_s"] <= pct["p99_s"] <= pct["max_s"]
    assert pct["max_s"] >= 2.0


def test_train_ft_summary_rollup():
    """train_ft_summary aggregates pushed metric snapshots from many
    processes into the cluster-wide fault-tolerance rollup the dashboard
    and `ray_tpu chaos list` serve."""
    from ray_tpu.util.metrics import train_ft_summary

    import json as _json

    payloads = [
        {
            "metrics": [
                {
                    "name": "train_resize_total",
                    "values": {_json.dumps(["a"]): 2.0},
                },
                {
                    "name": "collective_abort_total",
                    "values": {_json.dumps(["g"]): 3.0},
                },
                {
                    "name": "train_recovery_seconds",
                    # histogram snapshot: values = per-label sums, counts =
                    # per-label bucket observation counts
                    "values": {_json.dumps(["a", "resize"]): 3.0},
                    "counts": {_json.dumps(["a", "resize"]): [1, 1, 0]},
                },
            ]
        },
        {
            "metrics": [
                {
                    "name": "train_restart_total",
                    "values": {_json.dumps(["b"]): 1.0},
                }
            ]
        },
    ]
    out = train_ft_summary(payloads)
    assert out["resizes"] == 2.0
    assert out["restarts"] == 1.0
    assert out["aborts"] == 3.0
    assert out["recoveries"] == 2
    assert out["recovery_mean_s"] == pytest.approx(1.5)
