"""Multi-proxy ingress data plane: rendezvous-hash agreement, shared
SO_REUSEPORT listeners, proxy registry/drain/failover, and the per-proxy
metrics rollup (PR: production-scale ingress)."""

import http.client
import json
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.hash_ring import ReplicaRing


# -- ring units (no cluster) -------------------------------------------------


def test_ring_agreement_across_instances():
    """Any process building a ring from the same replica *set* — in any
    order — must pick the same winner for every key (the property that
    lets N proxies agree on the warm replica with no coordination)."""
    ids = [f"echo#replica-{i}" for i in range(8)]
    r1 = ReplicaRing(ids)
    r2 = ReplicaRing(list(reversed(ids)))
    for key in range(0, 50_000, 97):
        assert r1.lookup(key) == r2.lookup(key)


def test_ring_minimal_remap_on_membership_change():
    """Removing one replica moves ONLY the keys it owned (~1/n of them);
    every other key keeps its winner — warm KV blocks stay warm through a
    scale-down (the old sorted_ids[key % n] scheme remapped ~everything)."""
    ids = [f"r{i}" for i in range(8)]
    removed = "r3"
    before = ReplicaRing(ids)
    after = ReplicaRing([r for r in ids if r != removed])
    keys = list(range(0, 20_000, 7))
    owned = 0
    for k in keys:
        w = before.lookup(k)
        if w == removed:
            owned += 1
            assert after.lookup(k) != removed
        else:
            assert after.lookup(k) == w  # survivors keep every key
    # the removed replica owned roughly 1/8 of the keyspace
    assert 0.05 < owned / len(keys) < 0.25, owned / len(keys)


def test_ring_lookup_excluding():
    ring = ReplicaRing([f"r{i}" for i in range(4)])
    key = 123456
    winner = ring.lookup_index(key)
    alt = ring.lookup_excluding(key, {ring.ids[winner]})
    assert alt != winner
    # excluding everything falls back to the unfiltered winner (a
    # 1-replica deployment's restart is still worth a retry)
    assert ring.lookup_excluding(key, set(ring.ids)) == winner


# -- cluster tests -----------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=6)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _echo_deployment(num_replicas=2):
    @serve.deployment(num_replicas=num_replicas, max_ongoing_requests=32,
                      max_queued_requests=1024,
                      request_router_config=dict(prefix_affinity_tokens=4))
    class Echo:
        def __call__(self, payload):
            import os as _os

            return {"pid": _os.getpid()}

    return Echo


def _post(port, payload, timeout=10):
    """One request over a FRESH connection: the kernel re-picks which
    SO_REUSEPORT listener accepts it, so repeated calls spread across
    proxies."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/", json.dumps(payload).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, resp.headers.get("X-Proxy-Id"), body
    finally:
        conn.close()


def _post_retry(port, payload, deadline_s=30.0):
    """Retry connection errors and 503s (draining/dead proxy windows)
    until a 200 arrives — the client contract under proxy churn."""
    deadline = time.time() + deadline_s
    last = None
    while time.time() < deadline:
        try:
            status, proxy_id, body = _post(port, payload)
        except OSError as exc:
            last = exc
            time.sleep(0.2)
            continue
        if status == 200:
            return proxy_id, json.loads(body)
        last = (status, body)
        time.sleep(0.2)
    raise AssertionError(f"no 200 within {deadline_s}s: {last!r}")


def _fresh_serve(port, num_proxies):
    serve.shutdown()
    controller = serve.start(http_port=port, num_proxies=num_proxies)
    serve.run(_echo_deployment().bind(), name="ingress-app",
              route_prefix="/")
    return controller


def test_cross_proxy_pick_agreement_no_controller_roundtrip(cluster):
    """Two independent Routers (stand-ins for two proxy processes) warmed
    once must agree on the affinity pick for every key, and the pick loop
    itself must issue ZERO controller RPCs — the agreement comes from the
    shared rendezvous ring, not a round-trip."""
    from ray_tpu.serve.handle import Router
    from ray_tpu.util.metrics import rpc_calls_by_method

    @serve.deployment(num_replicas=3)
    class Who:
        def __call__(self, _):
            return None

    serve.run(Who.bind(), name="ringapp", _proxy=False)
    from ray_tpu.serve.api import _state as serve_state

    controller = serve_state["controller"]
    r1 = Router(controller, "ringapp")
    r2 = Router(controller, "ringapp")
    r1._refresh(force=True)
    r2._refresh(force=True)
    # suppress the periodic poll so the counters below measure ONLY the
    # pick loop (the poll is exercised elsewhere; here it would race)
    r1._REFRESH_S = r2._REFRESH_S = 1e9
    fetches = (r1.table_fetches, r2.table_fetches)
    before = rpc_calls_by_method().get("actor_task", 0.0)
    for key in range(200):
        rid1, _ = r1.pick("Who", affinity=key)
        rid2, _ = r2.pick("Who", affinity=key)
        assert rid1 == rid2, (key, rid1, rid2)
    after = rpc_calls_by_method().get("actor_task", 0.0)
    assert after == before  # no controller (actor) RPC per pick
    assert (r1.table_fetches, r2.table_fetches) == fetches
    assert r1.stats()["picks"] == 200
    serve.delete("ringapp")


def test_multiproxy_spread_affinity_metrics_drain(cluster):
    """One 2-proxy serve session, four claims (one session keeps the
    1-core tier-1 wall clock down): (a) proxies register in the GCS
    ``proxy:`` registry at start; (b) fresh connections spread across
    both SO_REUSEPORT listeners AND the same token-id prefix keeps
    landing on ONE serving replica — every proxy computes the same
    rendezvous winner locally; (c) per-proxy request counters roll up
    into metrics_summary()['ingress'] tagged by proxy_id; (d)
    drain_proxy 503s new work, deregisters, and traffic keeps
    succeeding through the survivor."""
    from ray_tpu.util import state as rt_state

    port = 18200
    controller = _fresh_serve(port, num_proxies=2)

    # (a) registry
    rows = rt_state.list_proxies()
    assert [r["proxy_id"] for r in rows] == ["http#0", "http#1"]
    assert all(r["port"] == port and r["pid"] for r in rows)

    # (b) spread + cross-proxy affinity agreement: sample the SAME
    # prefix over fresh connections until both proxies have terminated
    # at least one request (bounded) — the kernel picks the listener,
    # the rendezvous ring picks the replica
    payload = {"token_ids": [7, 7, 7, 7, 1, 2, 3]}
    pids, proxies = set(), set()
    deadline = time.time() + 30
    while time.time() < deadline and (
        len(proxies) < 2 or len(pids) == 0
    ):
        proxy_id, body = _post_retry(port, payload)
        proxies.add(proxy_id)
        pids.add(body["result"]["pid"])
    assert proxies == {"http#0", "http#1"}, proxies
    assert len(pids) == 1, pids

    # (c) proxies push metric snapshots on a ~1s cadence; poll the rollup
    deadline = time.time() + 15
    ingress = {}
    while time.time() < deadline:
        ingress = rt_state.metrics_summary()["ingress"]
        if ingress.get("num_proxies", 0) >= 2 and ingress.get(
            "requests_total", 0
        ) > 0:
            break
        time.sleep(0.5)
    assert ingress["num_proxies"] >= 2, ingress
    assert ingress["requests_total"] > 0
    for proxy_id in proxies:
        row = ingress["proxies"][proxy_id]
        assert row["requests"].get("ok", 0) > 0
        assert row["latency_ms"]["count"] > 0

    # (d) drain one proxy: deregisters, survivor keeps serving
    assert ray_tpu.get(
        controller.drain_proxy.remote("http#1"), timeout=30
    )
    assert [r["proxy_id"] for r in rt_state.list_proxies()] == ["http#0"]
    for _ in range(5):
        _post_retry(port, {"token_ids": [1]})


def test_proxy_kill_failover(cluster):
    """SIGKILL one of two proxies (ingress chaos): clients retrying
    connection errors keep succeeding on the survivor, and the
    controller's health poll deregisters the corpse."""
    from ray_tpu import testing
    from ray_tpu.util import state as rt_state

    port = 18206
    _fresh_serve(port, num_proxies=2)
    assert len(rt_state.list_proxies()) == 2
    killed_id, pid = testing.kill_serve_proxy("http#0")
    assert killed_id == "http#0" and pid
    # the survivor owns the port: retried traffic must keep flowing
    for _ in range(10):
        proxy_id, _ = _post_retry(port, {"token_ids": [2]})
        assert proxy_id in ("http#0", "http#1")
    deadline = time.time() + 30
    while time.time() < deadline:
        rows = rt_state.list_proxies()
        if [r["proxy_id"] for r in rows] == ["http#1"]:
            break
        time.sleep(0.5)
    assert [r["proxy_id"] for r in rt_state.list_proxies()] == ["http#1"]
    # post-mortem: the registry lifecycle is on the flight recorder
    # (event rings stream to the GCS on a ~1s cadence — poll, bounded)
    deadline = time.time() + 15
    events = set()
    while time.time() < deadline:
        events = {
            e.get("name") for e in rt_state.list_events(limit=2000)
        }
        if {"proxy_start", "proxy_stop"} <= events:
            break
        time.sleep(0.5)
    assert "proxy_start" in events, events
    assert "proxy_stop" in events, events
