"""Worker log capture: session log files, driver echo, logs state API.

Reference behavior: the per-node log monitor (_private/log_monitor.py) tails
worker stdout/stderr into /tmp/ray/session_*/logs and streams lines to the
driver when ray.init(log_to_driver=True); `ray logs` lists/fetches files.
"""

import time

import pytest


@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    yield ray_tpu
    ray_tpu.shutdown()


def _wait(pred, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.2)
    return False


def test_worker_prints_reach_log_files_and_driver(cluster, capfd):
    ray_tpu = cluster

    @ray_tpu.remote
    def shout(msg):
        print(msg)
        return msg

    marker = "log-capture-marker-12345"
    assert ray_tpu.get(shout.remote(marker)) == marker

    from ray_tpu.util import state

    # file side: the worker's session log file contains the line
    def in_files():
        logs = state.list_logs()
        for node_id, files in logs.items():
            for name in files:
                if marker in state.get_log(name, node_id=node_id):
                    return True
        return False

    assert _wait(in_files), "marker never appeared in session log files"

    # driver side: the pubsub echo printed it to stderr with a pid prefix
    def echoed():
        captured = capfd.readouterr()
        echoed.buf += captured.err
        return marker in echoed.buf and "(pid=" in echoed.buf

    echoed.buf = ""
    assert _wait(echoed), "marker was not echoed to the driver"


def test_list_logs_filters_by_node_prefix(cluster):
    ray_tpu = cluster

    @ray_tpu.remote
    def noop():
        return 1

    assert ray_tpu.get(noop.remote()) == 1
    from ray_tpu.util import state

    logs = state.list_logs()
    assert len(logs) == 1
    (node_id,) = logs
    assert state.list_logs(node_id=node_id[:8]) == logs
    # wrong prefix yields nothing
    other = "0" * 8 if not node_id.startswith("0" * 8) else "f" * 8
    assert state.list_logs(node_id=other) == {}


def test_read_log_is_sandboxed_to_log_dir(cluster):
    """read_log must not serve arbitrary paths."""
    ray_tpu = cluster

    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get(noop.remote())
    from ray_tpu.util import state

    assert state.get_log("../../../etc/passwd") == ""
    assert state.get_log("/etc/passwd") == ""
