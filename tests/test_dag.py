"""Compiled graphs (ray_tpu.dag) tests.

Models the reference's python/ray/dag/tests/experimental coverage: bind API,
interpreted execute, compile, multi-execution pipelining, multi-output,
actor-to-actor edges, error propagation, and teardown.
"""

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


@ray_tpu.remote
class Adder:
    def __init__(self, bias=0):
        self.bias = bias

    def add(self, x, y=0):
        return x + y + self.bias

    def boom(self, x):
        raise ValueError(f"boom {x}")

    def echo(self, x):
        return x


@ray_tpu.remote
def double(x):
    return 2 * x


def test_interpreted_function_dag(ray_start_regular):
    with InputNode() as inp:
        dag = double.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(3)) == 12


def test_interpreted_actor_dag(ray_start_regular):
    a = Adder.remote(10)
    with InputNode() as inp:
        dag = a.add.bind(inp, 5)
    assert ray_tpu.get(dag.execute(1)) == 16


def test_interpreted_class_node(ray_start_regular):
    with InputNode() as inp:
        node = Adder.bind(100)
        dag = node.add.bind(inp)
    assert ray_tpu.get(dag.execute(1)) == 101
    # the lazy actor is cached across executions
    assert ray_tpu.get(dag.execute(2)) == 102


def test_compiled_single_actor(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(41).get() == 42
        assert compiled.execute(-1).get() == 0
    finally:
        compiled.teardown()


def test_compiled_pipelined_executions(ray_start_regular):
    a = Adder.remote()
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        refs = [compiled.execute(i) for i in range(8)]
        assert [r.get() for r in refs] == list(range(8))
    finally:
        compiled.teardown()


def test_compiled_actor_chain(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(0).get() == 11
        assert compiled.execute(5).get() == 16
    finally:
        compiled.teardown()


def test_compiled_multi_output(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(2)
    with InputNode() as inp:
        dag = MultiOutputNode([a.add.bind(inp), b.add.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(10).get() == [11, 12]
    finally:
        compiled.teardown()


def test_compiled_input_attribute(ray_start_regular):
    a = Adder.remote()
    with InputNode() as inp:
        dag = a.add.bind(inp[0], inp[1])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(3, 4).get() == 7
    finally:
        compiled.teardown()


def test_compiled_error_propagation(ray_start_regular):
    a = Adder.remote()
    b = Adder.remote()
    with InputNode() as inp:
        dag = b.echo.bind(a.boom.bind(inp))
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(ValueError, match="boom 1"):
            compiled.execute(1).get()
        # the pipeline survives a failed execution
        with pytest.raises(ValueError, match="boom 2"):
            compiled.execute(2).get()
    finally:
        compiled.teardown()


def test_compiled_actor_still_callable(ray_start_regular):
    """Unlike the reference, normal .remote() calls keep working while a
    compiled loop is installed."""
    a = Adder.remote(5)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1).get() == 6
        assert ray_tpu.get(a.add.remote(10)) == 15
    finally:
        compiled.teardown()


def test_compile_rejects_function_nodes(ray_start_regular):
    with InputNode() as inp:
        dag = double.bind(inp)
    with pytest.raises(ValueError, match="actor method"):
        dag.experimental_compile()


def test_ref_single_consumption(ray_start_regular):
    a = Adder.remote()
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        ref = compiled.execute(1)
        assert ref.get() == 1
        with pytest.raises(ValueError):
            ref.get()
    finally:
        compiled.teardown()


def test_compiled_large_payloads_shm_path(ray_start_regular):
    """Payloads over the inline threshold ride reusable pinned arena slots
    (reference: mutable shared-memory channel objects,
    shared_memory_channel.py / node_manager.h:662 HandlePushMutableObject):
    many iterations must reuse slots correctly, including when the consumer
    HOLDS previous results (live zero-copy views defer slot recycling)."""
    import numpy as np

    @ray_tpu.remote
    class Scaler:
        def scale(self, x):
            return x * 2.0

    a = Scaler.remote()
    with InputNode() as inp:
        dag = a.scale.bind(inp)
    compiled = dag.experimental_compile()
    try:
        held = []
        for i in range(12):
            arr = np.full((300_000,), float(i), np.float32)  # ~1.2 MB
            out = compiled.execute(arr).get()
            assert out.shape == (300_000,)
            assert float(out[0]) == i * 2.0
            held.append(out)  # hold every result: slots must not be reused
            # while these views are alive, yet execution must not deadlock
        # all held values still intact (no slot was overwritten under us)
        for i, out in enumerate(held):
            assert float(out[0]) == i * 2.0, i
    finally:
        compiled.teardown()
