"""Pallas kernels vs XLA references (CPU interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
    reference_attention,
)
from ray_tpu.ops.rmsnorm import rmsnorm
from ray_tpu.ops.rope import apply_rope, rope_table


@pytest.fixture(scope="module")
def qkv():
    b, h, s, d = 2, 2, 256, 64
    ks = [jax.random.PRNGKey(i) for i in range(3)]
    return tuple(jax.random.normal(k, (b, h, s, d), jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward(qkv, causal):
    q, k, v = qkv
    out = flash_attention(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    assert float(jnp.abs(out - ref).max()) < 2e-2


def test_flash_lse_consistency(qkv):
    q, k, v = qkv
    out, lse = flash_attention_with_lse(q, k, v, causal=False)
    # direct lse computation
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(q.shape[-1])
    ref_lse = jax.scipy.special.logsumexp(s, axis=-1)
    assert float(jnp.abs(lse - ref_lse).max()) < 2e-2


def test_flash_grads(qkv):
    q, k, v = qkv

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        rel = float(jnp.abs(a - b).max()) / (float(jnp.abs(b).max()) + 1e-9)
        assert rel < 2e-2, rel


def test_flash_gqa(qkv):
    q, _, _ = qkv
    k = jax.random.normal(jax.random.PRNGKey(7), (2, 1, 256, 64))
    v = jax.random.normal(jax.random.PRNGKey(8), (2, 1, 256, 64))
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    assert float(jnp.abs(out - ref).max()) < 2e-2


def test_rmsnorm_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 96, 128))
    w = jax.random.normal(jax.random.PRNGKey(1), (128,)) * 0.1 + 1.0
    out = rmsnorm(x, w)
    ref = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
    assert float(jnp.abs(out - ref).max()) < 1e-4

    def loss_a(x, w):
        return (rmsnorm(x, w) ** 2).sum()

    def loss_b(x, w):
        return ((x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w) ** 2).sum()

    ga = jax.grad(loss_a, argnums=(0, 1))(x, w)
    gb = jax.grad(loss_b, argnums=(0, 1))(x, w)
    for a, b in zip(ga, gb):
        assert float(jnp.abs(a - b).max()) < 1e-2


def test_rope_properties():
    cos, sin = rope_table(128, 64)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 16, 64))
    rotated = apply_rope(x, cos, sin)
    # norms preserved per pair rotation
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(rotated), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )
    # offset slicing equals slicing the table
    shifted = apply_rope(x, cos, sin, offset=32)
    pad = jnp.zeros((1, 2, 32, 64), x.dtype)
    full = apply_rope(jnp.concatenate([pad, x], axis=2), cos, sin)[:, :, 32:]
    np.testing.assert_allclose(np.asarray(shifted), np.asarray(full), atol=1e-5)
