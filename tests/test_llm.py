"""ray_tpu.llm tests.

Models the reference's llm test surface (python/ray/llm/tests/): engine
generation correctness (the KV-cache decode path must match the full
forward pass token-for-token under greedy decoding), serve deployment
round trip, and the batch-inference stage.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu.llm import (
    GenerationRequest,
    LLMConfig,
    LLMEngine,
    LLMPredictor,
    build_llm_deployment,
)
from ray_tpu.models.llama import Llama, LlamaConfig, init_params
from ray_tpu.parallel.sharding import unbox_params


@pytest.fixture(scope="module")
def tiny_engine():
    cfg = LlamaConfig.tiny(max_seq_len=64)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    return cfg, params, LLMEngine(cfg, params, max_batch_size=4)


def _greedy_reference(cfg, params, prompt, n_new):
    """Greedy decoding via repeated FULL forward passes (no cache)."""
    model = Llama(cfg, None)
    toks = list(prompt)
    for _ in range(n_new):
        logits = model.apply(
            {"params": params}, jnp.asarray([toks], jnp.int32)
        )
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


@pytest.mark.slow
def test_cache_decode_matches_full_forward(tiny_engine):
    cfg, params, engine = tiny_engine
    prompt = [3, 14, 15, 92, 65, 35]
    n_new = 8
    ref = _greedy_reference(cfg, params, prompt, n_new)
    out = engine.generate(
        [GenerationRequest(token_ids=prompt, max_new_tokens=n_new)]
    )[0]
    assert out.token_ids == ref
    assert out.num_prompt_tokens == len(prompt)
    assert out.finished_reason == "length"


@pytest.mark.slow
def test_batched_same_length_prompts(tiny_engine):
    cfg, params, engine = tiny_engine
    prompts = [[1, 2, 3, 4], [9, 8, 7, 6], [5, 5, 5, 5]]
    outs = engine.generate(
        [GenerationRequest(token_ids=p, max_new_tokens=5) for p in prompts]
    )
    for p, o in zip(prompts, outs):
        assert o.token_ids == _greedy_reference(cfg, params, p, 5)


@pytest.mark.slow
def test_mixed_length_prompts_grouped(tiny_engine):
    cfg, params, engine = tiny_engine
    prompts = [[1, 2], [3, 4, 5, 6], [7, 8], [9, 10, 11, 12]]
    outs = engine.generate(
        [GenerationRequest(token_ids=p, max_new_tokens=4) for p in prompts]
    )
    for p, o in zip(prompts, outs):
        assert o.token_ids == _greedy_reference(cfg, params, p, 4)


@pytest.mark.slow
def test_eos_stops_generation(tiny_engine):
    cfg, params, engine = tiny_engine
    prompt = [3, 14, 15, 92]
    ref = _greedy_reference(cfg, params, prompt, 8)
    eos = ref[0]  # the first greedy token acts as EOS
    out = engine.generate(
        [GenerationRequest(token_ids=prompt, max_new_tokens=8,
                           eos_token_id=eos)]
    )[0]
    assert out.finished_reason == "eos"
    assert out.token_ids == [eos]


def test_temperature_sampling_changes_output(tiny_engine):
    _cfg, _params, engine = tiny_engine
    req = GenerationRequest(
        token_ids=[1, 2, 3, 4], max_new_tokens=16, temperature=5.0
    )
    a = engine.generate([req])[0].token_ids
    greedy = engine.generate(
        [GenerationRequest(token_ids=[1, 2, 3, 4], max_new_tokens=16)]
    )[0].token_ids
    # with very high temperature the trajectory should diverge from greedy
    assert a != greedy


def test_seq_len_guard(tiny_engine):
    _cfg, _params, engine = tiny_engine
    with pytest.raises(ValueError, match="max_seq_len"):
        engine.generate(
            [GenerationRequest(token_ids=[1] * 60, max_new_tokens=10)]
        )


@pytest.mark.slow
def test_llm_serve_deployment(ray_start_regular):
    from ray_tpu import serve

    llm_config = LLMConfig(
        model_id="llama-tiny",
        max_seq_len=64,
        max_new_tokens=4,
        resources_per_replica={"CPU": 1.0},
    )
    app = build_llm_deployment(llm_config)
    serve.start(proxy=False)
    handle = serve.run(app, name="llm-app", route_prefix=None, _proxy=False)
    try:
        resp = handle.remote({"token_ids": [1, 2, 3, 4], "max_new_tokens": 3})
        out = resp.result(timeout_s=120)
        assert len(out["token_ids"]) == 3
        assert out["finished_reason"] in ("length", "eos")
    finally:
        serve.shutdown()


@pytest.mark.slow
def test_llm_batch_stage(ray_start_regular):
    from ray_tpu import data as rd

    llm_config = LLMConfig(
        model_id="llama-tiny", max_seq_len=64, max_new_tokens=3
    )
    ds = rd.from_items(
        [{"token_ids": [i + 1, i + 2, i + 3]} for i in range(8)]
    )
    out = ds.map_batches(
        LLMPredictor,
        fn_constructor_args=(llm_config,),
        compute=rd.ActorPoolStrategy(size=1),
        batch_size=4,
    ).take_all()
    assert len(out) == 8
    assert all(len(r["generated"]) == 3 for r in out)


@pytest.mark.slow
class TestContinuousBatching:
    def test_matches_full_forward(self, tiny_engine):
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        cfg, params, _ = tiny_engine
        engine = ContinuousBatchingEngine(cfg, params, num_slots=4)
        prompt = [3, 14, 15, 92, 65, 35]
        ref = _greedy_reference(cfg, params, prompt, 8)
        rid = engine.add_request(
            GenerationRequest(token_ids=prompt, max_new_tokens=8)
        )
        results = engine.run_until_complete()
        assert results[rid].token_ids == ref
        assert results[rid].finished_reason == "length"

    def test_interleaved_mixed_lengths(self, tiny_engine):
        """Different prompt lengths decode TOGETHER in one pool (the whole
        point of continuous batching; the grouped LLMEngine cannot)."""
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        cfg, params, _ = tiny_engine
        engine = ContinuousBatchingEngine(cfg, params, num_slots=4)
        prompts = [[3, 14, 15], [92, 65, 35, 89, 79], [4], [31, 41]]
        refs = {
            engine.add_request(
                GenerationRequest(token_ids=p, max_new_tokens=6)
            ): _greedy_reference(cfg, params, p, 6)
            for p in prompts
        }
        results = engine.run_until_complete()
        for rid, ref in refs.items():
            assert results[rid].token_ids == ref, rid

    def test_late_admission_into_freed_slot(self, tiny_engine):
        """More requests than slots: later requests admit as slots free."""
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        cfg, params, _ = tiny_engine
        engine = ContinuousBatchingEngine(cfg, params, num_slots=2)
        prompts = [[3, 14], [92, 65, 35], [4, 5, 6, 7], [31]]
        refs = {
            engine.add_request(
                GenerationRequest(token_ids=p, max_new_tokens=4)
            ): _greedy_reference(cfg, params, p, 4)
            for p in prompts
        }
        # step manually: at most 2 slots busy at once
        results = {}
        while engine.num_active:
            for rid, res in engine.step():
                results[rid] = res
            assert len(engine._slots) <= 2
        for rid, ref in refs.items():
            assert results[rid].token_ids == ref, rid

    def test_eos_frees_slot(self, tiny_engine):
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        cfg, params, _ = tiny_engine
        engine = ContinuousBatchingEngine(cfg, params, num_slots=2)
        prompt = [3, 14, 15]
        ref = _greedy_reference(cfg, params, prompt, 8)
        eos = ref[2]  # force eos at the 3rd generated token
        rid = engine.add_request(
            GenerationRequest(
                token_ids=prompt, max_new_tokens=8, eos_token_id=eos
            )
        )
        results = engine.run_until_complete()
        assert results[rid].finished_reason == "eos"
        assert results[rid].token_ids == ref[:3]


class TestAdmission:
    """Regression tests for the CB admission path (slot bookkeeping and
    queue discipline, with and without the memory gate)."""

    def test_pending_fifo_under_full_slots(self, tiny_engine):
        """More requests than slots: admission order == arrival order."""
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        cfg, params, _ = tiny_engine
        engine = ContinuousBatchingEngine(cfg, params, num_slots=2)
        rids = [
            engine.add_request(
                GenerationRequest(token_ids=[i + 1, i + 2], max_new_tokens=6)
            )
            for i in range(5)
        ]
        admitted_order = []
        while engine.num_active:
            engine.step()
            for slot in engine._slots.values():
                if slot.request_id not in admitted_order:
                    admitted_order.append(slot.request_id)
        assert admitted_order == rids

    def test_slot_reuse_after_finish_at_admission(self, tiny_engine):
        """max_new_tokens=1 finishes AT admission: its slot must be handed
        to the next pending request in the same step, not leaked."""
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        cfg, params, _ = tiny_engine
        engine = ContinuousBatchingEngine(cfg, params, num_slots=1)
        r1 = engine.add_request(
            GenerationRequest(token_ids=[3, 14], max_new_tokens=1)
        )
        r2 = engine.add_request(
            GenerationRequest(token_ids=[15, 92], max_new_tokens=3)
        )
        finished = dict(engine.step())
        assert r1 in finished and len(finished[r1].token_ids) == 1
        # r2 took the freed slot within the same admission pass
        assert {s.request_id for s in engine._slots.values()} == {r2}
        results = engine.run_until_complete()
        assert len(results[r2].token_ids) == 3

    def test_finish_at_admission_via_eos(self, tiny_engine):
        cfg, params, _ = tiny_engine
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        engine = ContinuousBatchingEngine(cfg, params, num_slots=2)
        prompt = [3, 14, 15, 92]
        ref = _greedy_reference(cfg, params, prompt, 1)
        rid = engine.add_request(
            GenerationRequest(
                token_ids=prompt, max_new_tokens=8, eos_token_id=ref[0]
            )
        )
        results = engine.run_until_complete()
        assert results[rid].finished_reason == "eos"
        assert results[rid].token_ids == ref[:1]
        assert not engine._slots

    def test_run_until_complete_leaks_nothing(self, tiny_engine):
        """After draining, every per-request structure must be empty (a
        serving loop runs forever; any residue is a leak)."""
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        cfg, params, _ = tiny_engine
        engine = ContinuousBatchingEngine(cfg, params, num_slots=2)
        for i in range(6):
            engine.add_request(
                GenerationRequest(
                    token_ids=[i + 1, i + 2, i + 3],
                    max_new_tokens=1 + i % 3,
                )
            )
        results = engine.run_until_complete()
        assert len(results) == 6
        assert engine.num_active == 0
        assert not engine._slots
        assert not engine._pending
        assert not engine._finished_buf
        assert not engine._enqueue_ts

    def test_memory_gated_admission_preserves_fifo(self, tiny_engine):
        """With a KV pool too small for two prompts, the blocked request
        waits at the HEAD of the queue (no reordering, no crash) and
        admits after the holder retires."""
        from ray_tpu.kvcache import KVCacheManager
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        cfg, params, _ = tiny_engine
        kv = KVCacheManager(num_blocks=2, block_size=16)
        engine = ContinuousBatchingEngine(
            cfg, params, num_slots=4, kv_cache=kv
        )
        rids = [
            engine.add_request(
                GenerationRequest(
                    token_ids=list(range(b, b + 33)), max_new_tokens=4
                )
            )
            for b in (1, 100, 200)
        ]
        engine.step()
        assert len(engine._slots) == 1  # only the first fit
        assert [entry[0] for entry in engine._pending] == rids[1:]
        results = engine.run_until_complete()
        assert set(results) == set(rids)
        assert kv.stats()["admission_blocked"] >= 1
        assert engine.num_active == 0


def test_engine_seed_reproducible_and_per_instance():
    """Sampling seed control: an explicit seed reproduces the sampled
    stream exactly; different seeds diverge at high temperature (the old
    hardcoded PRNGKey(0) made every replica emit identical samples)."""
    cfg = LlamaConfig.tiny(max_seq_len=64)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    req = lambda: GenerationRequest(  # noqa: E731
        token_ids=[1, 2, 3, 4], max_new_tokens=16, temperature=5.0
    )
    a = LLMEngine(cfg, params, max_batch_size=2, seed=11).generate([req()])
    b = LLMEngine(cfg, params, max_batch_size=2, seed=11).generate([req()])
    c = LLMEngine(cfg, params, max_batch_size=2, seed=12).generate([req()])
    assert a[0].token_ids == b[0].token_ids
    assert a[0].token_ids != c[0].token_ids


@pytest.mark.slow
def test_tp_sharded_decode_matches_single_device():
    """Serving tensor parallelism: an engine over GSPMD-sharded params on a
    tp x fsdp mesh decodes token-for-token identically to the unsharded
    engine (the role vLLM's tensor_parallel_size plays behind ray.llm)."""
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.sharding import param_shardings

    cfg = LlamaConfig.tiny(max_seq_len=64)
    boxed = init_params(cfg, jax.random.PRNGKey(0))
    params = unbox_params(boxed)
    prompt = [3, 14, 15, 92, 65]

    ref_out = LLMEngine(cfg, params, max_batch_size=2).generate(
        [GenerationRequest(prompt, max_new_tokens=8)]
    )[0].token_ids

    mesh = make_mesh(8, tp=4, fsdp=2)
    sharded = jax.device_put(params, param_shardings(mesh, boxed))
    with mesh:
        tp_out = LLMEngine(cfg, sharded, mesh=mesh, max_batch_size=2).generate(
            [GenerationRequest(prompt, max_new_tokens=8)]
        )[0].token_ids
        from ray_tpu.llm import ContinuousBatchingEngine

        cb = ContinuousBatchingEngine(cfg, sharded, mesh=mesh, num_slots=2)
        rid = cb.add_request(GenerationRequest(prompt, max_new_tokens=8))
        cb_out = cb.run_until_complete()[rid].token_ids
    assert tp_out == ref_out
    assert cb_out == ref_out


def test_engine_generate_stream_matches_batch(tiny_engine):
    """generate_stream yields the same greedy tokens generate() produces,
    one at a time, ending with the summary GenerationResult."""
    cfg, params, engine = tiny_engine
    prompt = [3, 14, 15, 92, 65, 35]
    req = GenerationRequest(token_ids=prompt, max_new_tokens=6)
    ref = engine.generate([GenerationRequest(token_ids=prompt,
                                             max_new_tokens=6)])[0]
    items = list(engine.generate_stream(req))
    tokens, summary = items[:-1], items[-1]
    assert tokens == ref.token_ids
    assert summary.token_ids == ref.token_ids
    assert summary.finished_reason == ref.finished_reason
    assert summary.num_prompt_tokens == len(prompt)


@pytest.mark.slow
def test_llm_serve_token_streaming_e2e(ray_start_regular):
    """Token-streaming end-to-end through serve (the reference's
    DeploymentResponseGenerator path for ray.llm): the first token arrives
    before the full completion exists, and the streamed tokens equal the
    buffered result."""
    import time as _time

    from ray_tpu import serve

    llm_config = LLMConfig(
        model_id="llama-stream-tiny",
        max_seq_len=64,
        max_new_tokens=8,
        resources_per_replica={"CPU": 1.0},
    )
    app = build_llm_deployment(llm_config)
    serve.start(proxy=False)
    handle = serve.run(app, name="llm-stream", route_prefix=None, _proxy=False)
    try:
        request = {"token_ids": [1, 2, 3, 4], "max_new_tokens": 6}
        buffered = handle.remote(dict(request)).result(timeout_s=120)

        gen = handle.options(stream=True, method_name="stream").remote(
            dict(request)
        )
        t0 = _time.time()
        first = next(gen)
        first_latency = _time.time() - t0
        rest = list(gen)
        assert first["index"] == 0
        streamed_tokens = [first["token_id"]] + [
            d["token_id"] for d in rest if "token_id" in d
        ]
        summary = rest[-1]
        assert summary.get("finished") is True
        assert streamed_tokens == buffered["token_ids"]
        assert summary["token_ids"] == buffered["token_ids"]
        # TTFT sanity: the first token must not wait for the whole stream
        # (tiny model decodes fast; just assert it beat the full wall time)
        assert first_latency < 60
    finally:
        serve.shutdown()


@pytest.mark.slow
def test_llm_deployment_with_replica_autoscaling(ray_start_regular):
    """BASELINE configs[4]: LLM serving with replica autoscaling — the
    builder wires LLMConfig.autoscaling_config into the serve deployment
    and the controller scales engine replicas under request pressure."""
    import time

    from ray_tpu import serve

    llm_config = LLMConfig(
        model_id="llama-tiny",
        max_seq_len=64,
        max_new_tokens=8,
        resources_per_replica={"CPU": 0.5},
        autoscaling_config=dict(
            min_replicas=2,
            max_replicas=3,
            target_ongoing_requests=2,
        ),
    )
    app = build_llm_deployment(llm_config, name="llm-auto")
    serve.start(proxy=False)
    handle = serve.run(app, name="llm-auto-app", route_prefix=None, _proxy=False)
    try:
        def n_running():
            st = serve.status()["llm-auto-app"].deployments["llm-auto"]
            return sum(1 for r in st.replicas if r.state == "RUNNING")

        # the controller owns the replica count now: it must bring the
        # deployment up to the autoscaling floor (2 engine replicas), not
        # LLMConfig.num_replicas (1) — proves the config reached serve
        deadline = time.time() + 60
        while time.time() < deadline and n_running() < 2:
            time.sleep(0.5)
        assert n_running() >= 2, "autoscaler never reached min_replicas=2"
        out = handle.remote(
            {"token_ids": [1, 2, 3], "max_new_tokens": 8}
        ).result(timeout_s=120)
        assert out["finished_reason"] in ("length", "eos")
    finally:
        serve.shutdown()
