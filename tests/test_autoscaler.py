"""Autoscaler v2 tests.

Models the reference's autoscaler/v2 test approach: unit-test the
bin-packing scheduler with synthetic cluster states, then run the full
monitor loop against an in-process AutoscalingCluster with the fake node
provider (reference: tests using FakeMultiNodeProvider / AutoscalingCluster).
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalingConfig,
    NodeTypeConfig,
    ResourceScheduler,
)
from ray_tpu.cluster_utils import AutoscalingCluster


def _state(nodes=(), demands=(), pgs=()):
    return {
        "nodes": list(nodes),
        "pending_demands": list(demands),
        "pending_placement_groups": list(pgs),
    }


def _node(total, avail=None, labels=None, alive=True, head=False):
    return {
        "node_id": object(),
        "alive": alive,
        "is_head": head,
        "resources_total": total,
        "available": total if avail is None else avail,
        "labels": labels or {},
    }


CFG = AutoscalingConfig(
    node_types=[
        NodeTypeConfig("cpu-small", {"CPU": 4}, max_workers=5),
        NodeTypeConfig("tpu-v5e-8", {"CPU": 8, "TPU": 8},
                       labels={"ray.io/tpu-pod-type": "v5litepod-8"},
                       max_workers=4),
    ],
    max_workers=10,
)


class TestScheduler:
    def test_no_demand_no_launch(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(_state(nodes=[_node({"CPU": 4})]), {})
        assert d.launches == {}

    def test_fits_existing_capacity(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(nodes=[_node({"CPU": 4})],
                   demands=[{"resources": {"CPU": 2}, "count": 2}]),
            {},
        )
        assert d.launches == {}

    def test_launches_smallest_feasible_type(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(demands=[{"resources": {"CPU": 2}, "count": 1}]), {}
        )
        assert d.launches == {"cpu-small": 1}

    def test_tpu_demand_launches_tpu_type(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(demands=[{"resources": {"TPU": 8}, "count": 1}]), {}
        )
        assert d.launches == {"tpu-v5e-8": 1}

    def test_label_selector_routes_to_labeled_type(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(demands=[{
                "resources": {"CPU": 1},
                "label_selector": {"ray.io/tpu-pod-type": "v5litepod-8"},
                "count": 1,
            }]),
            {},
        )
        assert d.launches == {"tpu-v5e-8": 1}

    def test_bin_packs_multiple_demands_one_node(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(demands=[{"resources": {"CPU": 1}, "count": 4}]), {}
        )
        assert d.launches == {"cpu-small": 1}

    def test_max_workers_cap(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(demands=[{"resources": {"CPU": 4}, "count": 20}]), {}
        )
        assert d.launches["cpu-small"] == 5  # per-type cap
        assert d.infeasible

    def test_infeasible_demand_reported(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(demands=[{"resources": {"GPU": 1}, "count": 1}]), {}
        )
        assert d.launches == {}
        assert d.infeasible

    def test_strict_spread_pg_one_node_per_bundle(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(pgs=[{
                "strategy": "STRICT_SPREAD",
                "bundles": [{"CPU": 2}, {"CPU": 2}, {"CPU": 2}],
            }]),
            {},
        )
        assert d.launches == {"cpu-small": 3}

    def test_pack_pg_shares_nodes(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(pgs=[{
                "strategy": "PACK",
                "bundles": [{"CPU": 2}, {"CPU": 2}],
            }]),
            {},
        )
        assert d.launches == {"cpu-small": 1}

    def test_inflight_launches_counted(self):
        s = ResourceScheduler(CFG)
        d = s.schedule(
            _state(demands=[{"resources": {"CPU": 4}, "count": 5}]),
            {"cpu-small": 4},
        )
        assert d.launches.get("cpu-small", 0) <= 1


@pytest.fixture
def autoscaling_cluster():
    cluster = AutoscalingCluster(
        head_resources={"CPU": 1},
        worker_node_types=[
            dict(name="cpu-worker", resources={"CPU": 2}, max_workers=3),
            dict(name="tpu-worker", resources={"CPU": 2, "TPU": 4},
                 labels={"ray.io/tpu-pod-type": "v5litepod-4"},
                 max_workers=2),
        ],
        idle_timeout_s=2.0,
        update_interval_s=0.25,
    )
    cluster.start()
    cluster.connect()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_scale_up_on_demand(autoscaling_cluster):
    """An infeasible-now TPU task triggers a tpu-worker launch and runs."""

    @ray_tpu.remote(num_cpus=1, num_tpus=4)
    def tpu_task():
        return "ran"

    ref = tpu_task.remote()
    assert ray_tpu.get(ref, timeout=60) == "ran"
    types = {
        i.node_type for i in autoscaling_cluster.provider.non_terminated_nodes()
    }
    assert "tpu-worker" in types


def test_scale_up_many_tasks(autoscaling_cluster):
    @ray_tpu.remote(num_cpus=2)
    def heavy(i):
        time.sleep(0.2)
        return i

    refs = [heavy.remote(i) for i in range(6)]
    assert sorted(ray_tpu.get(refs, timeout=60)) == list(range(6))
    assert len(autoscaling_cluster.provider.non_terminated_nodes()) >= 1


def test_scale_down_when_idle(autoscaling_cluster):
    @ray_tpu.remote(num_cpus=2)
    def quick():
        return 1

    assert ray_tpu.get(quick.remote(), timeout=60) == 1
    deadline = time.time() + 30
    while time.time() < deadline:
        if not autoscaling_cluster.provider.non_terminated_nodes():
            break
        time.sleep(0.5)
    assert not autoscaling_cluster.provider.non_terminated_nodes()


def test_tpu_slice_provider_scales_pending_slice_up_and_down(shutdown_only):
    """A pending slice reservation scales the cluster up by EXACTLY one
    whole slice (all hosts, atomically: head resource on worker 0, slice
    labels on every host), and the slice retires as one unit after idle
    timeout (reference: slice-granular node groups,
    _private/accelerators/tpu.py:213, gcp/node_provider.py:63)."""
    from ray_tpu.autoscaler import TpuSliceProvider, tpu_slice_node_type
    from ray_tpu.util.tpu import reserve_tpu_slice

    slice_type = tpu_slice_node_type(
        "v5e-16", cpus_per_host=2.0, min_slices=0, max_slices=2
    )
    assert slice_type.group_size == 2  # v5e-16 = 2 hosts x 8 chips

    cluster = AutoscalingCluster(
        head_resources={"CPU": 2},
        worker_node_types=[slice_type],
        idle_timeout_s=3.0,
        update_interval_s=0.25,
        provider_cls=TpuSliceProvider,
    )
    cluster.start()
    cluster.connect()
    try:
        # no TPU nodes yet; reserving a slice parks a pending head-resource
        # PG the autoscaler must satisfy by launching ONE slice
        reservation = reserve_tpu_slice("v5e-16", timeout=120.0)
        assert reservation.num_hosts == 2

        instances = cluster.provider.non_terminated_nodes()
        assert len(instances) == 1, [i.instance_id for i in instances]

        # exactly one slice: 2 TPU hosts sharing one slice name, head has 3
        nodes = [n for n in ray_tpu.nodes() if n["Resources"].get("TPU")]
        assert len(nodes) == 2
        slice_names = {
            n["Labels"]["ray.io/tpu-slice-name"] for n in nodes
        }
        assert len(slice_names) == 1
        heads = [
            n for n in nodes
            if any(k.endswith("-head") for k in n["Resources"])
        ]
        assert len(heads) == 1

        # release the reservation: the slice idles and retires WHOLE
        reservation.release()
        deadline = time.time() + 60
        while time.time() < deadline:
            if not cluster.provider.non_terminated_nodes():
                break
            time.sleep(0.5)
        assert not cluster.provider.non_terminated_nodes()
        assert not [
            n for n in ray_tpu.nodes()
            if n["Alive"] and n["Resources"].get("TPU")
        ]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.mark.slow
def test_tpu_slice_partial_launch_rolls_back(shutdown_only):
    """Chaos: a host launch failing mid-slice must roll back the already
    launched hosts — the cluster never holds a partial ICI domain."""
    from ray_tpu.autoscaler import TpuSliceProvider, tpu_slice_node_type
    from ray_tpu.cluster_utils import Cluster

    slice_type = tpu_slice_node_type("v5e-16", min_slices=0, max_slices=2)
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        config = AutoscalingConfig(
            node_types=[slice_type], idle_timeout_s=60, update_interval_s=0.25
        )
        provider = TpuSliceProvider(cluster, config)
        launched = []
        real_add = cluster.add_node

        def flaky_add(**kw):
            if launched:
                raise RuntimeError("host 1 failed to boot")
            launched.append(1)
            return real_add(**kw)

        cluster.add_node = flaky_add
        with pytest.raises(RuntimeError, match="host 1"):
            provider.create_node(slice_type.name)
        assert provider.non_terminated_nodes() == []
        # the half-launched host 0 was rolled back: its raylet was killed
        # non-gracefully, so the GCS flags it dead after the health window
        cluster.add_node = real_add
        cluster.connect()
        import ray_tpu as rt

        deadline = time.time() + 30
        while time.time() < deadline:
            live_tpu = [
                n for n in rt.nodes()
                if n["Alive"] and n["Resources"].get("TPU")
            ]
            if not live_tpu:
                break
            time.sleep(0.5)
        assert not live_tpu
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_autoscaler_monitor_survives_provider_chaos(shutdown_only):
    """Chaos: the provider raising mid-reconcile (every create fails) must
    not kill the monitor loop; once the provider heals, scale-up happens."""
    from ray_tpu.autoscaler import FakeMultiNodeProvider

    class FlakyProvider(FakeMultiNodeProvider):
        fail = True

        def create_node(self, node_type_name):
            if FlakyProvider.fail:
                raise RuntimeError("cloud API down")
            return super().create_node(node_type_name)

    cluster = AutoscalingCluster(
        head_resources={"CPU": 1},
        worker_node_types=[
            dict(name="cpu-big", resources={"CPU": 4}, min_workers=0,
                 max_workers=2)
        ],
        idle_timeout_s=60.0,
        update_interval_s=0.2,
        provider_cls=FlakyProvider,
    )
    cluster.start()
    cluster.connect()
    try:
        @ray_tpu.remote(num_cpus=4)
        def big():
            return 99

        ref = big.remote()  # infeasible until a cpu-big node appears
        time.sleep(1.5)  # several failing reconcile ticks
        assert cluster.provider.non_terminated_nodes() == []
        FlakyProvider.fail = False  # provider heals
        assert ray_tpu.get(ref, timeout=120) == 99
        assert len(cluster.provider.non_terminated_nodes()) >= 1
    finally:
        cluster.shutdown()
