"""Partition-tolerance tests: network chaos mesh, retryable transport with a
per-link circuit breaker, and split-brain fencing (reference model: the
chaos/network-failure suites driven by RAY_testing_rpc_failure plus the GCS
health-check manager's suspect/dead machinery)."""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu._internal import rpc as rpc_mod
from ray_tpu._internal.rpc import RpcError


# ---------------------------------------------------------------------------
# Unit: chaos mesh plan evaluation
# ---------------------------------------------------------------------------


def _mesh(rules, seed=42):
    rpc_mod.set_rpc_chaos({"seed": seed, "rules": rules})


def test_chaos_plan_deterministic_under_seed():
    """The same seed yields the same fault sequence — chaos runs replay."""
    rules = [{"method": "*", "fail": 0.5, "delay_ms": 1.0, "jitter_ms": 3.0}]
    try:
        _mesh(rules)
        seq1 = [rpc_mod._chaos_plan("m", None, "h:1") for _ in range(32)]
        _mesh(rules)
        seq2 = [rpc_mod._chaos_plan("m", None, "h:1") for _ in range(32)]
        assert seq1 == seq2
        assert any(a == "fail" for _, a in seq1)
        assert any(a is None for _, a in seq1)
    finally:
        rpc_mod.set_rpc_chaos({})


def test_chaos_rule_directional_match():
    """A src/dst-scoped rule drops A->B while B->A flows: directional
    partitions, not symmetric ones."""
    try:
        _mesh([{"src": "aa", "dst": "h:1", "fail": 1.0}])
        assert rpc_mod._chaos_plan("m", "aabbcc", "h:1")[1] == "fail"
        # other direction / other peer / anonymous caller: untouched
        assert rpc_mod._chaos_plan("m", "bbaacc", "h:1")[1] is None
        assert rpc_mod._chaos_plan("m", "aabbcc", "h:2")[1] is None
        assert rpc_mod._chaos_plan("m", None, "h:1")[1] is None
    finally:
        rpc_mod.set_rpc_chaos({})


def test_chaos_exempt_methods_never_faulted():
    """chaos_fetch distributes the spec itself: healing a partition must
    propagate through the partition, so the mesh never touches it."""
    try:
        _mesh([{"method": "*", "fail": 1.0, "blackhole": True}])
        assert rpc_mod._chaos_plan("chaos_fetch", "aa", "h:1") == (0.0, None)
        assert rpc_mod._chaos_plan("kv_get", "aa", "h:1")[1] is not None
    finally:
        rpc_mod.set_rpc_chaos({})


# ---------------------------------------------------------------------------
# Unit: retryable transport + circuit breaker
# ---------------------------------------------------------------------------


class _FlakyClient:
    name = "fake"

    def __init__(self, fail_times, exc=None):
        self.calls = 0
        self.fail_times = fail_times
        self.exc = exc or rpc_mod._transport_error("boom")

    async def call(self, method, *args, timeout=None, **kwargs):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise self.exc
        return "ok"


def test_retry_call_recovers_from_transient_failures():
    c = _FlakyClient(2)
    out = asyncio.run(
        rpc_mod.retry_call(c, "m", attempts=3, timeout=1.0, backoff_s=0.001)
    )
    assert out == "ok"
    assert c.calls == 3


def test_retry_call_exhausts_attempts():
    c = _FlakyClient(10)
    with pytest.raises(RpcError, match="boom"):
        asyncio.run(rpc_mod.retry_call(c, "m", attempts=3, backoff_s=0.001))
    assert c.calls == 3


def test_retry_call_does_not_retry_application_errors():
    """Remote handler exceptions prove the link is alive — only transport
    failures are retried."""
    c = _FlakyClient(10, exc=ValueError("app bug"))
    with pytest.raises(ValueError):
        asyncio.run(rpc_mod.retry_call(c, "m", attempts=5, backoff_s=0.001))
    assert c.calls == 1


def test_retry_call_respects_total_timeout():
    c = _FlakyClient(1000)
    t0 = time.perf_counter()
    with pytest.raises(RpcError):
        asyncio.run(
            rpc_mod.retry_call(
                c, "m", attempts=1000, total_timeout=0.3, backoff_s=0.05
            )
        )
    assert time.perf_counter() - t0 < 2.0


def test_circuit_breaker_transitions():
    """closed -> open after N consecutive transport failures -> half_open
    probe after the cooldown -> closed on success (reopens on a half-open
    failure without re-counting to the threshold)."""
    rpc_mod.configure_circuit_breaker(3, 60.0)
    try:
        c = rpc_mod.RpcClient("127.0.0.1", 1, name="breaker-test")
        for _ in range(2):
            c._breaker_record(False)
        assert c._breaker_state == "closed"  # below threshold
        c._breaker_record(False)
        assert c._breaker_state == "open"
        with pytest.raises(RpcError, match="circuit open"):
            c._breaker_check()
        # cooldown elapses: one probe allowed through
        c._breaker_opened_at -= 120.0
        c._breaker_check()
        assert c._breaker_state == "half_open"
        c._breaker_record(False)  # failed probe reopens immediately
        assert c._breaker_state == "open"
        c._breaker_opened_at -= 120.0
        c._breaker_check()
        c._breaker_record(True)
        assert c._breaker_state == "closed"
        assert c._breaker_failures == 0
    finally:
        rpc_mod.configure_circuit_breaker(5, 2.0)


def test_batcher_fails_fast_on_closing_writer():
    """Reconnect race: a frame enqueued into a writer the recv loop is
    tearing down must fail the caller immediately, not strand its future."""

    class _ClosingWriter:
        def is_closing(self):
            return True

    async def go():
        batcher = rpc_mod._FrameBatcher(_ClosingWriter())
        with pytest.raises(ConnectionResetError):
            await batcher.enqueue([b"frame"])

    asyncio.run(go())


# ---------------------------------------------------------------------------
# Integration: blackhole -> typed error within the deadline, breaker opens
# ---------------------------------------------------------------------------


def test_blackhole_typed_error_and_circuit_opens(shutdown_only):
    """A blackholed link surfaces a typed transport error at the caller's
    deadline (never an unbounded hang); repeated failures open the per-link
    circuit so later calls fail fast; clearing the mesh lets the half-open
    probe close it again."""
    ray_tpu.init(num_cpus=2)
    from ray_tpu import _worker_api

    worker = _worker_api.get_core_worker()
    host, port = worker.gcs_address
    # a dedicated client: the pooled GCS client also carries the worker's
    # background traffic, whose successes reset the consecutive-failure
    # count mid-test on a loaded box (the breaker is per-client state)
    gcs = rpc_mod.RpcClient(host, port, name="breaker-probe")

    def call_once(timeout):
        return _worker_api.run_on_worker_loop(
            gcs.call("list_placement_groups", timeout=timeout)
        )

    rpc_mod.configure_circuit_breaker(3, 0.5)
    try:
        rpc_mod.set_rpc_chaos({
            "seed": 5,
            "rules": [{
                "method": "list_placement_groups",
                "dst": f"{host}:{port}",
                "blackhole": True,
            }],
        })
        t0 = time.perf_counter()
        with pytest.raises(RpcError, match="blackhole"):
            call_once(1.0)
        elapsed = time.perf_counter() - t0
        assert 0.9 <= elapsed < 5.0, f"blackhole surfaced in {elapsed:.2f}s"
        for _ in range(2):
            with pytest.raises(RpcError):
                call_once(0.3)
        assert gcs._breaker_state == "open"
        t0 = time.perf_counter()
        with pytest.raises(RpcError, match="circuit open"):
            call_once(5.0)
        assert time.perf_counter() - t0 < 0.2, "open circuit must fail fast"
        # heal: clear the mesh, wait out the cooldown, probe closes the link
        rpc_mod.set_rpc_chaos({})
        time.sleep(0.6)
        assert isinstance(call_once(5.0), list)
        assert gcs._breaker_state == "closed"
    finally:
        rpc_mod.set_rpc_chaos({})
        rpc_mod.configure_circuit_breaker(5, 2.0)
        _worker_api.run_on_worker_loop(gcs.close())


def test_dropped_call_does_not_stall_actor_sequence(shutdown_only):
    """A chaos-dropped actor call must not wedge the actor for its caller:
    the abandoned call leaves a hole in the per-caller in-order seq stream,
    and the next call's sequence watermark tells the executor to skip it.
    Before the watermark, every later call parked behind the hole forever
    (the exact stall the chaos soak surfaced)."""
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote(num_cpus=0)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote(), timeout=30) == 1
    rpc_mod.set_rpc_chaos(
        {"seed": 2, "rules": [{"method": "actor_task", "fail": 1.0}]}
    )
    try:
        with pytest.raises(Exception):
            ray_tpu.get(c.bump.remote(), timeout=30)
    finally:
        rpc_mod.set_rpc_chaos({})
    # the dropped bump never executed; the next call must skip its seq
    # hole and run promptly, observing exactly one prior increment
    assert ray_tpu.get(c.bump.remote(), timeout=10) == 2


# ---------------------------------------------------------------------------
# Integration: split-brain — directional partition, fencing, failover
# ---------------------------------------------------------------------------


def _pump(handle, counts, n):
    for _ in range(n):
        try:
            assert handle.remote(21).result(timeout_s=20) == 42
            counts["ok"] += 1
        except Exception as e:  # noqa: BLE001 — tallied, asserted at the end
            counts["fail"] += 1
            counts["errors"].append(repr(e))


def test_split_brain_fencing_and_failover():
    """The headline partition scenario: a serve replica's node loses its
    route TO the GCS (directional — GCS->node probes still flow). The GCS
    marks the node SUSPECT, the controller replaces the replica, the
    partitioned raylet self-fences (its replica rejects work with the typed
    retryable NodeFencedError instead of double-serving), live clients see
    100% success throughout, and healing the partition unfences the node
    back to ALIVE."""
    from ray_tpu import serve, testing
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import state

    cluster = Cluster(
        head_node_args={"num_cpus": 2},
        _system_config={
            "health_check_period_s": 0.5,
            "suspect_after_s": 2.5,
            "fence_after_s": 1.0,
            "health_check_timeout_s": 30.0,
            "chaos_poll_period_s": 0.25,
        },
    )
    try:
        cluster.connect()

        # Occupy one head CPU so the deployment's second replica MUST land
        # on node B; killed later to make room for the replacement.
        @ray_tpu.remote(num_cpus=1)
        class Blocker:
            def ping(self):
                return "ok"

        blocker = Blocker.remote()
        assert ray_tpu.get(blocker.ping.remote(), timeout=60) == "ok"

        node_b = cluster.add_node(num_cpus=1)
        node_b_hex = node_b.node_id.hex()
        gcs_host, gcs_port = cluster.gcs_address

        @serve.deployment(num_replicas=2)
        class Doubler:
            def __call__(self, x):
                return x * 2

        handle = serve.run(Doubler.bind(), name="splitapp", _proxy=False)

        def replica_rows():
            return [
                r for r in testing.list_serve_replicas("splitapp")
                if r["state"] == "RUNNING" and r["pid"]
            ]

        deadline = time.time() + 60
        while time.time() < deadline:
            rows = replica_rows()
            if len(rows) == 2 and any(
                r.get("node_id") == node_b_hex for r in rows
            ):
                break
            time.sleep(0.2)
        rows = replica_rows()
        victim = [r for r in rows if r.get("node_id") == node_b_hex]
        assert victim, f"no replica landed on node B: {rows}"
        victim_id = victim[0]["replica_id"]

        counts = {"ok": 0, "fail": 0, "errors": []}
        _pump(handle, counts, 10)  # steady state before the partition

        # Directional partition: node B -> GCS drops; GCS -> node B flows.
        testing.set_network_chaos({
            "seed": 1,
            "rules": [{
                "src": node_b_hex[:12],
                "dst": f"{gcs_host}:{gcs_port}",
                "fail": 1.0,
            }],
        })
        ray_tpu.kill(blocker)  # head room for the replacement replica
        t_partition = time.time()

        # GCS: stale reports + probe verdict -> SUSPECT (not yet DEAD).
        suspect_seen = False
        deadline = time.time() + 30
        while time.time() < deadline:
            _pump(handle, counts, 3)
            states = {n["node_id"]: n["state"] for n in state.list_nodes()}
            if states.get(node_b_hex) == "SUSPECT":
                suspect_seen = True
                break
        assert suspect_seen, "node B never became SUSPECT"

        # Controller: the replica on the suspect node is replaced on a
        # healthy node — back to 2 RUNNING with the victim gone.
        deadline = time.time() + 60
        replaced = False
        while time.time() < deadline:
            _pump(handle, counts, 3)
            rows = replica_rows()
            ids = {r["replica_id"] for r in rows}
            if victim_id not in ids and len(rows) == 2:
                replaced = True
                break
        assert replaced, f"victim {victim_id} never replaced: {replica_rows()}"
        assert all(r.get("node_id") != node_b_hex for r in replica_rows())

        # Heal: clear the mesh; node B's next report unfences + clears
        # SUSPECT without a restart ("clean re-register").
        testing.clear_network_chaos()
        deadline = time.time() + 30
        healed = False
        while time.time() < deadline:
            _pump(handle, counts, 3)
            states = {n["node_id"]: n["state"] for n in state.list_nodes()}
            if states.get(node_b_hex) == "ALIVE":
                healed = True
                break
        assert healed, "node B never returned to ALIVE after healing"
        assert time.time() - t_partition < 120

        # Live traffic saw 100% success through the whole partition.
        assert counts["fail"] == 0, f"client failures: {counts['errors'][:5]}"
        assert counts["ok"] >= 20

        # Flight recorder: the full suspect -> fence -> unfence lifecycle.
        deadline = time.time() + 20
        names = set()
        while time.time() < deadline:
            names = {e.get("name") for e in state.list_events(limit=5000)}
            if {"node_suspect", "node_fenced", "node_unfenced"} <= names:
                break
            time.sleep(0.5)
        assert "node_suspect" in names
        assert "node_fenced" in names
        assert "node_unfenced" in names

        # The fenced replica rejected work with the typed retryable error:
        # the handle recorded NodeFencedError failovers (not silent drops).
        retry_events = [
            e for e in state.list_events(limit=5000, name="request_retry")
            if e.get("reason") == "NodeFencedError"
        ]
        assert retry_events, "no NodeFencedError failover was recorded"
    finally:
        try:
            from ray_tpu import serve as _serve

            _serve.shutdown()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        finally:
            cluster.shutdown()
