"""Multi-agent RLlib + connector pipelines (reference:
rllib/env/multi_agent_env.py:30, connectors/connector_pipeline_v2.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import rllib


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 0})
    yield
    ray_tpu.shutdown()


class CoopMatch(rllib.MultiAgentEnv):
    """2-agent cooperative toy: both agents see the same Discrete(3)
    context; each earns +0.2 for matching it, and BOTH earn +1 more when
    both match simultaneously (the cooperative coupling). Episodes run 8
    steps with a fresh context each step; max team return/episode ~= 19.2."""

    possible_agents = ("a0", "a1")
    EP_LEN = 8
    N = 3

    def __init__(self, config=None):
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._ctx = 0

    def observation_space(self, agent_id):
        import gymnasium as gym

        return gym.spaces.Discrete(self.N)

    def action_space(self, agent_id):
        import gymnasium as gym

        return gym.spaces.Discrete(self.N)

    def _obs(self):
        return {a: self._ctx for a in self.possible_agents}

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._ctx = int(self._rng.integers(self.N))
        return self._obs(), {}

    def step(self, action_dict):
        hits = {a: int(action_dict[a]) == self._ctx for a in self.possible_agents}
        both = all(hits.values())
        rewards = {
            a: 0.2 * hits[a] + (1.0 if both else 0.0)
            for a in self.possible_agents
        }
        self._t += 1
        done = self._t >= self.EP_LEN
        self._ctx = int(self._rng.integers(self.N))
        terms = {a: done for a in self.possible_agents}
        terms["__all__"] = done
        truncs = {a: False for a in self.possible_agents}
        truncs["__all__"] = False
        return self._obs(), rewards, terms, truncs, {}


def test_multi_agent_ppo_learns_cooperative_toy(cluster):
    """The verdict's acceptance bar: multi-agent PPO learns a 2-agent
    cooperative toy in-suite (shared policy — parameter sharing)."""
    config = (
        rllib.MultiAgentPPOConfig()
        .environment(CoopMatch)
        .multi_agent(
            policies=["shared"], policy_mapping_fn=lambda agent_id: "shared"
        )
        .env_runners(num_env_runners=1, rollout_fragment_length=128)
        .training(lr=5e-3, num_epochs=6, minibatch_size=64, entropy_coeff=0.0)
        .debugging(seed=7)
    )
    algo = config.build()
    try:
        first = algo.train()
        result = first
        for _ in range(25):
            result = algo.train()
            if result["episode_return_mean"] > 15.0:
                break
        # random play: P(match)=1/3 per agent -> E[return] ~ 8*(2*0.2/3+2/9)
        # ~= 2.8; learned play approaches ~19.2
        assert result["episode_return_mean"] > 15.0, result
        assert result["episode_return_mean"] > first["episode_return_mean"]
        assert "shared/loss" in result or any(
            k.startswith("shared/") for k in result
        )
    finally:
        algo.stop()


def test_multi_agent_separate_policies_and_checkpoint(cluster, tmp_path):
    """Two separate policies update independently and round-trip a checkpoint."""
    config = (
        rllib.MultiAgentPPOConfig()
        .environment(CoopMatch)
        .multi_agent(
            policies=["p0", "p1"],
            policy_mapping_fn=lambda a: "p0" if a == "a0" else "p1",
        )
        .env_runners(num_env_runners=1, rollout_fragment_length=32)
        .debugging(seed=3)
    )
    algo = config.build()
    try:
        result = algo.train()
        assert result["num_env_steps_sampled"] == 64  # 32 steps x 2 agents
        assert any(k.startswith("p0/") for k in result)
        assert any(k.startswith("p1/") for k in result)
        path = algo.save(str(tmp_path / "ckpt"))
        before = {
            pid: [np.asarray(x) for x in __import__("jax").tree.leaves(l.get_params())]
            for pid, l in algo.learners.items()
        }
        algo.train()
        algo.restore(path)
        after = {
            pid: [np.asarray(x) for x in __import__("jax").tree.leaves(l.get_params())]
            for pid, l in algo.learners.items()
        }
        for pid in before:
            for a, b in zip(before[pid], after[pid]):
                np.testing.assert_array_equal(a, b)
    finally:
        algo.stop()


def test_connector_pipeline_composition():
    """ConnectorPipeline semantics: ordering, prepend/append/insert_after,
    and the built-in flatten."""
    import gymnasium as gym

    from ray_tpu.rllib import (
        ConnectorContext,
        ConnectorPipeline,
        FlattenObservations,
        Lambda,
    )

    ctx = ConnectorContext(gym.spaces.Discrete(4), gym.spaces.Discrete(2))
    pipeline = ConnectorPipeline([FlattenObservations()])
    out = pipeline(np.array([1, 3]), ctx)
    np.testing.assert_array_equal(
        out, [[0, 1, 0, 0], [0, 0, 0, 1]]
    )

    pipeline.append(Lambda(lambda d, c: d * 2.0, "double"))
    pipeline.prepend(Lambda(lambda d, c: d, "ident"))
    pipeline.insert_after(
        FlattenObservations, Lambda(lambda d, c: d + 1.0, "inc")
    )
    # order: ident -> flatten -> inc -> double
    out = pipeline(np.array([0]), ctx)
    np.testing.assert_array_equal(out, [[4.0, 2.0, 2.0, 2.0]])
    with pytest.raises(ValueError):
        pipeline.insert_after(type("Nope", (), {}), Lambda(lambda d, c: d))


def test_custom_connector_reaches_single_agent_runner(cluster):
    """A custom env-to-module connector configured through the builder is
    actually applied on the rollout path: scale CartPole obs by 0 and the
    policy sees constant inputs -> logp is identical across timesteps."""
    from ray_tpu.rllib import ConnectorPipeline, FlattenObservations, Lambda

    def zero_obs():
        return ConnectorPipeline(
            [FlattenObservations(), Lambda(lambda d, c: d * 0.0, "zero")]
        )

    config = (
        rllib.PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=1,
            num_envs_per_env_runner=2,
            rollout_fragment_length=16,
            env_to_module_connector=zero_obs,
        )
        .debugging(seed=1)
    )
    algo = config.build()
    try:
        params = algo.learner.get_params()
        ro = ray_tpu.get(algo.runners[0].sample.remote(params), timeout=120)
        assert np.all(ro["obs"] == 0.0), "custom connector not applied"
    finally:
        algo.stop()
