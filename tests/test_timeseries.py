"""Telemetry time-series plane: downsampling rings, the GCS-backed store
(retention, compaction, restart survival), the MAD straggler detector,
the alert engine lifecycle, and the dashboard/CLI read paths.

Unit tests exercise util/timeseries.py, util/alerts.py and
runtime/gcs/timeseries_store.py directly (the store only needs an object
with ``.storage`` and ``.append_synthetic_event``); one live cluster at
the end drives the full path — ts_push ingest, straggler verdict within
three steps, alert firing/resolution, /api/timeseries + /api/alerts +
/api/events filters, and ``ray_tpu top`` / ``ray_tpu alerts``.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.runtime.gcs.store import SqliteStoreClient
from ray_tpu.runtime.gcs.timeseries_store import (
    GcsTimeseriesStore,
    _compact_points,
)
from ray_tpu.util import timeseries
from ray_tpu.util.alerts import AlertEngine, AlertRule, StragglerDetector


# -- downsampling ring --------------------------------------------------------


def test_ring_invariants_preserved_under_downsampling():
    ring = timeseries.DownsamplingRing(capacity=16)
    n = 5000
    values = [float(i % 97) for i in range(n)]
    for i, v in enumerate(values):
        ring.append(float(i), v)
    assert len(ring) <= 16
    assert ring.total_count() == n
    pts = ring.points()
    # count and sum exact; min/max never tighten
    assert sum(p["count"] for p in pts) == n
    total = sum(p["value"] * p["count"] for p in pts)
    assert total == pytest.approx(sum(values))
    assert min(p["min"] for p in pts) == min(values)
    assert max(p["max"] for p in pts) == max(values)
    # stride doubled (power of two), timestamps stay ordered
    assert ring.stride > 1 and (ring.stride & (ring.stride - 1)) == 0
    assert [p["ts"] for p in pts] == sorted(p["ts"] for p in pts)


def test_ring_keeps_full_span_and_exemplars():
    ring = timeseries.DownsamplingRing(capacity=4)
    ring.append(0.0, 1.0, exemplar="trace-first")
    for i in range(1, 200):
        ring.append(float(i), 1.0)
    pts = ring.points()
    # oldest data degrades in resolution but is never forgotten
    assert pts[0]["ts_first"] == 0.0
    assert pts[-1]["ts"] == 199.0
    assert any(p["exemplar"] == "trace-first" for p in pts)
    assert ring.last()["ts"] == 199.0


def test_ring_rejects_tiny_capacity():
    with pytest.raises(ValueError):
        timeseries.DownsamplingRing(capacity=1)


# -- series + stream ----------------------------------------------------------


def test_series_name_registry_rejects_duplicates():
    assert "step_time_s" in timeseries.registered_series_names()
    with pytest.raises(ValueError):
        timeseries.SeriesName("step_time_s")


def test_series_record_respects_enable_switch():
    s = timeseries.Series(timeseries.STEP_TIME_S, {"run": "t"})
    prev = timeseries.set_enabled(False)
    try:
        s.record(1.0)
        assert s.ring.total_count() == 0 and s.drain() == []
        timeseries.set_enabled(True)
        s.record(2.0, exemplar="tr-1")
        assert s.ring.total_count() == 1
    finally:
        timeseries.set_enabled(prev)
    batch = s.drain()
    assert len(batch) == 1 and batch[0][1] == 2.0 and batch[0][2] == "tr-1"


def test_stream_register_idempotent_and_payload_roundtrip():
    stream = timeseries.TelemetryStream(push_period_s=3600.0)
    a = stream.register(
        timeseries.STEP_TIME_S, labels={"run": "r", "rank": "0"}
    )
    b = stream.register(
        timeseries.STEP_TIME_S, labels={"rank": "0", "run": "r"}
    )
    assert a is b  # label order does not fork the series
    prev = timeseries.set_enabled(True)
    try:
        a.record(0.5, ts=10.0)
    finally:
        timeseries.set_enabled(prev)
    payload = stream.build_payload()
    assert payload is not None
    row = next(r for r in payload["series"] if r["name"] == "step_time_s")
    assert row["labels"] == {"run": "r", "rank": "0"}
    assert row["points"] == [[10.0, 0.5, None]]
    assert stream.build_payload() is None  # drained
    stream.requeue_payload(payload)  # push failed: points survive
    assert stream.build_payload()["series"][0]["points"] == [[10.0, 0.5, None]]


def test_sampler_backed_series_polled_on_flush_cadence():
    stream = timeseries.TelemetryStream(push_period_s=3600.0)
    box = {"v": 1.5}
    stream.register(
        timeseries.SERVE_QUEUE_DEPTH,
        labels={"deployment": "d", "replica": "r0"},
        sampler=lambda: box["v"],
    )
    prev = timeseries.set_enabled(True)
    try:
        stream.sample_once(now=1.0)
        box["v"] = None  # idle: sampler returning None records nothing
        stream.sample_once(now=2.0)
    finally:
        timeseries.set_enabled(prev)
    s = stream.get(
        timeseries.SERVE_QUEUE_DEPTH,
        {"deployment": "d", "replica": "r0"},
    )
    assert s.ring.total_count() == 1 and s.ring.last()["value"] == 1.5


def test_series_id_stable_across_label_order():
    a = timeseries.series_id("step_time_s", {"a": 1, "b": 2}, "w1")
    b = timeseries.series_id("step_time_s", {"b": 2, "a": 1}, "w1")
    assert a == b and a.startswith("step_time_s:")
    assert a != timeseries.series_id("step_time_s", {"a": 1, "b": 2}, "w2")


# -- GCS store: retention, compaction, restart --------------------------------


class _StubGcs:
    """The two attributes GcsTimeseriesStore needs from GcsServer."""

    def __init__(self, storage):
        self.storage = storage
        self.events = []

    def append_synthetic_event(self, name, **fields):
        self.events.append({"name": name, **fields})


def _push(store, worker, points, name="step_time_s", labels=None, node="n0"):
    return store.push({
        "worker_id": worker, "node_id": node, "pid": 1, "ts": time.time(),
        "series": [{
            "name": name,
            "labels": labels or {"group": "g", "rank": worker[-1]},
            "points": points,
        }],
    })


def test_store_compaction_and_retention(tmp_path):
    gcs = _StubGcs(SqliteStoreClient(str(tmp_path / "gcs.db")))
    store = GcsTimeseriesStore(gcs)
    store.max_points = 8
    now = time.time()
    pts = [[now - 100 + i * 0.1, float(i), None] for i in range(100)]
    assert _push(store, "w0", pts) == 100
    (entry,) = store.query(name="step_time_s")
    assert len(entry["points"]) <= 8  # pair-merged under the cap
    # compaction degrades resolution, not span: the newest timestamp and
    # chronological order survive, merged values stay within data range
    assert entry["points"][-1][0] == pytest.approx(pts[-1][0])
    assert entry["points"][0][0] >= pts[0][0]
    ts_seq = [p[0] for p in entry["points"]]
    assert ts_seq == sorted(ts_seq)
    assert all(0.0 <= p[1] <= 99.0 for p in entry["points"])
    # points beyond retention are reaped...
    store.retention_s = 50.0
    old = [[now - 300, 9.0, None]]
    _push(store, "w1", old)
    fresh = store.query(worker_id="w1")
    assert fresh == [] or all(
        p[0] >= now - 51 for e in fresh for p in e["points"]
    )
    # ...and a series whose whole history aged out disappears entirely
    store.evaluate(now + 120, force=True)
    assert store.query(name="step_time_s") == []
    gcs.storage.close()


def test_store_survives_gcs_restart(tmp_path):
    path = str(tmp_path / "gcs.db")
    gcs = _StubGcs(SqliteStoreClient(path))
    store = GcsTimeseriesStore(gcs)
    now = time.time()
    _push(store, "w0", [[now, 1.0, "tr-9"]])
    store.set_rule({
        "name": "slow", "series": "step_time_s", "threshold": 2.0,
    })
    gcs.storage.close()  # "crash"

    gcs2 = _StubGcs(SqliteStoreClient(path))
    store2 = GcsTimeseriesStore(gcs2)
    store2.restore_from(gcs2.storage)
    (entry,) = store2.query(name="step_time_s")
    assert entry["worker_id"] == "w0"
    assert entry["points"] == [[pytest.approx(now), 1.0, "tr-9"]]
    assert [r["name"] for r in store2.alert_engine.rules()] == ["slow"]
    # deleting a rule deletes its persisted record too
    assert store2.delete_rule("slow") is True
    gcs2.storage.close()
    gcs3 = _StubGcs(SqliteStoreClient(path))
    store3 = GcsTimeseriesStore(gcs3)
    store3.restore_from(gcs3.storage)
    assert store3.alert_engine.rules() == []
    gcs3.storage.close()


def test_compact_points_unit():
    pts = [[float(i), float(i), None] for i in range(10)]
    out = _compact_points(list(pts), now=10.0, retention_s=100.0,
                          max_points=4)
    assert len(out) <= 4
    assert out[-1][0] == 9.0  # newest timestamp survives


# -- straggler detector -------------------------------------------------------


def _group_entries(now, slow_rank=3, slow=3.0, fast=1.0, steps=3):
    entries = []
    for rank in range(4):
        v = slow if rank == slow_rank else fast
        entries.append({
            "id": f"step_time_s:{rank:010d}",
            "name": "step_time_s",
            "labels": {"group": "g1", "rank": str(rank), "run": "r"},
            "worker_id": f"w{rank}",
            "node_id": f"n{rank}",
            "points": [[now - (steps - i) * v, v, f"tr-{rank}-{i}"]
                       for i in range(steps)],
        })
    return entries


def test_mad_straggler_detection_and_resolution():
    det = StragglerDetector()
    events = []
    now = time.time()
    # three steps from each of four workers; rank 3 runs 3x slow
    verdicts = det.evaluate(
        _group_entries(now), now,
        lambda name, **f: events.append({"name": name, **f}),
    )
    assert verdicts[0]["straggler"] is True  # sorted by deviation
    assert verdicts[0]["worker_id"] == "w3"
    assert verdicts[0]["rank"] == "3"
    assert verdicts[0]["node_id"] == "n3"
    assert sum(v["straggler"] for v in verdicts) == 1
    (fired,) = [e for e in events if e["name"] == "straggler_detected"]
    assert fired["worker_id"] == "w3" and fired["group"] == "g1"
    assert fired["exemplar"] == "tr-3-2"  # newest exemplar in window
    assert len(fired["series_tail"]) == 3  # the offending series attached
    # firing is edge-triggered: a second evaluation does not re-emit
    det.evaluate(_group_entries(now), now, lambda n, **f: events.append(f))
    assert len([e for e in events if e.get("name")]) == 1
    # worker recovers -> resolved event on the falling edge
    events.clear()
    det.evaluate(
        _group_entries(now, slow=1.0), now,
        lambda name, **f: events.append({"name": name, **f}),
    )
    assert [e["name"] for e in events] == ["straggler_resolved"]
    assert all(v["straggler"] is False for v in det.verdicts())


def test_straggler_needs_quorum_and_tolerates_uniform_jitter():
    det = StragglerDetector()
    now = time.time()
    # two workers: below min_workers, no verdicts at all
    assert det.evaluate(_group_entries(now)[:2], now) == []
    # uniform group with tiny jitter: rel_floor keeps MAD~0 from flagging
    entries = _group_entries(now, slow=1.02)
    assert all(not v["straggler"] for v in det.evaluate(entries, now))


# -- alert engine -------------------------------------------------------------


def _ttft_entry(now, values, exemplar="tr-slow"):
    return {
        "id": "serve_ttft_s:abc", "name": "serve_ttft_s",
        "labels": {"deployment": "d", "replica": "r0"},
        "worker_id": "w0", "node_id": "n0",
        "points": [
            [now - (len(values) - 1 - i), v,
             exemplar if i == len(values) - 1 else None]
            for i, v in enumerate(values)
        ],
    }


def test_alert_threshold_lifecycle_with_for_s_and_exemplar():
    eng = AlertEngine()
    eng.set_rule(AlertRule(
        "slow_ttft", "serve_ttft_s", threshold=0.5, for_s=5.0,
        labels={"deployment": "d"},
    ))
    events = []
    emit = lambda name, **f: events.append({"name": name, **f})  # noqa: E731
    now = time.time()
    # breach starts the pending clock but does not fire before for_s
    eng.evaluate([_ttft_entry(now, [0.1, 0.9])], now, emit)
    assert eng.active() == [] and events == []
    # still breached after for_s -> firing, with the window's exemplar
    eng.evaluate([_ttft_entry(now + 6, [0.9, 0.8])], now + 6, emit)
    (active,) = eng.active()
    assert active["rule"] == "slow_ttft" and active["value"] == 0.8
    assert active["exemplar"] == "tr-slow"
    assert [e["name"] for e in events] == ["alert_firing"]
    # recovery resolves and logs the transition
    eng.evaluate([_ttft_entry(now + 8, [0.2])], now + 8, emit)
    assert eng.active() == []
    assert [e["name"] for e in events] == ["alert_firing", "alert_resolved"]
    assert [r["transition"] for r in eng.log] == ["firing", "resolved"]


def test_alert_label_filter_scopes_rule():
    eng = AlertEngine()
    eng.set_rule(AlertRule(
        "slow_ttft", "serve_ttft_s", threshold=0.5,
        labels={"deployment": "other"},
    ))
    now = time.time()
    eng.evaluate([_ttft_entry(now, [0.9])], now)
    assert eng.active() == []  # labels don't match -> never considered


def test_alert_vanished_series_resolves():
    eng = AlertEngine()
    eng.set_rule(AlertRule("slow_ttft", "serve_ttft_s", threshold=0.5))
    events = []
    emit = lambda name, **f: events.append({"name": name, **f})  # noqa: E731
    now = time.time()
    eng.evaluate([_ttft_entry(now, [0.9])], now, emit)
    assert len(eng.active()) == 1
    eng.evaluate([], now + 1, emit)  # retention reaped the series
    assert eng.active() == []
    assert events[-1]["name"] == "alert_resolved"
    assert events[-1]["reason"] == "series_gone"


def test_alert_rate_of_change_and_burn_rate_kinds():
    now = time.time()
    roc = AlertRule("leak", "kv_pool_occupancy", kind="rate_of_change",
                    threshold=0.05)
    # 0.2 -> 0.8 over 4s = 0.15/s, over the 0.05/s budget
    window = [[now - 4, 0.2, None], [now, 0.8, None]]
    assert roc.breached(roc.signal(window))
    assert not roc.breached(roc.signal([[now - 4, 0.2, None],
                                        [now, 0.21, None]]))
    assert roc.signal([[now, 0.2, None]]) is None  # needs a span
    burn = AlertRule("burn", "serve_ttft_s", kind="burn_rate",
                     threshold=0.5, burn_fraction=0.5)
    bad = [[now - i, 0.9, None] for i in range(3)]
    good = [[now - i, 0.1, None] for i in range(3)]
    assert burn.breached(burn.signal(bad))
    assert not burn.breached(burn.signal(good + bad[:1]))  # 1/4 < 50%
    with pytest.raises(ValueError):
        AlertRule("x", "s", kind="nonsense")
    with pytest.raises(ValueError):
        AlertRule("x", "s", cmp="ge")


def test_alert_rule_json_roundtrip():
    rule = AlertRule("r", "step_time_s", kind="burn_rate", threshold=2.0,
                     cmp="lt", window_s=30, for_s=5, burn_fraction=0.8,
                     labels={"group": "g"})
    assert AlertRule.from_dict(rule.to_dict()).to_dict() == rule.to_dict()


# -- events_dropped accounting ------------------------------------------------


def test_events_dropped_counter_and_rollup():
    from ray_tpu.util import metrics

    before = metrics.events_dropped_total()
    metrics.record_events_dropped(7)
    assert metrics.events_dropped_total() == before + 7
    # same {"values": {json-labels: value}} shape metrics._snapshot emits
    payloads = [
        {"metrics": [{"name": "events_dropped_total", "type": "counter",
                      "values": {"[]": 3.0}}]},
        {"metrics": [{"name": "events_dropped_total", "type": "counter",
                      "values": {"[]": 2.0}},
                     {"name": "other", "type": "counter",
                      "values": {"[]": 9.0}}]},
    ]
    assert metrics.events_dropped_from_payloads(payloads) == 5.0


# -- perf: telemetry overhead budget ------------------------------------------


def test_telemetry_overhead_under_one_percent():
    from ray_tpu._internal.perf import _telemetry_overhead_bench

    out = _telemetry_overhead_bench(0.1)
    assert out["telemetry_overhead_pct"] < 1.0
    assert 0 < out["telemetry_record_ns"] < 50_000


# -- live cluster: ingest, straggler verdict, alerts, HTTP + CLI --------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def test_cluster_straggler_alerts_and_read_paths(shutdown_only, capsys):
    node = ray_tpu.init(
        num_cpus=4, resources={"TPU": 4}, include_dashboard=True
    )
    from ray_tpu.scripts import cli
    from ray_tpu.util import state

    # four synthetic workers report three steps each; rank 3 runs 3x slow
    now = time.time()
    for rank in range(4):
        v = 3.0 if rank == 3 else 1.0
        assert state._gcs_call("ts_push", {
            "worker_id": f"w{rank}", "node_id": f"n{rank}", "pid": 100 + rank,
            "ts": now,
            "series": [{
                "name": "step_time_s",
                "labels": {"group": "g1", "rank": str(rank), "run": "demo"},
                "points": [[now - (3 - i) * v, v, f"tr-{rank}-{i}"]
                           for i in range(3)],
            }],
        }) == 3
    time.sleep(0.6)  # let the store's evaluation rate limiter expire

    # straggler named within the three pushed steps, top-ranked by deviation
    verdicts = state.straggler_verdicts()
    assert verdicts and verdicts[0]["worker_id"] == "w3"
    assert verdicts[0]["straggler"] is True
    fired = state.list_events(name="straggler_detected")
    assert fired and fired[-1]["worker_id"] == "w3"
    assert fired[-1]["synthetic"] is True

    # alert rule fires on the slow series, then resolves on recovery
    state.set_alert_rule({
        "name": "slow_step", "series": "step_time_s", "threshold": 2.0,
        "labels": {"group": "g1"},
    })
    time.sleep(0.6)
    snap = state.alerts_snapshot()
    assert [r["name"] for r in snap["rules"]] == ["slow_step"]
    assert any(a["worker_id"] == "w3" for a in snap["active"])
    state._gcs_call("ts_push", {
        "worker_id": "w3", "node_id": "n3", "pid": 103, "ts": time.time(),
        "series": [{
            "name": "step_time_s",
            "labels": {"group": "g1", "rank": "3", "run": "demo"},
            "points": [[time.time(), 1.0, None]],
        }],
    })
    time.sleep(0.6)
    snap = state.alerts_snapshot()
    assert snap["active"] == []
    assert any(r["transition"] == "resolved" for r in snap["log"])
    assert state.list_events(name="alert_firing")
    assert state.list_events(name="alert_resolved")

    # driver-side stream: register + record + flush lands in the store
    s = timeseries.register_series(
        timeseries.SERVE_TTFT_S,
        labels={"deployment": "d", "replica": "r0"},
    )
    prev = timeseries.set_enabled(True)
    try:
        s.record(0.123, exemplar="tr-live")
        assert timeseries.flush_stream() is True
    finally:
        timeseries.set_enabled(prev)
    (ttft,) = state.query_timeseries(name="serve_ttft_s")
    assert ttft["points"][-1][1] == 0.123
    assert ttft["points"][-1][2] == "tr-live"
    assert any(
        r["name"] == "serve_ttft_s" for r in state.list_timeseries()
    )

    # dashboard read paths
    dash = node.dashboard
    ts = _get_json(dash.url + "/api/timeseries?name=step_time_s")
    assert len(ts["series"]) == 4
    assert all(e["points"] for e in ts["series"])
    al = _get_json(dash.url + "/api/alerts")
    assert set(al) >= {"active", "rules", "log", "stragglers"}
    assert al["stragglers"][0]["worker_id"] == "w3"
    ev = _get_json(
        dash.url + "/api/events?name=straggler_detected&since=0"
    )
    assert ev["events"] and all(
        e["name"] == "straggler_detected" for e in ev["events"]
    )
    assert set(ev["dropped"]) == {"rings", "store"}
    far_future = now + 10**6
    assert _get_json(
        dash.url + f"/api/events?since={far_future}"
    )["events"] == []

    # CLI: `ray_tpu top` ranks the straggler first; `ray_tpu alerts` dumps
    # the snapshot; `--events` tails the alert stream
    assert cli.main(["top", "--address", "local", "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows[0]["worker_id"] == "w3"
    assert cli.main(["top", "--address", "local"]) == 0
    text = capsys.readouterr().out
    assert "STRAGGLER" in text and "GROUP" in text
    assert cli.main(["alerts", "--address", "local"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert set(snap) >= {"active", "rules", "log", "stragglers"}
    assert cli.main(["alerts", "--address", "local", "--events"]) == 0
    tail = json.loads(capsys.readouterr().out)
    assert {"straggler_detected", "alert_firing", "alert_resolved"} <= {
        e["name"] for e in tail
    }
    assert cli.main([
        "alerts", "--address", "local", "--delete-rule", "slow_step",
    ]) == 0
    assert capsys.readouterr().out.strip() == '{"deleted": true}'
