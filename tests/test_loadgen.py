"""Loadgen + autoscale-policy unit tests: no cluster required.

Covers the open-loop generator's building blocks (arrival processes,
Zipf workload synthesis, trace round-trips, dispatch/outcome recording),
the bucket-quantile estimator the push plane's rollups use, and the
``evaluate()`` policy state machine (hysteresis, cooldowns, step/bound
clamps, the starting-replica guard).
"""

import json
import threading
import time

import pytest

from ray_tpu.exceptions import BackPressureError, DeadlineExceededError
from ray_tpu.loadgen import (
    BurstyRampArrivals,
    CallableTarget,
    LoadGenerator,
    PoissonArrivals,
    RequestClass,
    Trace,
    TraceRecord,
    ZipfPrefixes,
    bundled_trace,
    synthesize,
)
from ray_tpu.serve.autoscale import (
    AutoscalePolicy,
    AutoscaleSignals,
    AutoscaleState,
    evaluate,
    shed_total,
    ttft_p99_ms,
)
from ray_tpu.util.metrics import (
    autoscale_summary,
    kvcache_summary,
    merged_histogram,
    quantile_from_buckets,
    serve_latency_summary,
)


# ---------------------------------------------------------------------------
# arrival processes
# ---------------------------------------------------------------------------


def test_poisson_arrivals_deterministic_and_rate():
    a = PoissonArrivals(rate_hz=50.0, duration_s=10.0, seed=3)
    times = a.times()
    assert times == PoissonArrivals(50.0, 10.0, seed=3).times()
    assert times != PoissonArrivals(50.0, 10.0, seed=4).times()
    assert all(0 < t < 10.0 for t in times)
    assert times == sorted(times)
    # mean count 500; 5 sigma ~ 112
    assert 350 < len(times) < 650


def test_bursty_ramp_rate_profile_and_phases():
    b = BurstyRampArrivals([(2.0, 0.0, 10.0), (2.0, 4.0), (1.0, 6.0, 0.0)])
    assert b.duration_s == 5.0
    assert b.rate_at(0.0) == 0.0
    assert b.rate_at(1.0) == pytest.approx(5.0)
    assert b.rate_at(2.5) == pytest.approx(4.0)  # flat 2-tuple phase
    assert b.rate_at(4.5) == pytest.approx(3.0)
    assert b.rate_at(99.0) == 0.0
    times = b.times()
    assert times == BurstyRampArrivals(
        [(2.0, 0.0, 10.0), (2.0, 4.0), (1.0, 6.0, 0.0)]
    ).times()
    assert all(0 < t < 5.0 for t in times)
    # thinning concentrates arrivals where the rate is high: the ramp's
    # second half should out-arrive its first half
    first = sum(1 for t in times if t < 1.0)
    second = sum(1 for t in times if 1.0 <= t < 2.0)
    assert second > first


def test_bursty_ramp_validation():
    with pytest.raises(ValueError):
        BurstyRampArrivals([])
    with pytest.raises(ValueError):
        BurstyRampArrivals([(0.0, 1.0)])
    with pytest.raises(ValueError):
        BurstyRampArrivals([(1.0, -1.0, 2.0)])
    with pytest.raises(ValueError):
        BurstyRampArrivals([(1.0, 2.0, 3.0, 4.0)])
    with pytest.raises(ValueError):
        PoissonArrivals(0.0, 1.0)


# ---------------------------------------------------------------------------
# workload synthesis
# ---------------------------------------------------------------------------


def test_zipf_prefixes_skew_and_determinism():
    import random

    z = ZipfPrefixes(num_prefixes=16, alpha=1.3, prefix_tokens=8, seed=11)
    rng = random.Random(0)
    draws = [z.sample(rng) for _ in range(4000)]
    assert all(0 <= d < 16 for d in draws)
    counts = [draws.count(k) for k in range(16)]
    assert counts[0] == max(counts)  # rank 0 dominates
    assert counts[0] > 3 * counts[8]
    # prefix token ids are a pure function of (seed, prefix_id)
    assert z.tokens(3) == ZipfPrefixes(16, 1.3, 8, seed=11).tokens(3)
    assert z.tokens(3) != z.tokens(4)
    assert len(z.tokens(3)) == 8


def test_synthesize_classes_and_prefixes():
    classes = [
        RequestClass("short", weight=0.9, prompt_tokens=12,
                     max_new_tokens=4, deadline_s=5.0),
        RequestClass("long", weight=0.1, prompt_tokens=48,
                     max_new_tokens=32, deadline_s=None),
    ]
    z = ZipfPrefixes(num_prefixes=8, alpha=1.2, prefix_tokens=8, seed=2)
    trace = synthesize([0.5, 0.1, 0.3] + [i * 0.01 for i in range(400)],
                       classes, z, seed=5)
    assert [r.t for r in trace.requests] == sorted(
        r.t for r in trace.requests
    )
    by_cls = {c.name: [r for r in trace.requests if r.cls == c.name]
              for c in classes}
    assert len(by_cls["short"]) > 5 * len(by_cls["long"])
    for r in trace.requests:
        expect = 12 if r.cls == "short" else 48
        assert len(r.token_ids) == expect
        assert r.token_ids[:8] == z.tokens(r.prefix_id)  # shared prefix
        assert r.deadline_s == (5.0 if r.cls == "short" else None)
    # same inputs, same trace
    again = synthesize([0.5, 0.1, 0.3] + [i * 0.01 for i in range(400)],
                       classes, z, seed=5)
    assert [r.as_dict() for r in again.requests] == [
        r.as_dict() for r in trace.requests
    ]


def test_synthesize_validation():
    z = ZipfPrefixes(num_prefixes=2)
    with pytest.raises(ValueError):
        synthesize([0.1], [], z)
    with pytest.raises(ValueError):
        synthesize([0.1], [RequestClass("x", weight=0.0)], z)


# ---------------------------------------------------------------------------
# trace round-trips
# ---------------------------------------------------------------------------


def test_trace_save_load_roundtrip(tmp_path):
    trace = Trace(
        meta={"name": "t"},
        requests=[
            TraceRecord(t=0.1, cls="a", prefix_id=2, token_ids=[1, 2, 3],
                        max_new_tokens=7, deadline_s=1.5),
            TraceRecord(t=0.4),
        ],
    )
    path = str(tmp_path / "trace.json")
    trace.save(path)
    loaded = Trace.load(path)
    assert loaded.meta == {"name": "t"}
    assert [r.as_dict() for r in loaded.requests] == [
        r.as_dict() for r in trace.requests
    ]
    assert loaded.duration_s == pytest.approx(0.4)


def test_trace_scaled_and_limit():
    trace = Trace(requests=[TraceRecord(t=float(i)) for i in range(10)])
    fast = trace.scaled(0.5, limit=4)
    assert [r.t for r in fast.requests] == [0.0, 0.5, 1.0, 1.5]
    assert fast.meta["time_scale"] == 0.5
    assert len(trace.requests) == 10  # original untouched


def test_bundled_trace_shape():
    trace = bundled_trace("ramp_burst_decay")
    assert trace.meta["name"] == "ramp_burst_decay"
    assert len(trace.requests) > 50
    assert trace.duration_s < 13.0
    # Zipf head: the hottest prefix appears far more often than the median
    from collections import Counter

    counts = Counter(r.prefix_id for r in trace.requests)
    assert counts.most_common(1)[0][1] >= 10
    assert {r.cls for r in trace.requests} == {"short", "long"}
    with pytest.raises(FileNotFoundError):
        bundled_trace("nope")


# ---------------------------------------------------------------------------
# open-loop generator
# ---------------------------------------------------------------------------


def _quick_trace(n, spacing, **kw):
    return Trace(requests=[
        TraceRecord(t=i * spacing, **kw) for i in range(n)
    ])


def test_loadgen_open_loop_does_not_wait_for_slow_target():
    """The defining open-loop property: a target that takes 0.5s cannot
    slow a 20ms-spaced schedule — dispatch lag stays near zero while all
    requests overlap in flight."""
    inflight = []
    peak = []
    lock = threading.Lock()

    def slow(payload):
        with lock:
            inflight.append(1)
            peak.append(len(inflight))
        time.sleep(0.5)
        with lock:
            inflight.pop()

    trace = _quick_trace(10, 0.02)
    res = LoadGenerator(CallableTarget(slow), max_inflight=32).run(trace)
    assert len(res.records) == 10
    assert all(r.outcome == "ok" for r in res.records)
    assert max(peak) >= 5  # closed-loop would never overlap
    assert res.summary()["max_lag_s"] < 0.3


def test_loadgen_outcome_classification():
    def fail(payload):
        n = payload["max_new_tokens"]
        if n == 1:
            raise DeadlineExceededError("too slow")
        if n == 2:
            raise BackPressureError("queue full")
        if n == 3:
            raise RuntimeError("boom")
        return n

    trace = Trace(requests=[
        TraceRecord(t=0.0, max_new_tokens=n) for n in (1, 2, 3, 4)
    ])
    res = LoadGenerator(CallableTarget(fail), max_inflight=4).run(trace)
    outcomes = {r.index: r.outcome for r in res.records}
    assert outcomes == {
        0: "deadline", 1: "shed", 2: "error:RuntimeError", 3: "ok"
    }
    s = res.summary()
    assert s["outcomes"] == {
        "deadline": 1, "shed": 1, "error:RuntimeError": 1, "ok": 1
    }
    assert len(res.failures) == 3 and len(res.ok) == 1


def test_loadgen_result_save_and_to_trace(tmp_path):
    trace = _quick_trace(5, 0.01, token_ids=[1, 2], cls="short")
    res = LoadGenerator(
        CallableTarget(lambda p: None), max_inflight=4
    ).run(trace)
    rec = res.to_trace()
    assert len(rec.requests) == 5
    assert rec.meta.get("recorded") is True
    # recorded trace keeps payloads; schedule becomes actual dispatch times
    assert all(r.token_ids == [1, 2] for r in rec.requests)
    path = str(tmp_path / "run.json")
    res.save(path)
    with open(path) as f:
        doc = json.load(f)
    assert doc["summary"]["requests"] == 5
    assert len(doc["records"]) == 5
    assert len(doc["trace"]["requests"]) == 5


def test_loadgen_time_scale_compresses_schedule():
    trace = _quick_trace(5, 0.2)
    t0 = time.perf_counter()
    res = LoadGenerator(
        CallableTarget(lambda p: None), max_inflight=4
    ).run(trace, time_scale=0.1)
    assert time.perf_counter() - t0 < 0.5  # 0.8s schedule compressed to 0.08
    assert [r.sched_t for r in res.records] == pytest.approx(
        [0.0, 0.02, 0.04, 0.06, 0.08]
    )


# ---------------------------------------------------------------------------
# bucket quantiles + rollups
# ---------------------------------------------------------------------------


def test_quantile_from_buckets_interpolation():
    bounds = [1.0, 2.0, 4.0]
    assert quantile_from_buckets(bounds, [0, 0, 0, 0], 0.5) is None
    # 10 samples all in (1, 2]: p50 interpolates to the bucket midpoint
    assert quantile_from_buckets(bounds, [0, 10, 0, 0], 0.5) == pytest.approx(
        1.5
    )
    # uniform mass across [0,1],(1,2],(2,4]: rank 3 of 12 is 3/4 into the
    # first bucket; rank 9 is 1/4 into the third
    counts = [4, 4, 4, 0]
    assert quantile_from_buckets(bounds, counts, 0.25) == pytest.approx(0.75)
    assert quantile_from_buckets(bounds, counts, 0.75) == pytest.approx(2.5)
    # overflow bucket clamps to the last boundary
    assert quantile_from_buckets(bounds, [0, 0, 0, 5], 0.99) == 4.0
    # q clamped into [0, 1]
    assert quantile_from_buckets(bounds, [5, 0, 0, 0], 2.0) == pytest.approx(
        1.0
    )


def _payload(name, tag_keys, series, boundaries=None):
    snap = {"name": name, "tag_keys": list(tag_keys), "values": {},
            "counts": {}}
    if boundaries is not None:
        snap["boundaries"] = list(boundaries)
    for tags, value, counts in series:
        key = json.dumps(list(tags))
        snap["values"][key] = value
        if counts is not None:
            snap["counts"][key] = list(counts)
    return {"metrics": [snap]}


def test_merged_histogram_across_payloads_with_tag_filter():
    bounds = [0.1, 1.0]
    p1 = _payload("h", ("deployment",),
                  [(("a",), 5.0, [2, 1, 0]), (("b",), 9.0, [0, 0, 3])],
                  boundaries=bounds)
    p2 = _payload("h", ("deployment",), [(("a",), 1.0, [1, 0, 0])],
                  boundaries=bounds)
    m = merged_histogram([p1, p2], "h", {"deployment": "a"})
    assert m["counts"] == [3, 1, 0]
    assert m["sum"] == 6.0 and m["count"] == 4.0
    assert merged_histogram([p1], "h", {"deployment": "zzz"}) is None
    assert merged_histogram([p1], "other") is None
    unfiltered = merged_histogram([p1, p2], "h")
    assert unfiltered["count"] == 7.0


def test_serve_latency_summary_from_buckets():
    bounds = [0.1, 1.0, 10.0]
    payloads = [
        _payload("serve_ttft_seconds", ("deployment",),
                 [(("dep",), 4.0, [0, 8, 0, 0])], boundaries=bounds),
        _payload("serve_replica_warmup_seconds", ("deployment",),
                 [(("dep",), 2.0, [0, 0, 2, 0])], boundaries=bounds),
    ]
    s = serve_latency_summary(payloads)
    row = s["ttft_ms"]["dep"]
    assert row["count"] == 8.0
    assert row["mean"] == pytest.approx(500.0)  # 4s / 8 -> ms
    assert row["p50"] == pytest.approx(550.0)  # mid (0.1, 1.0] in ms
    assert 100.0 < row["p99"] <= 1000.0
    warm = s["warmup_s"]["dep"]
    assert warm["count"] == 2.0
    assert 1.0 < warm["p50"] <= 10.0


def test_kvcache_summary_bucket_quantiles():
    bounds = [1.0, 10.0, 100.0]
    payloads = [_payload(
        "kvcache_ttft_ms", ("cache",),
        [(("hit",), 40.0, [0, 10, 0, 0])], boundaries=bounds,
    )]
    row = kvcache_summary(payloads)["ttft_ms"]["hit"]
    assert row["mean_ms"] == pytest.approx(4.0)
    assert row["p50_ms"] == pytest.approx(5.5)  # mid (1, 10]
    assert row["p99_ms"] <= 10.0


def test_autoscale_summary_rollup():
    bounds = [0.5, 2.0]
    payloads = [
        _payload("autoscale_scale_up_total", ("deployment",),
                 [(("d1",), 3.0, None), (("d2",), 1.0, None)]),
        _payload("autoscale_scale_down_total", ("deployment",),
                 [(("d1",), 2.0, None)]),
        _payload("autoscale_decision_seconds", ("deployment", "direction"),
                 [(("d1", "up"), 2.0, [4, 0, 0])], boundaries=bounds),
    ]
    s = autoscale_summary(payloads)
    assert s["scale_ups"] == 4.0 and s["scale_downs"] == 2.0
    assert s["by_deployment"]["d1"] == {"scale_ups": 3.0, "scale_downs": 2.0}
    assert s["by_deployment"]["d2"]["scale_ups"] == 1.0
    assert 0.0 < s["decision_p50_s"] <= 0.5
    assert s["decision_p99_s"] <= 0.5


# ---------------------------------------------------------------------------
# policy state machine
# ---------------------------------------------------------------------------


def _sig(**kw):
    defaults = dict(queue_depth=0.0, queue_per_replica=0.0, shed_delta=0.0,
                    ttft_p99_ms=None, running=1, starting=0, target=1)
    defaults.update(kw)
    return AutoscaleSignals(**defaults)


def test_policy_validation():
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscalePolicy(interval_s=0)
    p = AutoscalePolicy.from_dict({"min_replicas": 2, "max_replicas": 5})
    assert p.as_dict()["max_replicas"] == 5


def test_evaluate_scale_up_on_queue_pressure_with_hysteresis():
    policy = AutoscalePolicy(max_replicas=4, target_queue_per_replica=2.0,
                             up_hysteresis=2, cooldown_up_s=0.0)
    st = AutoscaleState()
    sig = _sig(queue_per_replica=5.0, target=1)
    assert evaluate(policy, st, sig, now=10.0) is None  # 1st breach: wait
    d = evaluate(policy, st, sig, now=11.0)
    assert d is not None and d.direction == "up"
    assert (d.from_replicas, d.to_replicas) == (1, 2)
    assert "queue/replica" in d.reason
    assert d.breach_age_s == pytest.approx(1.0)  # onset at 10.0


def test_evaluate_starting_guard_blocks_runaway_up():
    policy = AutoscalePolicy(max_replicas=4, target_queue_per_replica=1.0,
                             up_hysteresis=1, cooldown_up_s=0.0)
    st = AutoscaleState()
    sig = _sig(queue_per_replica=9.0, target=2, starting=1)
    assert evaluate(policy, st, sig, now=1.0) is None
    sig.starting = 0
    assert evaluate(policy, st, sig, now=2.0).direction == "up"


def test_evaluate_up_cooldown_and_step_clamp():
    policy = AutoscalePolicy(max_replicas=4, target_queue_per_replica=1.0,
                             up_hysteresis=1, cooldown_up_s=5.0,
                             scale_up_step=10)
    st = AutoscaleState()
    d = evaluate(policy, st, _sig(queue_per_replica=9.0, target=1), now=10.0)
    assert (d.from_replicas, d.to_replicas) == (1, 4)  # clamped to max
    # still pressured immediately after: cooldown blocks
    assert evaluate(
        policy, st, _sig(queue_per_replica=9.0, target=4), now=11.0
    ) is None
    # at max anyway: nothing to do even after cooldown
    assert evaluate(
        policy, st, _sig(queue_per_replica=9.0, target=4), now=99.0
    ) is None


def test_evaluate_scale_down_requires_idle_streak_and_cooldown():
    policy = AutoscalePolicy(min_replicas=1, max_replicas=4,
                             target_queue_per_replica=2.0,
                             idle_queue_per_replica=0.5, down_hysteresis=3,
                             cooldown_down_s=0.0, scale_down_step=2)
    st = AutoscaleState()
    idle = _sig(queue_per_replica=0.0, target=4)
    assert evaluate(policy, st, idle, now=1.0) is None
    assert evaluate(policy, st, idle, now=2.0) is None
    d = evaluate(policy, st, idle, now=3.0)
    assert d.direction == "down"
    assert (d.from_replicas, d.to_replicas) == (4, 2)
    assert d.breach_age_s == pytest.approx(2.0)  # idle since 1.0
    # a busy-but-not-pressured eval resets the idle streak
    st2 = AutoscaleState()
    mid = _sig(queue_per_replica=1.0, target=4)  # between idle and pressure
    evaluate(policy, st2, idle, now=1.0)
    evaluate(policy, st2, idle, now=2.0)
    assert evaluate(policy, st2, mid, now=3.0) is None
    assert st2.idle_evals == 0


def test_evaluate_down_cooldown_counts_from_last_up():
    policy = AutoscalePolicy(min_replicas=1, max_replicas=4,
                             target_queue_per_replica=2.0,
                             up_hysteresis=1, down_hysteresis=1,
                             cooldown_up_s=0.0, cooldown_down_s=10.0)
    st = AutoscaleState()
    d = evaluate(policy, st, _sig(queue_per_replica=9.0, target=1), now=5.0)
    assert d.direction == "up"
    # idle right after the scale-up: down cooldown measured from last_up_ts
    idle = _sig(queue_per_replica=0.0, target=2)
    assert evaluate(policy, st, idle, now=6.0) is None
    assert evaluate(policy, st, idle, now=16.0).direction == "down"
    # never below min_replicas
    assert evaluate(
        policy, st, _sig(queue_per_replica=0.0, target=1), now=99.0
    ) is None


def test_evaluate_shed_and_ttft_pressure():
    policy = AutoscalePolicy(max_replicas=4, target_queue_per_replica=0.0,
                             max_shed_per_interval=0.0,
                             target_ttft_p99_ms=100.0, up_hysteresis=1,
                             cooldown_up_s=0.0)
    st = AutoscaleState()
    d = evaluate(policy, st, _sig(shed_delta=3.0, target=1), now=1.0)
    assert d is not None and "sheds" in d.reason
    st = AutoscaleState()
    d = evaluate(policy, st, _sig(ttft_p99_ms=250.0, target=1), now=1.0)
    assert d is not None and "ttft_p99" in d.reason
    # ttft under target (or unknown): no pressure
    st = AutoscaleState()
    assert evaluate(policy, st, _sig(ttft_p99_ms=50.0, target=1), 1.0) is None
    assert st.pressured_evals == 0


def test_shed_total_and_ttft_signal_deltas():
    mk = lambda shed, counts: [
        _payload("serve_shed_total", ("deployment",),
                 [(("dep",), shed, None)]),
        _payload("serve_ttft_seconds", ("deployment",),
                 [(("dep",), 1.0, counts)], boundaries=[0.1, 1.0]),
    ]
    assert shed_total(mk(5.0, [1, 0, 0]), "dep") == 5.0
    assert shed_total(mk(5.0, [1, 0, 0]), "other") == 0.0

    st = AutoscaleState()
    # first window: all mass in (0.1, 1.0] -> p99 in (100, 1000] ms
    p99 = ttft_p99_ms(mk(0.0, [0, 10, 0]), "dep", st)
    assert 100.0 < p99 <= 1000.0
    # no new samples since baseline -> None (window delta is empty)
    assert ttft_p99_ms(mk(0.0, [0, 10, 0]), "dep", st) is None
    # new fast samples dominate the window even though cumulative
    # counts still hold the old slow mass
    p99 = ttft_p99_ms(mk(0.0, [40, 10, 0]), "dep", st)
    assert p99 <= 100.0
    # deployment with no serve histogram falls back to kvcache buckets
    st2 = AutoscaleState()
    kv = [_payload("kvcache_ttft_ms", ("cache",),
                   [(("miss",), 500.0, [0, 0, 4])],
                   boundaries=[1.0, 10.0])]
    est = ttft_p99_ms(kv, "dep", st2)
    assert est == pytest.approx(10.0)  # overflow clamps to last bound (ms)
    assert st2.last_ttft_source == "kvcache"
    # and nothing at all -> None
    st3 = AutoscaleState()
    assert ttft_p99_ms([], "dep", st3) is None
