"""Tests for ray_tpu.serve (reference model: python/ray/serve/tests/)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=6, resources={"TPU": 4})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_apps():
    yield
    # delete apps between tests but keep controller/proxy warm
    try:
        for app in list(serve.status().keys()):
            serve.delete(app)
    except Exception:
        pass


def test_basic_deployment_and_handle(cluster):
    @serve.deployment
    class Greeter:
        def __call__(self, name):
            return f"hello {name}"

    handle = serve.run(Greeter.bind(), name="greet", _proxy=False)
    assert handle.remote("tpu").result(timeout_s=30) == "hello tpu"

    st = serve.status()["greet"]
    assert st.status == "RUNNING"
    assert st.deployments["Greeter"].status == "HEALTHY"


def test_function_deployment(cluster):
    @serve.deployment
    def square(x):
        return x * x

    handle = serve.run(square.bind(), name="sq", _proxy=False)
    assert handle.remote(7).result(timeout_s=30) == 49


def test_multi_replica_load_balancing(cluster):
    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _):
            return self.pid

    handle = serve.run(WhoAmI.bind(), name="who", _proxy=False)
    pids = {handle.remote(None).result(timeout_s=30) for _ in range(20)}
    assert len(pids) == 2  # both replicas served traffic


def test_composition_nested_handles(cluster):
    @serve.deployment
    class Adder:
        def __init__(self, increment):
            self.increment = increment

        def __call__(self, x):
            return x + self.increment

    @serve.deployment
    class Chain:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            partial = self.adder.remote(x).result(timeout_s=30)
            return partial * 10

    app = Chain.bind(Adder.bind(3))
    handle = serve.run(app, name="chain", _proxy=False)
    assert handle.remote(4).result(timeout_s=30) == 70


def test_method_routing(cluster):
    @serve.deployment
    class Multi:
        def __call__(self, x):
            return ("call", x)

        def other(self, x):
            return ("other", x)

    handle = serve.run(Multi.bind(), name="multi", _proxy=False)
    assert handle.remote(1).result(timeout_s=30) == ("call", 1)
    assert handle.other.remote(2).result(timeout_s=30) == ("other", 2)


def test_user_config_reconfigure(cluster):
    @serve.deployment(user_config={"threshold": 1})
    class Configurable:
        def __init__(self):
            self.threshold = None

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self, _):
            return self.threshold

    handle = serve.run(Configurable.bind(), name="cfg", _proxy=False)
    assert handle.remote(None).result(timeout_s=30) == 1

    @serve.deployment(user_config={"threshold": 5})
    class Configurable2:
        def __init__(self):
            self.threshold = None

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self, _):
            return self.threshold

    Configurable2._config.name = "Configurable"
    serve.run(Configurable2.bind(), name="cfg", _proxy=False)
    deadline = time.time() + 20
    while time.time() < deadline:
        if handle.remote(None).result(timeout_s=30) == 5:
            break
        time.sleep(0.3)
    assert handle.remote(None).result(timeout_s=30) == 5


def test_replica_failure_recovery(cluster):
    @serve.deployment
    class Fragile:
        def __call__(self, x):
            if x == "die":
                import os

                os._exit(1)
            return "alive"

    handle = serve.run(Fragile.bind(), name="fragile", _proxy=False)
    assert handle.remote("ok").result(timeout_s=30) == "alive"
    try:
        handle.remote("die").result(timeout_s=10)
    except Exception:
        pass
    # controller should replace the dead replica
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        try:
            if handle.remote("ok").result(timeout_s=10) == "alive":
                ok = True
                break
        except Exception:
            time.sleep(0.5)
    assert ok, "replica was not replaced after crash"


def test_http_proxy_end_to_end(cluster):
    @serve.deployment
    class Echo:
        def __call__(self, body):
            return {"echo": body}

    serve.run(Echo.bind(), name="echo_app", route_prefix="/echo")
    deadline = time.time() + 30
    result = None
    while time.time() < deadline:
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:8000/echo",
                data=json.dumps({"msg": "hi"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                result = json.loads(resp.read())
            break
        except Exception:
            time.sleep(0.5)
    assert result == {"result": {"echo": {"msg": "hi"}}}, result

    with urllib.request.urlopen(
        "http://127.0.0.1:8000/-/healthz", timeout=10
    ) as resp:
        assert json.loads(resp.read())["status"] == "ok"


def test_autoscaling_up_and_down(cluster):
    @serve.deployment(
        autoscaling_config=dict(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1,
            upscale_delay_s=0.5,
            downscale_delay_s=2.0,
        ),
        max_ongoing_requests=10,
    )
    class Slow:
        def __call__(self, _):
            time.sleep(1.5)
            return "done"

    handle = serve.run(Slow.bind(), name="auto", _proxy=False)

    def n_running():
        st = serve.status()["auto"].deployments["Slow"]
        return sum(1 for r in st.replicas if r.state == "RUNNING")

    assert n_running() == 1
    # flood with concurrent requests to drive queue length up
    responses = [handle.remote(None) for _ in range(12)]
    deadline = time.time() + 45
    scaled = False
    while time.time() < deadline:
        if n_running() >= 2:
            scaled = True
            break
        responses.extend(handle.remote(None) for _ in range(3))
        time.sleep(0.5)
    assert scaled, "deployment did not scale up under load"
    for r in responses:
        try:
            r.result(timeout_s=60)
        except Exception:
            pass
    # idle: should scale back toward min_replicas
    deadline = time.time() + 60
    downscaled = False
    while time.time() < deadline:
        if n_running() <= 2:
            downscaled = True
            break
        time.sleep(0.5)
    assert downscaled, "deployment did not scale down when idle"


def test_delete_application(cluster):
    @serve.deployment
    class Temp:
        def __call__(self, _):
            return 1

    serve.run(Temp.bind(), name="temp", _proxy=False)
    assert "temp" in serve.status()
    serve.delete("temp")
    assert "temp" not in serve.status()


def test_serve_batch_accumulates(cluster):
    from ray_tpu import serve

    @serve.deployment
    class Batcher:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        async def handle(self, items):
            # one result per item, tagged with the batch size it rode in
            return [{"v": i * 2, "batch": len(items)} for i in items]

        async def __call__(self, x):
            return await self.handle(x)

    handle = serve.run(Batcher.bind(), name="batch-app", _proxy=False)
    responses = [handle.remote(i) for i in range(8)]
    results = [r.result(timeout_s=60) for r in responses]
    assert [r["v"] for r in results] == [2 * i for i in range(8)]
    # at least one call actually rode in a multi-item batch
    assert max(r["batch"] for r in results) >= 2
    serve.delete("batch-app")


def test_serve_batch_error_propagates(cluster):
    from ray_tpu import serve

    @serve.deployment
    class Bad:
        @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.05)
        async def handle(self, items):
            raise RuntimeError("batch exploded")

        async def __call__(self, x):
            return await self.handle(x)

    handle = serve.run(Bad.bind(), name="badbatch-app", _proxy=False)
    with pytest.raises(Exception, match="batch exploded"):
        handle.remote(1).result(timeout_s=60)
    serve.delete("badbatch-app")


def test_serve_multiplexed_lru(cluster):
    from ray_tpu import serve

    @serve.deployment
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id):
            self.loads.append(model_id)
            return {"id": model_id}

        async def __call__(self, _x):
            model = await self.get_model()
            return {
                "served_by": model["id"],
                "ctx": serve.get_multiplexed_model_id(),
                "loads": list(self.loads),
            }

    handle = serve.run(MultiModel.bind(), name="mux-app", _proxy=False)
    r1 = handle.options(multiplexed_model_id="m1").remote(0).result(timeout_s=60)
    assert r1["served_by"] == "m1" and r1["ctx"] == "m1"
    r2 = handle.options(multiplexed_model_id="m2").remote(0).result(timeout_s=60)
    # m1 cached: no reload
    r3 = handle.options(multiplexed_model_id="m1").remote(0).result(timeout_s=60)
    assert r3["loads"].count("m1") == 1
    # third model evicts LRU (m2); asking for m2 again reloads it
    handle.options(multiplexed_model_id="m3").remote(0).result(timeout_s=60)
    r5 = handle.options(multiplexed_model_id="m2").remote(0).result(timeout_s=60)
    assert r5["loads"].count("m2") == 2
    serve.delete("mux-app")


def test_prefix_affinity_key_stability():
    """The affinity key must be stable across processes (crc32, not
    hash()) and derived from the leading tokens only."""
    from ray_tpu.serve.handle import _prefix_affinity_key

    req = {"token_ids": list(range(40)), "max_new_tokens": 4}
    k1 = _prefix_affinity_key((req,), {}, 16)
    k2 = _prefix_affinity_key((), {"request": dict(req)}, 16)
    assert k1 is not None and k1 == k2
    # same head, different tail -> same key (that's the cache-reuse signal)
    other = {"token_ids": list(range(16)) + [999]}
    assert _prefix_affinity_key((other,), {}, 16) == k1
    # different head -> (almost surely) different key
    assert _prefix_affinity_key(({"token_ids": [7] * 16},), {}, 16) != k1
    # prompt-string fallback, and None when there is nothing to hash
    assert _prefix_affinity_key(({"prompt": "hello world"},), {}, 8) is not None
    assert _prefix_affinity_key((42, "x"), {}, 8) is None


def test_prefix_affinity_routes_same_prompt_to_same_replica(cluster):
    """handle.options(prefix_affinity_tokens=N): requests sharing a prompt
    prefix keep landing on one replica (where its KV blocks live) instead
    of spraying across the fleet pow2-style."""
    import os

    @serve.deployment(num_replicas=2)
    class Which:
        def __call__(self, request):
            return os.getpid()

    handle = serve.run(Which.bind(), name="affinity-app", _proxy=False)
    affine = handle.options(prefix_affinity_tokens=8)
    prompt = {"token_ids": [5, 6, 7, 8, 9, 10, 11, 12], "max_new_tokens": 2}
    pids = {
        affine.remote(dict(prompt)).result(timeout_s=60) for _ in range(6)
    }
    assert len(pids) == 1, f"shared prefix spread across replicas: {pids}"
    # a longer prompt with the same head co-locates with it
    longer = {"token_ids": prompt["token_ids"] + [99, 98], "max_new_tokens": 2}
    assert affine.remote(longer).result(timeout_s=60) in pids
    serve.delete("affinity-app")


def test_serve_batch_composes_with_multiplex(cluster):
    """@serve.batch under @serve.multiplexed: pending queues are
    partitioned by model id, so one flush never mixes models, and the
    batch task re-enters the model-id context — the handler's
    get_multiplexed_model_id() returns the batch's model, not ""
    (regression: a single shared queue interleaved m1/m2 items and the
    handler ran with an empty model id)."""
    from ray_tpu import serve

    @serve.deployment
    class MuxBatcher:
        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id):
            return {"id": model_id}

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        async def handle(self, items):
            # the whole point: the batch task must know its model id
            model = await self.get_model()
            ctx = serve.get_multiplexed_model_id()
            return [
                {"v": i, "model": model["id"], "ctx": ctx,
                 "batch": len(items)}
                for i in items
            ]

        async def __call__(self, x):
            return await self.handle(x)

    handle = serve.run(MuxBatcher.bind(), name="muxbatch-app", _proxy=False)
    responses = [
        (f"m{1 + i % 2}",
         handle.options(multiplexed_model_id=f"m{1 + i % 2}").remote(i))
        for i in range(8)
    ]
    results = [(m, r.result(timeout_s=60)) for m, r in responses]
    for i, (model_id, out) in enumerate(results):
        assert out["v"] == i
        assert out["model"] == model_id, "batch mixed models"
        assert out["ctx"] == model_id, "model-id context lost in batch task"
    # same-model requests still actually batch together
    assert max(out["batch"] for _m, out in results) >= 2
    serve.delete("muxbatch-app")


def test_local_testing_mode_no_cluster():
    """serve.run(_local_testing_mode=True) needs no cluster at all
    (reference: serve/_private/local_testing_mode.py)."""
    from ray_tpu import serve

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Gateway:
        def __init__(self, doubler):
            self.doubler = doubler

        def __call__(self, x):
            return self.doubler.remote(x).result() + 1

        async def aecho(self, x):
            return x

    app = Gateway.bind(Doubler.bind())
    handle = serve.run(app, _local_testing_mode=True)
    assert handle.remote(10).result() == 21
    # method routing + async methods work locally
    assert handle.options(method_name="aecho").remote("hi").result() == "hi"


def test_local_testing_mode_batching_and_multiplex():
    from ray_tpu import serve

    @serve.deployment
    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.02)
        async def __call__(self, items):
            return [i + 100 for i in items]

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            return {"id": model_id}

        async def which_model(self):
            model = await self.get_model()
            return model["id"]

    handle = serve.run(Batched.bind(), _local_testing_mode=True)
    rs = [handle.remote(i) for i in range(4)]
    assert [r.result(5) for r in rs] == [100, 101, 102, 103]
    out = (
        handle.options(multiplexed_model_id="m7", method_name="which_model")
        .remote()
        .result(5)
    )
    assert out == "m7"


def test_grpc_ingress(cluster):
    """gRPC proxy routes to deployments (reference: serve gRPC proxy path,
    proxy.py:533) via the generic bytes service."""
    from ray_tpu import serve

    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"echo": payload}

        def shout(self, payload):
            return str(payload).upper()

    serve.start(proxy=False, grpc_port=0)
    serve.run(Echo.bind(), _proxy=False)
    try:
        addr = serve.grpc_proxy_address()
        assert addr is not None
        out = serve.grpc_call(addr, {"x": 1})
        assert out == {"echo": {"x": 1}}
        out2 = serve.grpc_call(addr, "hi", method="shout")
        assert out2 == "HI"
    finally:
        serve.shutdown()


def test_response_chaining(cluster):
    """A DeploymentResponse passed into another handle call resolves to its
    VALUE before the downstream method runs (reference: model composition by
    passing responses between deployments)."""
    from ray_tpu import serve

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Adder:
        def __call__(self, x):
            assert isinstance(x, int), f"chained arg not resolved: {x!r}"
            return x + 1

    serve.run(Doubler.bind(), name="chain_doubler", _proxy=False)
    serve.run(Adder.bind(), name="chain_adder", _proxy=False)
    try:
        doubler = serve.get_app_handle("chain_doubler")
        adder = serve.get_app_handle("chain_adder")
        resp = doubler.remote(20)          # -> 40 (not awaited)
        out = adder.remote(resp).result(timeout_s=60)
        assert out == 41
    finally:
        serve.delete("chain_doubler")
        serve.delete("chain_adder")


def test_controller_crash_recovery(cluster):
    """Kill the controller worker under traffic: routers keep serving from
    their cached tables, the restarted controller recovers goal state from
    its GCS-KV checkpoint and re-adopts the SAME replicas — no churn
    (reference: controller.py:98-148 checkpoint/recover)."""
    import os
    import signal
    import time as _time

    from ray_tpu import _worker_api

    node = _worker_api.get_node()
    serve.start(proxy=False)

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return ("pid", os.getpid(), x)

    handle = serve.run(Echo.bind(), name="crashapp", _proxy=False)
    assert handle.remote(1).result(timeout_s=60)[2] == 1

    def replica_ids():
        st = serve.status()["crashapp"]
        return sorted(
            r.replica_id
            for dep in st.deployments.values()
            for r in dep.replicas
            if r.state == "RUNNING"
        )

    before = replica_ids()
    assert len(before) == 2

    # SIGKILL the controller's worker process
    ctrl_pids = [
        lease.worker.pid
        for lease in node.raylet._leases.values()
        if getattr(lease.spec, "actor_name", None) == "SERVE_CONTROLLER"
    ]
    assert len(ctrl_pids) == 1
    os.kill(ctrl_pids[0], signal.SIGKILL)

    # traffic keeps flowing through the handle's cached routing table while
    # the controller is down/restarting
    for i in range(10):
        assert handle.remote(i).result(timeout_s=60)[2] == i

    # the restarted controller converges to the SAME replica set
    deadline = _time.time() + 120
    after = None
    while _time.time() < deadline:
        try:
            after = replica_ids()
            if after == before:
                break
        except Exception:
            pass
        _time.sleep(0.5)
    assert after == before, (before, after)
    # and keeps managing: scale the app up through the recovered controller
    serve.delete("crashapp")
