"""Speculative decoding + chunked prefill (PR 19).

The two engine-loop optimizations share one correctness bar: they must be
invisible in the tokens. Temperature-0 parity pins the speculative verify
pass (accept = argmax match) and the chunked prefill scheduler against
the dense engine's greedy trajectory token-for-token; block accounting
pins rollback leak-freedom (a rejected proposal must not strand COW
blocks); the no-stall test pins the actual scheduling claim — in-flight
decodes keep emitting while a long prompt prefills in chunks.

Kept OUT of @pytest.mark.slow deliberately: temp-0 parity is the tier-1
gate the ISSUE names. Engines are module-scoped fixtures — jit programs
compile once per engine instance, so sharing the instance across tests
is what keeps this file tier-1-affordable.
"""

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.kvcache import KVCacheManager
from ray_tpu.llm import GenerationRequest, LLMConfig
from ray_tpu.llm.engine import ContinuousBatchingEngine
from ray_tpu.models.llama import Llama, LlamaConfig, init_params
from ray_tpu.parallel.sharding import unbox_params


@pytest.fixture(scope="module")
def tiny_pair():
    """Target + two 1-layer drafts over the same vocab. The target's
    second layer is zeroed to an exact identity (wo / w_down kernels = 0
    leave the residual stream untouched), so ``dsame`` — the surviving
    layer packaged as a 1-layer model — is mathematically the target:
    acceptance 1.0 by construction. ``drand`` is a different random
    model: acceptance ~0, every step exercises rejection/rollback. One
    engine + ``swap_params`` serves both regimes, halving this file's
    dominant cost (jit compiles are per engine instance)."""
    cfg = LlamaConfig.tiny(max_seq_len=128)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    z = jnp.zeros_like
    l1 = params["layer_1"]
    l1["attn"]["wo"]["base"]["kernel"] = z(l1["attn"]["wo"]["base"]["kernel"])
    l1["mlp"]["w_down"]["kernel"] = z(l1["mlp"]["w_down"]["kernel"])
    dcfg = LlamaConfig.tiny(max_seq_len=128, n_layers=1)
    drand = unbox_params(init_params(dcfg, jax.random.PRNGKey(1)))
    dsame = {k: params[k] for k in ("embed", "final_norm", "layer_0",
                                    "lm_head")}
    return cfg, params, dcfg, drand, dsame


def _engine(cfg, params, *, draft=None, k=0, chunk=0, num_blocks=64,
            num_slots=4):
    kv = KVCacheManager(num_blocks=num_blocks, block_size=8)
    eng = ContinuousBatchingEngine(
        cfg, params, num_slots=num_slots, kv_cache=kv, seed=0,
        draft=draft, spec_tokens=k, prefill_chunk_tokens=chunk,
    )
    return eng, kv


@pytest.fixture(scope="module")
def spec(tiny_pair):
    """The one speculative engine; tests swap the draft's params between
    ``drand`` (rejection-heavy) and ``dsame`` (acceptance 1.0)."""
    cfg, params, dcfg, drand, _ = tiny_pair
    return _engine(cfg, params, draft=(dcfg, drand), k=4)


@pytest.fixture(scope="module")
def chunked(tiny_pair):
    cfg, params, _, _, _ = tiny_pair
    return _engine(cfg, params, chunk=8)


def _assert_greedy_trajectory(cfg, params, prompt, generated):
    """Assert ``generated`` is the model's greedy continuation of
    ``prompt``: ONE teacher-forced apply over prompt+generated, then
    check each generated token is the argmax at its predecessor
    position. Equivalent to regenerating the greedy trajectory (by
    induction on the matching prefix) at 1/n the eager-apply cost."""
    model = Llama(cfg, None)
    seq = list(prompt) + list(generated)
    logits = model.apply({"params": params}, jnp.asarray([seq], jnp.int32))
    preds = [int(t) for t in jnp.argmax(logits[0], axis=-1)]
    for i, tok in enumerate(generated):
        assert tok == preds[len(prompt) - 1 + i], f"diverged at {i}"


# ONE prompt length (each distinct length costs a prefill compile for
# target AND draft — the dominant cost of this file); decode tails long
# enough to cross block boundaries cover the block-crossing paths
PROMPTS = [[1, 2, 3, 4, 5, 6, 7]]


class TestSpecParity:
    def test_spec_matches_dense_low_acceptance(self, tiny_pair, spec):
        """Random draft: ~every proposal rejected, so the emitted stream
        is built almost entirely from correction tokens + rollbacks — and
        must still equal the dense greedy trajectory exactly."""
        cfg, params, _, drand, _ = tiny_pair
        eng, _ = spec
        eng._draft.swap_params(drand)
        rids = [
            eng.add_request(
                GenerationRequest(token_ids=p, max_new_tokens=10)
            )
            for p in PROMPTS
        ]
        out = eng.run_until_complete()
        for rid, p in zip(rids, PROMPTS):
            assert len(out[rid].token_ids) == 10
            _assert_greedy_trajectory(cfg, params, p, out[rid].token_ids)

    def test_spec_matches_dense_full_acceptance(self, tiny_pair, spec):
        """Draft == target (the identity-layer construction): every
        proposal accepted — acceptance 1.0, the k+1-tokens-per-step fast
        path — and the same parity bar."""
        cfg, params, _, _, dsame = tiny_pair
        eng, _ = spec
        eng._draft.swap_params(dsame)
        prompt = [5, 4, 3, 2, 1, 6, 7]
        rid = eng.add_request(
            GenerationRequest(token_ids=prompt, max_new_tokens=12)
        )
        out = eng.run_until_complete()
        assert len(out[rid].token_ids) == 12
        _assert_greedy_trajectory(cfg, params, prompt, out[rid].token_ids)

    def test_spec_acceptance_metrics_move(self, tiny_pair, spec):
        from ray_tpu.util.metrics import llm_counters

        _, _, _, _, dsame = tiny_pair
        eng, _ = spec
        eng._draft.swap_params(dsame)
        before = llm_counters()
        # 7-token prompt reuses the fixture's already-compiled prefill
        eng.add_request(
            GenerationRequest(token_ids=[2, 5, 2, 5, 2, 5, 2],
                              max_new_tokens=8)
        )
        eng.run_until_complete()
        after = llm_counters()
        proposed = (
            after["spec_proposed_tokens"] - before["spec_proposed_tokens"]
        )
        accepted = (
            after["spec_accepted_tokens"] - before["spec_accepted_tokens"]
        )
        assert proposed > 0
        # identical draft: (almost) everything proposed is accepted
        assert accepted / proposed > 0.8
        assert after["itl_observations"] > before["itl_observations"]

    def test_spec_temperature_smoke(self, tiny_pair, spec):
        """temp>0 rides the rejection-sampling branch: emitted ids must be
        in-vocab and the request must complete (distribution equality is
        a statistical property; the deterministic bar is temp-0 parity)."""
        cfg, _, _, drand, _ = tiny_pair
        eng, _ = spec
        eng._draft.swap_params(drand)
        rid = eng.add_request(
            GenerationRequest(
                token_ids=[7, 6, 5, 4, 3, 2, 1], max_new_tokens=10,
                temperature=0.9,
            )
        )
        out = eng.run_until_complete()
        assert len(out[rid].token_ids) == 10
        assert all(0 <= t < cfg.vocab_size for t in out[rid].token_ids)

    def test_spec_headroom_guard(self, spec):
        eng, _ = spec
        with pytest.raises(ValueError, match="spec_tokens"):
            eng.add_request(
                GenerationRequest(token_ids=[1] * 100, max_new_tokens=26)
            )


class TestRollbackLeakFreedom:
    def test_blocks_return_to_baseline_after_rejections(self, tiny_pair,
                                                         spec):
        """Every block the radix index holds is accounted for after a
        rejection-heavy run retires all requests: in_use == index nodes
        (no stranded lease refs from speculative lease extension)."""
        _, _, _, drand, _ = tiny_pair
        eng, kv = spec
        eng._draft.swap_params(drand)
        for p in PROMPTS:
            eng.add_request(
                GenerationRequest(token_ids=p, max_new_tokens=16)
            )
        eng.run_until_complete()
        assert eng.num_active == 0
        assert kv.blocks_in_use == kv.stats()["index_nodes"]

    def test_extend_release_accounting(self):
        kv = KVCacheManager(num_blocks=16, block_size=8)
        lease = kv.acquire([1] * 17)  # 2 full blocks reserved
        base = kv.blocks_in_use
        got = kv.extend(lease, 3)
        assert got == 3
        assert kv.blocks_in_use == base + 3
        kv.release(lease)
        assert kv.blocks_in_use == 0
        # closed lease: extension refuses instead of leaking
        assert kv.extend(lease, 2) == 0


class TestChunkedPrefill:
    def test_chunked_matches_unchunked(self, tiny_pair, chunked):
        cfg, params, _, _, _ = tiny_pair
        prompt = list(range(1, 41))  # 40 tokens, budget 8/step
        eng, _ = chunked
        rid = eng.add_request(
            GenerationRequest(token_ids=prompt, max_new_tokens=8)
        )
        out = eng.run_until_complete()
        assert len(out[rid].token_ids) == 8
        _assert_greedy_trajectory(cfg, params, prompt, out[rid].token_ids)

    def test_chunked_prefill_with_prefix_hit(self, chunked):
        """A second request sharing a cached prefix still prefills only
        the suffix under a chunk budget — and stays token-identical."""
        from ray_tpu.util.metrics import kvcache_counters

        eng, kv = chunked
        prompt = [2] * 24
        r1 = eng.add_request(
            GenerationRequest(token_ids=prompt, max_new_tokens=4)
        )
        out1 = eng.run_until_complete()
        before = kvcache_counters()["prefix_hit_tokens"]
        r2 = eng.add_request(
            GenerationRequest(token_ids=prompt, max_new_tokens=4)
        )
        out2 = eng.run_until_complete()
        assert out2[r2].token_ids == out1[r1].token_ids
        assert kvcache_counters()["prefix_hit_tokens"] > before

    def test_decodes_do_not_stall_behind_long_prompt(self, chunked):
        """The scheduling claim itself: while a long prompt advances
        chunk-by-chunk, the in-flight short request emits one token EVERY
        step — no step gaps. Reuses the module engine (a fresh one would
        recompile every decode width this file already paid for)."""
        eng, _ = chunked
        short = eng.add_request(
            GenerationRequest(token_ids=[1] * 8, max_new_tokens=30)
        )
        eng.step()  # short admitted + first token
        long_prompt = list(range(80))
        eng.add_request(
            GenerationRequest(token_ids=long_prompt, max_new_tokens=4)
        )
        slot = next(iter(eng._slots.values()))
        assert slot.request_id == short
        prefilling_steps = 0
        for _ in range(60):
            before = len(slot.generated)
            eng.step()
            if eng._prefilling:
                # a long prefill is mid-flight AND the decode advanced
                prefilling_steps += 1
                assert len(slot.generated) == before + 1
                assert eng.last_step_prefill_tokens <= 8
            if eng.num_active == 0:
                break
        # 80 tokens / budget 8 => the long prompt was parked ~10 steps
        assert prefilling_steps >= 9
        assert eng.num_active == 0


class TestConfigKnobs:
    def test_spec_needs_draft(self):
        with pytest.raises(ValueError, match="draft_model"):
            LLMConfig(spec_tokens=4, kv_cache_blocks=32)

    def test_draft_defaults_spec_tokens(self):
        cfg = LLMConfig(draft_model="llama-tiny", kv_cache_blocks=32)
        assert cfg.spec_tokens == 4
        assert cfg.build_draft_model_config().max_seq_len == cfg.max_seq_len

    def test_spec_requires_paged_engine(self):
        with pytest.raises(ValueError, match="kv_cache_blocks"):
            LLMConfig(draft_model="llama-tiny")
        with pytest.raises(ValueError, match="kv_cache_blocks"):
            LLMConfig(prefill_chunk_tokens=256)

    def test_draft_max_seq_len_must_cover_target(self, tiny_pair):
        cfg, params, _, _, _ = tiny_pair
        dcfg = LlamaConfig.tiny(max_seq_len=64, n_layers=1)
        dparams = unbox_params(init_params(dcfg, jax.random.PRNGKey(1)))
        with pytest.raises(ValueError, match="max_seq_len"):
            _engine(cfg, params, draft=(dcfg, dparams), k=4)


class TestLongPrefillMixWorkload:
    def test_trace_classes_and_summary_itl(self):
        from ray_tpu.loadgen import (
            CallableTarget,
            LoadGenerator,
            long_prefill_mix,
        )

        trace = long_prefill_mix(
            40, rps=400.0, long_prompt_tokens=256,
            short_prompt_tokens=16, seed=3,
        )
        names = {r.cls for r in trace.requests}
        assert names == {"short_decode", "long_prefill"}
        longs = [r for r in trace.requests if r.cls == "long_prefill"]
        assert longs and all(len(r.token_ids) == 256 for r in longs)

        def fake_stream(payload):
            for _ in range(3):
                yield 0

        gen = LoadGenerator(CallableTarget(fake_stream), max_inflight=8)
        result = gen.run(trace, time_scale=0.01)
        summary = result.summary()
        assert set(summary["classes"]) == names
        sd = summary["classes"]["short_decode"]
        assert "itl_p99_ms" in sd  # streamed gaps landed per class
        assert all(len(r.itl_s) == 2 for r in result.ok)
