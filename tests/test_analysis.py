"""Tests for the static-analysis plane (ray_tpu.analysis, `ray_tpu lint`).

Three layers:

- per-checker fixture tests: each rule fires on a minimal positive fixture
  and stays silent on the matching negative one (the contract ISSUE 9's
  acceptance criteria name);
- framework tests: baseline split/round-trip, fingerprint stability, CLI
  exit codes (0 clean / 1 findings or stale / 2 internal error);
- the repo gate: the analyzer over the real ray_tpu package plus the
  committed baseline must report zero new findings and zero stale entries,
  and every exception class must survive a pickle round-trip with its typed
  fields intact (the dynamic twin of RT006).
"""

import inspect
import json
import pickle
import textwrap

import pytest

from ray_tpu import analysis, exceptions
from ray_tpu.analysis import (
    Analyzer,
    apply_baseline,
    checker_catalog,
    load_baseline,
    write_baseline,
)
from ray_tpu.scripts import cli


def _run(tmp_path, files, rules=None):
    """Write a fixture package under tmp_path/pkg and analyze it.

    Findings come back with paths like ``pkg/runtime/mod.py`` so the
    path-scoped rules (RT001's asyncio planes, RT004/RT005 home files) see
    the same shapes they see in the real repo.
    """
    pkg = tmp_path / "pkg"
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Analyzer(pkg, rules=rules, rel_to=tmp_path).run()


def _rules(result):
    return [f.rule for f in result.findings]


# ---------------------------------------------------------------- RT001


def test_rt001_flags_blocking_calls_in_async_def(tmp_path):
    result = _run(tmp_path, {
        "runtime/mod.py": """
            import time

            async def bad_sleep():
                time.sleep(1)

            async def bad_result(fut):
                return fut.result()

            async def bad_result_none(fut):
                return fut.result(timeout=None)
        """,
    }, rules=["RT001"])
    assert _rules(result) == ["RT001", "RT001", "RT001"]
    assert "time.sleep" in result.findings[0].message


def test_rt001_silent_on_sync_defs_and_bounded_result(tmp_path):
    result = _run(tmp_path, {
        "runtime/mod.py": """
            import time

            def sync_sleep_is_fine():
                time.sleep(1)

            async def bounded_result_is_fine(fut):
                return fut.result(timeout=5)

            async def nested_sync_def_is_fine():
                def helper():
                    time.sleep(1)
                return helper
        """,
    }, rules=["RT001"])
    assert result.findings == []


def test_rt001_scoped_to_asyncio_planes(tmp_path):
    # collective rendezvous loops legitimately sleep in sync threads; the
    # rule only patrols the asyncio planes (runtime/serve/dag/client/...)
    result = _run(tmp_path, {
        "collective/mod.py": """
            import time

            async def out_of_scope():
                time.sleep(1)
        """,
    }, rules=["RT001"])
    assert result.findings == []


# ---------------------------------------------------------------- RT002


def test_rt002_flags_raw_run_in_executor_and_global_trace_state(tmp_path):
    result = _run(tmp_path, {
        "runtime/worker/core_worker.py": """
            _current_trace = None

            class CoreWorker:
                async def bad(self, fn):
                    return await self.loop.run_in_executor(self._pool, fn)

                async def _run_traced(self, fn):
                    return await self.loop.run_in_executor(self._pool, fn)
        """,
    }, rules=["RT002"])
    msgs = [f.message for f in result.findings]
    assert len(result.findings) == 2
    assert any("run_in_executor" in m for m in msgs)
    assert any("ContextVar" in m for m in msgs)


def test_rt002_silent_on_run_traced_and_contextvar(tmp_path):
    result = _run(tmp_path, {
        "runtime/worker/core_worker.py": """
            import contextvars

            _current_trace = contextvars.ContextVar("trace", default=None)

            class CoreWorker:
                async def good(self, fn):
                    return await self._run_traced(fn)

                async def _run_traced(self, fn):
                    return await self.loop.run_in_executor(self._pool, fn)
        """,
        # run_in_executor outside core_worker.py is other planes' business
        "serve/proxy.py": """
            async def fine(loop, fn):
                return await loop.run_in_executor(None, fn)
        """,
    }, rules=["RT002"])
    assert result.findings == []


# ---------------------------------------------------------------- RT003


def test_rt003_flags_bare_write_to_lock_guarded_attr(tmp_path):
    result = _run(tmp_path, {
        "mod.py": """
            class S:
                def __init__(self):
                    self._count = 0  # exempt: no concurrency yet

                def guarded(self):
                    with self._lock:
                        self._count += 1

                def racy(self):
                    self._count = 0
        """,
    }, rules=["RT003"])
    assert len(result.findings) == 1
    f = result.findings[0]
    assert "S.racy" in f.message and "_count" in f.message


def test_rt003_silent_when_every_write_holds_the_lock(tmp_path):
    result = _run(tmp_path, {
        "mod.py": """
            class S:
                def guarded(self):
                    with self._lock:
                        self._count += 1

                def also_guarded(self):
                    with self._lock:
                        self._count = 0

                def read_only(self):
                    return self._count  # bare reads are not flagged
        """,
    }, rules=["RT003"])
    assert result.findings == []


# ---------------------------------------------------------------- RT004


def test_rt004_flags_registry_violations(tmp_path):
    result = _run(tmp_path, {
        "util/metrics.py": """
            class Counter:
                def __init__(self, name, description="", tag_keys=()):
                    pass

            a = Counter("tasks_total", tag_keys=("node",))
            b = Counter("tasks_total", tag_keys=("replica",))
            c = Counter("BadName")
        """,
        "serve/mod.py": """
            from ..util.metrics import Counter

            d = Counter("stray_metric")
        """,
    }, rules=["RT004"])
    msgs = " | ".join(f.message for f in result.findings)
    assert "declared 2 times" in msgs
    assert "conflicting" in msgs
    assert "not snake_case" in msgs
    assert "outside util/metrics.py" in msgs


def test_rt004_ignores_collections_counter(tmp_path):
    result = _run(tmp_path, {
        "serve/mod.py": """
            from collections import Counter

            votes = Counter("abracadabra")
        """,
    }, rules=["RT004"])
    assert result.findings == []


# ---------------------------------------------------------------- RT005


def test_rt005_flags_stray_key_literals_once_each(tmp_path):
    result = _run(tmp_path, {
        "mod.py": '''
            def keys(group, epoch, rank):
                plain = "colabort:" + group
                fstr = f"colmember:{group}:{epoch}:{rank}"
                return plain, fstr
        ''',
    }, rules=["RT005"])
    # one finding per literal — the f-string head must not double-report
    assert len(result.findings) == 2
    assert {f.line for f in result.findings} == {3, 4}


def test_rt005_exempts_registry_and_docstrings(tmp_path):
    result = _run(tmp_path, {
        "runtime/gcs/keys.py": """
            COLLECTIVE_ABORT = "colabort:"
        """,
        "mod.py": '''
            def sweeper():
                """Sweeps colabort:<group> keys (prose is fine)."""
                return None
        ''',
    }, rules=["RT005"])
    assert result.findings == []


# ---------------------------------------------------------------- RT006


def test_rt006_flags_custom_init_without_reduce(tmp_path):
    result = _run(tmp_path, {
        "exceptions.py": """
            class Bad(Exception):
                def __init__(self, code, detail):
                    self.code = code
                    super().__init__(f"error {code}: {detail}")
        """,
    }, rules=["RT006"])
    assert len(result.findings) == 1
    assert "Bad" in result.findings[0].message


def test_rt006_silent_with_reduce_or_default_init(tmp_path):
    result = _run(tmp_path, {
        "exceptions.py": """
            class Good(Exception):
                def __init__(self, code):
                    self.code = code
                    super().__init__(f"error {code}")

                def __reduce__(self):
                    return (type(self), (self.code,))

            class AlsoGood(Exception):
                pass
        """,
    }, rules=["RT006"])
    assert result.findings == []


# ---------------------------------------------------------------- RT007


def test_rt007_flags_event_registry_violations(tmp_path):
    result = _run(tmp_path, {
        "util/events.py": """
            class EventName(str):
                pass

            A = EventName("replica_state")
            B = EventName("replica_state")
            C = EventName("BadName")
            D = EventName("dyn_" + "amic")
        """,
        "serve/mod.py": """
            from ..util.events import EventName

            E = EventName("stray_event")
        """,
    }, rules=["RT007"])
    msgs = " | ".join(f.message for f in result.findings)
    assert "declared 2 times" in msgs
    assert "not snake_case" in msgs
    assert "literal string" in msgs
    assert "outside util/events.py" in msgs


def test_rt007_ignores_unrelated_classes(tmp_path):
    result = _run(tmp_path, {
        "serve/mod.py": """
            class EventName(str):
                pass

            local = EventName("Whatever Goes")
        """,
    }, rules=["RT007"])
    # an unimported local class of the same name is not the registry
    assert result.findings == []


# ---------------------------------------------------------------- RT009


def test_rt009_flags_hot_path_host_roundtrips(tmp_path):
    result = _run(tmp_path, {
        "llm/engine.py": """
            import jax
            import jax.numpy as jnp
            import numpy as np

            def decode_step(logits, x):
                a = jax.device_get(logits)
                logits.block_until_ready()
                b = np.asarray(jnp.argmax(logits, axis=-1))
                c = float(jnp.max(logits))
                return a, b, c
        """,
    }, rules=["RT009"])
    assert _rules(result) == ["RT009"] * 4
    msgs = " ".join(f.message for f in result.findings)
    assert "host_sync" in msgs


def test_rt009_host_sync_chokepoint_and_host_values_exempt(tmp_path):
    result = _run(tmp_path, {
        "kvcache/manager.py": """
            import jax.numpy as jnp
            import numpy as np

            def host_sync(x):
                return np.asarray(x.block_until_ready())

            def admit(token_ids, row):
                ids = np.asarray(token_ids, np.int32)  # host list: fine
                tok = int(row[0])                      # host array: fine
                dev = jnp.asarray(ids)                 # host->device: fine
                return ids, tok, dev
        """,
        "serve/router.py": """
            import jax

            def off_hot_path(x):
                return jax.device_get(x)  # out of scope for RT009
        """,
    }, rules=["RT009"])
    assert result.findings == []


# ---------------------------------------------------------------- RT010


def test_rt010_flags_direct_reduce_in_train(tmp_path):
    result = _run(tmp_path, {
        "train/loop.py": """
            from ray_tpu.collective import allreduce, reducescatter

            def train_step(group, grads, tensor):
                summed = group.allreduce(grads)
                shard = group.reducescatter(tensor)
                also = allreduce(grads)
                scattered = reducescatter(tensor)
                return summed, shard, also, scattered
        """,
    }, rules=["RT010"])
    assert _rules(result) == ["RT010"] * 4
    msgs = " ".join(f.message for f in result.findings)
    assert "reduce_gradients" in msgs


def test_rt010_wrapper_and_non_train_exempt(tmp_path):
    result = _run(tmp_path, {
        "train/collective.py": """
            from .. import collective as _collective

            def allreduce(value, op=None):
                kwargs = {} if op is None else {"op": op}
                return _collective.allreduce(value, **kwargs)

            def reduce_gradients(grads):
                return gradient_scheduler().step(grads)
        """,
        "collective/scheduler.py": """
            def reduce(self, group, flat):
                return group.allreduce(flat)  # scheduler internals: fine
        """,
        "rllib/learner.py": """
            def sync(group, grads):
                return group.allreduce(grads)  # not train/: out of scope
        """,
    }, rules=["RT010"])
    assert result.findings == []


# ---------------------------------------------------------------- RT011


def test_rt011_flags_raw_puts_in_serving_kv_paths(tmp_path):
    result = _run(tmp_path, {
        "kvtier/tier.py": """
            async def export(worker, meta, bufs):
                oid, _ = await worker.put_serialized(meta, bufs)
                return oid
        """,
        "kvcache/spill.py": """
            def spill(client, key, blob):
                return client.call("store_put", key, blob)
        """,
        "llm/engine.py": """
            async def stash(worker, meta, bufs):
                return await worker.put_serialized(meta, bufs)
        """,
    }, rules=["RT011"])
    assert _rules(result) == ["RT011"] * 3
    msgs = " ".join(f.message for f in result.findings)
    assert "_internal/transfer.py" in msgs
    assert "store_put" in msgs


def test_rt011_transfer_layer_and_other_planes_exempt(tmp_path):
    result = _run(tmp_path, {
        # the chokepoint itself: outside the patrolled paths
        "_internal/transfer.py": """
            async def put_chunks(worker, meta, bufs):
                return await worker.put_serialized(meta, bufs)
        """,
        # object plane proper: put_serialized is ITS primitive
        "runtime/worker/core_worker.py": """
            async def put(self, meta, bufs):
                return await self.put_serialized(meta, bufs)
        """,
        # other GCS RPCs in serving paths are fine, as is going through
        # the transfer layer
        "kvtier/registry.py": """
            from ray_tpu._internal import transfer

            async def register(client, shipment, worker, values):
                refs = await transfer.put_chunks(worker, values)
                return client.call("kvtier_register", shipment), refs
        """,
    }, rules=["RT011"])
    assert result.findings == []


# ---------------------------------------------------------------- RT013


def test_rt013_flags_bank_mutation_outside_store(tmp_path):
    result = _run(tmp_path, {
        "llm/engine.py": """
            def attach(self, store, tree, slot):
                store._bank = rebuild(store._bank, tree, slot)
        """,
        "serve/replica.py": """
            def hot_swap(self, store, tree, slot):
                store._write_slot(store._bank, tree, slot)
        """,
        "kvcache/manager.py": """
            def steal(self, pool):
                self._adapter_bank = pool
        """,
    }, rules=["RT013"])
    assert _rules(result) == ["RT013"] * 3  # 2 bank assigns + 1 raw call
    msgs = " ".join(f.message for f in result.findings)
    assert "AdapterStore" in msgs


def test_rt013_store_itself_and_other_planes_exempt(tmp_path):
    result = _run(tmp_path, {
        # the chokepoint itself: outside the patrolled paths
        "lora/store.py": """
            def acquire(self, adapter_id):
                self._bank = self._write_slot(self._bank, tree, slot)
        """,
        # leasing through the store API in serving paths is fine
        "llm/serving.py": """
            def resolve(self, store, adapter_id):
                lease = store.acquire(adapter_id)
                return lease
        """,
        # unrelated trains-plane code with its own _bank attr name is
        # out of scope by path
        "train/optim.py": """
            def init(self):
                self._bank = {}
        """,
    }, rules=["RT013"])
    assert result.findings == []


# ------------------------------------------------------------- framework


def test_catalog_has_all_thirteen_rules():
    assert sorted(checker_catalog()) == [
        "RT001", "RT002", "RT003", "RT004", "RT005", "RT006", "RT007",
        "RT008", "RT009", "RT010", "RT011", "RT012", "RT013",
    ]


def test_unknown_rule_id_rejected(tmp_path):
    with pytest.raises(ValueError, match="RT999"):
        Analyzer(tmp_path, rules=["RT999"])


def test_parse_error_reported_not_fatal(tmp_path):
    result = _run(tmp_path, {
        "broken.py": "def oops(:\n",
        "fine.py": "x = 1\n",
    })
    assert result.files_scanned == 1
    assert len(result.parse_errors) == 1
    assert "broken.py" in result.parse_errors[0]


def test_fingerprint_excludes_line_number():
    a = analysis.Finding(rule="RT001", path="p.py", line=3, message="m")
    b = analysis.Finding(rule="RT001", path="p.py", line=300, message="m")
    assert a.fingerprint == b.fingerprint


def test_baseline_split_and_round_trip(tmp_path):
    old = analysis.Finding(rule="RT003", path="a.py", line=1, message="old")
    fixed = analysis.Finding(rule="RT003", path="b.py", line=2, message="gone")
    fresh = analysis.Finding(rule="RT001", path="c.py", line=3, message="new")
    path = write_baseline([old, fixed], tmp_path / "baseline.json")
    entries = load_baseline(path)

    new, suppressed, stale = apply_baseline([old, fresh], entries)
    assert new == [fresh]
    assert suppressed == [old]
    assert [e["message"] for e in stale] == ["gone"]


def test_malformed_baseline_raises(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"version": 99}))
    with pytest.raises(ValueError, match="unsupported baseline"):
        load_baseline(p)
    assert load_baseline(tmp_path / "missing.json") == []


# ------------------------------------------------------------------ CLI


def _write_fixture(tmp_path, src):
    d = tmp_path / "scan"
    d.mkdir()
    (d / "mod.py").write_text(textwrap.dedent(src))
    return d


def test_cli_lint_exit_0_on_clean_tree(tmp_path, capsys):
    d = _write_fixture(tmp_path, "x = 1\n")
    assert cli.main(["lint", "--no-baseline", str(d)]) == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_cli_lint_exit_1_on_findings_and_json_shape(tmp_path, capsys):
    d = _write_fixture(tmp_path, """
        class Bad(Exception):
            def __init__(self, code):
                self.code = code
    """)
    (d / "mod.py").rename(d / "exceptions.py")
    assert cli.main(["lint", "--no-baseline", "--json", str(d)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["files_scanned"] == 1
    assert doc["counts"] == {"RT006": 1}
    assert doc["findings"][0]["rule"] == "RT006"
    assert doc["baselined"] == 0 and doc["stale_baseline"] == []


def test_cli_lint_exit_1_on_stale_baseline_entry(tmp_path, capsys):
    d = _write_fixture(tmp_path, "x = 1\n")
    baseline = tmp_path / "baseline.json"
    write_baseline(
        [analysis.Finding(rule="RT001", path="gone.py", line=1, message="m")],
        baseline,
    )
    assert cli.main(["lint", "--baseline", str(baseline), str(d)]) == 1
    assert "stale baseline" in capsys.readouterr().out


def test_cli_lint_exit_2_on_internal_error(tmp_path, capsys):
    d = _write_fixture(tmp_path, "x = 1\n")
    assert cli.main(["lint", "--rules", "RT999", str(d)]) == 2
    assert "internal error" in capsys.readouterr().err


def test_cli_lint_baseline_update_writes_file(tmp_path, capsys):
    d = _write_fixture(tmp_path, """
        class Bad(Exception):
            def __init__(self, code):
                self.code = code
    """)
    (d / "mod.py").rename(d / "exceptions.py")
    baseline = tmp_path / "baseline.json"
    assert cli.main(
        ["lint", "--baseline-update", "--baseline", str(baseline), str(d)]
    ) == 0
    assert len(load_baseline(baseline)) == 1
    # and with the baseline applied the same tree now gates clean
    capsys.readouterr()
    assert cli.main(["lint", "--baseline", str(baseline), str(d)]) == 0


# -------------------------------------------------------------- the gate


def test_repo_gate_zero_new_findings_zero_stale():
    """The committed invariant: the live tree minus the committed baseline
    is clean, and the baseline holds no entries for already-fixed findings
    (shrink-only policy). A failure here means either fix the new finding
    or—only for pre-existing debt—run `ray_tpu lint --baseline-update`."""
    pkg_root = analysis.DEFAULT_BASELINE_PATH.parents[1]
    repo_root = pkg_root.parent
    result = Analyzer(pkg_root, rel_to=repo_root).run()
    assert result.parse_errors == []
    assert result.files_scanned > 150

    new, _suppressed, stale = apply_baseline(
        result.findings, load_baseline()
    )
    assert new == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.message}" for f in new
    )
    assert stale == [], (
        "baseline entries for fixed findings — shrink the baseline: "
        + json.dumps(stale, indent=2)
    )


# ------------------------------------------------- exception pickle gate


_EXC_INSTANCES = [
    exceptions.RayTpuError("boom"),
    exceptions.TaskError("f", "tb text", ValueError("root cause")),
    exceptions.ActorError("actor failed"),
    exceptions.ActorDiedError("abc123", "oom killed"),
    exceptions.ActorUnschedulableError("no feasible node"),
    exceptions.WorkerCrashedError("sigsegv"),
    exceptions.NodeDiedError("node-2 heartbeat lost"),
    exceptions.ObjectLostError("obj1", "all copies gone"),
    exceptions.OwnerDiedError("obj2", "owner died"),
    exceptions.ObjectStoreFullError("store full"),
    exceptions.OutOfMemoryError("rss over limit"),
    exceptions.TaskCancelledError("task-7"),
    exceptions.GetTimeoutError("timed out after 5s"),
    exceptions.RuntimeEnvSetupError("pip env failed"),
    exceptions.PlacementGroupSchedulingError("infeasible bundle"),
    exceptions.CollectiveAbortedError("ring0", 3, "member died"),
    exceptions.BackPressureError("replica-1", 4, 9, 0.25),
    exceptions.DeadlineExceededError("deploy", 1.5, 1.0, "handle"),
    exceptions.ReplicaDrainingError("replica-2"),
    exceptions.NodeFencedError("node-3", "gcs unreachable"),
    exceptions.MeshValidationError("tp=3 does not divide 8 devices"),
    exceptions.RpcError("connection reset"),
    exceptions.PendingCallsLimitExceeded("queue cap"),
]


@pytest.mark.parametrize(
    "exc", _EXC_INSTANCES, ids=lambda e: type(e).__name__
)
def test_exception_pickle_round_trip(exc):
    """Every framework exception travels as an object value; pickling must
    preserve its concrete type, message, and typed fields (the serve retry
    envelope reads retry_after_s/deadline off the instance caller-side)."""
    back = pickle.loads(pickle.dumps(exc))
    assert type(back) is type(exc)
    assert str(back) == str(exc)
    assert set(back.__dict__) == set(exc.__dict__)
    for key, want in exc.__dict__.items():
        got = back.__dict__[key]
        if isinstance(want, BaseException):
            # exceptions compare by identity; structural check instead
            assert type(got) is type(want) and got.args == want.args
        else:
            assert got == want, key


def test_every_exception_class_is_round_tripped():
    """Coverage guard: adding an exception class without extending the
    round-trip list above fails here, not in production."""
    declared = {
        obj
        for obj in vars(exceptions).values()
        if inspect.isclass(obj) and issubclass(obj, exceptions.RayTpuError)
    }
    covered = {type(e) for e in _EXC_INSTANCES}
    assert declared <= covered, sorted(
        c.__name__ for c in declared - covered
    )
