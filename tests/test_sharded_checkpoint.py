"""Orbax sharded checkpoint (reference: ray.train.Checkpoint storage +
SURVEY §5's 'orbax-style async sharded checkpoint' TPU equivalent)."""

import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.train import (
    ShardedCheckpointWriter,
    restore_sharded,
    save_sharded,
)


@pytest.fixture
def state_and_mesh(tmp_path):
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    sh = NamedSharding(mesh, P("dp", "tp"))
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8), sh)
    return {"w": x, "step": jnp.asarray(3)}, mesh, str(tmp_path / "ckpt")


def test_save_restore_roundtrip(state_and_mesh):
    state, _mesh, path = state_and_mesh
    save_sharded(path, state)
    restored = restore_sharded(path)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(state["w"]))
    assert int(restored["step"]) == 3


def test_restore_onto_different_mesh(state_and_mesh):
    """Checkpoint from a 4x2 mesh restores onto a 2x4 mesh with a different
    partitioning — the elastic-restart path."""
    state, _mesh, path = state_and_mesh
    save_sharded(path, state)
    mesh2 = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "tp"))
    shardings = {
        "w": NamedSharding(mesh2, P(None, "tp")),
        "step": NamedSharding(mesh2, P()),
    }
    restored = restore_sharded(path, shardings=shardings)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(state["w"]))
    assert restored["w"].sharding.spec == P(None, "tp")


def test_async_writer_overlaps(state_and_mesh):
    state, _mesh, path = state_and_mesh
    writer = ShardedCheckpointWriter()
    try:
        writer.save(path, state)
        state2 = {"w": state["w"] * 2, "step": jnp.asarray(4)}
        # join the in-flight write before clearing the directory it targets
        writer.wait()
        shutil.rmtree(path, ignore_errors=True)
        writer.save(path, state2)
    finally:
        writer.close()
    restored = restore_sharded(path)
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.asarray(state["w"]) * 2
    )
