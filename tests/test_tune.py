"""Tests for ray_tpu.tune (reference model: python/ray/tune/tests/)."""

import pytest

import ray_tpu
from ray_tpu import tune


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


def test_variant_generation_grid_and_sample():
    from ray_tpu.tune.search import generate_variants

    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "bs": tune.choice([16, 32]),
        "nested": {"depth": tune.grid_search([2, 4])},
        "fixed": 7,
    }
    variants = generate_variants(space, num_samples=3, seed=0)
    assert len(variants) == 2 * 2 * 3
    assert all(v["fixed"] == 7 for v in variants)
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert {v["nested"]["depth"] for v in variants} == {2, 4}
    assert all(v["bs"] in (16, 32) for v in variants)


def test_sample_domains():
    from ray_tpu.tune.search import generate_variants

    space = {
        "u": tune.uniform(0, 1),
        "lu": tune.loguniform(1e-4, 1e-1),
        "ri": tune.randint(0, 10),
        "q": tune.quniform(0, 1, 0.25),
        "dep": tune.sample_from(lambda cfg: cfg["ri"] * 2),
    }
    (v,) = generate_variants(space, seed=42)
    assert 0 <= v["u"] <= 1
    assert 1e-4 <= v["lu"] <= 1e-1
    assert v["ri"] in range(10)
    assert v["q"] in (0, 0.25, 0.5, 0.75, 1.0)
    assert v["dep"] == v["ri"] * 2


def test_tuner_grid_best_result(cluster):
    def objective(config):
        score = -((config["x"] - 3) ** 2)
        tune.report({"score": score})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4, 5])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    )
    results = tuner.fit()
    assert len(results) == 6
    assert results.num_errors == 0
    best = results.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 0


def test_tuner_multi_iteration_and_stop_criteria(cluster):
    def train_fn(config):
        for i in range(100):
            tune.report({"loss": 1.0 / (i + 1)})

    tuner = tune.Tuner(
        train_fn,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=tune.RunConfig(stop={"training_iteration": 5}),
    )
    results = tuner.fit()
    assert len(results) == 2
    for r in results:
        assert r.metrics["training_iteration"] <= 10  # stopped early


def test_asha_prunes_bad_trials(cluster):
    def train_fn(config):
        for i in range(20):
            tune.report({"acc": config["quality"] * (i + 1)})

    tuner = tune.Tuner(
        train_fn,
        # best-first order: later (worse) trials land below the rung cutoff
        # set by earlier ones — ASHA's asynchronous pruning in action
        param_space={"quality": tune.grid_search([1.0, 0.5, 0.1, 0.0])},
        tune_config=tune.TuneConfig(
            metric="acc",
            mode="max",
            scheduler=tune.ASHAScheduler(
                metric="acc",
                mode="max",
                max_t=20,
                grace_period=2,
                reduction_factor=2,
            ),
            max_concurrent_trials=2,
        ),
    )
    results = tuner.fit()
    assert len(results) == 4
    best = results.get_best_result()
    assert best.config["quality"] == 1.0
    # at least one bad trial must have been stopped before max_t
    iters = [r.metrics.get("training_iteration", 0) for r in results]
    assert min(iters) < 20


def test_trial_failure_retry_then_error(cluster):
    def flaky(config):
        raise RuntimeError("boom")

    tuner = tune.Tuner(
        flaky,
        param_space={"x": tune.grid_search([1])},
        tune_config=tune.TuneConfig(metric="m", mode="max", max_failures=1),
    )
    results = tuner.fit()
    assert len(results) == 1
    assert results.num_errors == 1
    assert "boom" in results[0].error


def test_with_resources(cluster):
    def probe(config):
        tune.report({"ok": 1})

    tuner = tune.Tuner(
        tune.with_resources(probe, {"CPU": 1, "TPU": 1}),
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
    )
    results = tuner.fit()
    assert results.num_errors == 0
    assert len(results) == 2


def test_result_dataframe(cluster):
    def objective(config):
        tune.report({"score": config["x"]})

    results = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    ).fit()
    df = results.get_dataframe()
    assert len(df) == 3
    assert set(df["config/x"]) == {1, 2, 3}


@pytest.mark.slow  # 10s: PBT loop; ASHA/hyperband/TPE/BOHB stay tier-1
def test_pbt_perturbs_and_checkpoints(cluster):
    """Bottom-quantile trials clone a top trial's checkpoint + mutated
    config; cloned trials see the donor's progress via tune.get_checkpoint."""

    def objective(config):
        import time as _time

        ckpt = tune.get_checkpoint()
        step = ckpt["step"] if ckpt else 0
        best = ckpt["best"] if ckpt else 0.0
        for _ in range(40):
            step += 1
            # lr=0.5 is good, lr near 0 makes no progress
            best += config["lr"]
            # slow iterations: both runners must overlap (actor spawn takes
            # ~seconds) so the population has two live members to rank
            _time.sleep(0.15)
            tune.report(
                {"score": best}, checkpoint={"step": step, "best": best}
            )

    scheduler = tune.PopulationBasedTraining(
        metric="score",
        mode="max",
        perturbation_interval=5,
        hyperparam_mutations={"lr": tune.uniform(0.0, 0.5)},
        quantile_fraction=0.5,
        seed=0,
    )
    tuner = tune.Tuner(
        objective,
        param_space={"lr": tune.grid_search([0.001, 0.5])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", scheduler=scheduler,
            max_concurrent_trials=2,
        ),
        run_config=tune.RunConfig(stop={"training_iteration": 40}),
    )
    results = tuner.fit()
    assert len(results) == 2
    best = results.get_best_result()
    assert best.metrics["score"] > 10  # the good lr dominates
    # the originally-bad trial must have been perturbed toward the good one
    worst = min(
        (r for r in results if r.error is None),
        key=lambda r: r.metrics.get("score", 0),
    )
    assert worst.config["lr"] > 0.001 or worst.metrics["score"] > 1.0


def test_hyperband_brackets_stop_bad_trials(cluster):
    scheduler = tune.HyperBandScheduler(
        metric="acc", mode="max", max_t=27, reduction_factor=3
    )

    def objective(config):
        for i in range(27):
            tune.report({"acc": config["quality"] + i * 0.01})

    tuner = tune.Tuner(
        objective,
        param_space={"quality": tune.grid_search([0.1, 0.2, 0.8, 0.9])},
        tune_config=tune.TuneConfig(
            metric="acc", mode="max", scheduler=scheduler,
            max_concurrent_trials=4,
        ),
    )
    results = tuner.fit()
    assert len(results) == 4
    assert results.get_best_result().config["quality"] == pytest.approx(0.9)


def test_tpe_searcher_converges():
    """Pure searcher logic (no cluster): TPE should concentrate samples near
    the optimum after startup trials."""
    searcher = tune.TPESearcher(
        metric="loss", mode="min", n_startup_trials=8, seed=0
    )
    searcher.set_search_properties(
        "loss", "min", {"x": tune.uniform(-10, 10), "c": tune.choice(["a", "b"])}
    )
    for i in range(40):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        loss = (cfg["x"] - 2.0) ** 2 + (0.0 if cfg["c"] == "a" else 5.0)
        searcher.on_trial_complete(tid, {"loss": loss})
    late = [searcher.suggest(f"probe{i}") for i in range(10)]
    xs = [c["x"] for c in late]
    assert sum(abs(x - 2.0) < 4.0 for x in xs) >= 6
    assert sum(c["c"] == "a" for c in late) >= 6


def test_tpe_searcher_with_tuner(cluster):
    def objective(config):
        tune.report({"loss": (config["x"] - 1.0) ** 2})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(-5, 5)},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=12,
            search_alg=tune.TPESearcher(n_startup_trials=4, seed=1),
            max_concurrent_trials=2,
        ),
    )
    results = tuner.fit()
    assert len(results) == 12
    assert results.get_best_result().metrics["loss"] < 4.0


def test_tpe_nested_param_space():
    """Nested dict spaces must keep working past the startup phase."""
    searcher = tune.TPESearcher(
        metric="loss", mode="min", n_startup_trials=3, seed=0
    )
    searcher.set_search_properties(
        "loss", "min", {"opt": {"lr": tune.uniform(0.0, 1.0)}, "k": 5}
    )
    for i in range(10):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        assert isinstance(cfg["opt"], dict)
        assert isinstance(cfg["opt"]["lr"], float), cfg
        assert cfg["k"] == 5
        searcher.on_trial_complete(tid, {"loss": (cfg["opt"]["lr"] - 0.3) ** 2})


def test_bohb_searcher_models_largest_qualified_budget():
    """BOHB fits its density model on the largest budget with enough
    observations: results at budget 9 (good trials clustered at x=2) must
    override a misleading cluster reported at budget 1."""
    searcher = tune.BOHBSearcher(
        metric="loss", mode="min", n_startup_trials=4,
        random_fraction=0.0, seed=0,
    )
    searcher.set_search_properties("loss", "min", {"x": tune.uniform(-10, 10)})
    for i in range(12):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        x = cfg["x"]
        # budget-1 report: misleading metric favoring x near -8
        searcher.on_trial_result(
            tid, {"training_iteration": 1, "loss": (x + 8.0) ** 2}
        )
        # budget-9 report: true objective favoring x near 2
        searcher.on_trial_complete(
            tid, {"training_iteration": 9, "loss": (x - 2.0) ** 2}
        )
    late = [searcher.suggest(f"probe{i}") for i in range(10)]
    xs = [c["x"] for c in late]
    assert sum(abs(x - 2.0) < 4.0 for x in xs) >= 6, xs


@pytest.mark.slow  # 6s: BOHB stays tier-1 via test_bohb_searcher_models_largest_qualified_budget
def test_bohb_with_hyperband_tuner(cluster):
    def objective(config):
        for i in range(6):
            tune.report({"loss": (config["x"] - 1.0) ** 2 + 1.0 / (i + 1)})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(-5, 5)},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=12,
            search_alg=tune.BOHBSearcher(
                n_startup_trials=5, random_fraction=0.34, seed=2
            ),
            scheduler=tune.HyperBandForBOHB(max_t=6, reduction_factor=3),
            max_concurrent_trials=2,
        ),
    )
    results = tuner.fit()
    assert len(results) == 12
    # integration coverage (intermediate results reach the searcher, the
    # scheduler pairing runs): any sane search beats the worst-case corner
    assert results.get_best_result().metrics["loss"] < 16.0


def test_external_searcher_wrappers_are_gated():
    for cls in (tune.OptunaSearch, tune.HyperOptSearch):
        with pytest.raises(ImportError, match="TPESearcher"):
            cls()
