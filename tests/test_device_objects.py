"""Device objects / tensor_transport (reference: RDT GPU objects,
python/ray/experimental/gpu_object_manager + @ray.method(tensor_transport)).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.experimental import DeviceObjectRef


@ray_tpu.remote
class Producer:
    @ray_tpu.method(tensor_transport="device")
    def make(self, n):
        import jax.numpy as jnp

        return {"w": jnp.arange(n, dtype=jnp.float32), "step": 3}

    @ray_tpu.method(tensor_transport="device")
    def double_local(self, ref):
        # ref resolves zero-copy from this actor's own device store
        import jax

        return jax.tree.map(
            lambda x: x * 2 if hasattr(x, "shape") else x, ref
        )

    def scalar(self):
        return 42


@ray_tpu.remote
class Consumer:
    @ray_tpu.method(tensor_transport="device")
    def total(self, tree):
        # tree arrives resolved (fetched from the producer worker)
        import jax.numpy as jnp

        return float(jnp.sum(tree["w"])) + tree["step"]


def test_device_ref_roundtrip(ray_start_regular):
    p = Producer.remote()
    ref = ray_tpu.get(p.make.remote(8))
    assert isinstance(ref, DeviceObjectRef)
    assert "arrays" in ref.spec

    # consumer on another worker fetches the payload worker->worker
    c = Consumer.remote()
    out = ray_tpu.get(c.total.remote(ref))
    assert out == float(np.arange(8).sum()) + 3


def test_local_zero_copy_and_chaining(ray_start_regular):
    p = Producer.remote()
    ref = ray_tpu.get(p.make.remote(4))
    ref2 = ray_tpu.get(p.double_local.remote(ref))
    assert isinstance(ref2, DeviceObjectRef)
    c = Consumer.remote()
    assert ray_tpu.get(c.total.remote(ref2)) == float(
        (np.arange(4) * 2).sum()
    ) + 3


def test_scalar_results_pass_through(ray_start_regular):
    p = Producer.remote()
    assert ray_tpu.get(p.scalar.remote()) == 42


def test_driver_side_get_and_free(ray_start_regular):
    from ray_tpu.experimental import device_get, free_device_object

    p = Producer.remote()
    ref = ray_tpu.get(p.make.remote(5))
    tree = device_get(ref)
    assert float(tree["w"].sum()) == float(np.arange(5).sum())
    assert tree["step"] == 3

    assert free_device_object(ref)
    with pytest.raises(KeyError):
        device_get(ref)


def test_device_put_from_driver(ray_start_regular):
    import jax.numpy as jnp

    from ray_tpu.experimental import device_get, device_put_object

    ref = device_put_object({"x": jnp.ones((3, 3))})
    # local zero-copy hit returns the same pytree object
    tree = device_get(ref)
    assert tree["x"].shape == (3, 3)
    # an actor doubles the driver-owned object (worker fetches from driver),
    # the driver fetches the doubled result back from the worker
    p = Producer.remote()  # handle must outlive the fetch-back below
    ref2 = ray_tpu.get(p.double_local.remote(ref))
    tree2 = device_get(ref2)
    assert float(tree2["x"].sum()) == 18.0


def test_nested_refs_resolve(ray_start_regular):
    """Refs inside containers resolve too (the implicit-resolution promise)."""
    p = Producer.remote()
    c = Consumer.remote()
    r1 = ray_tpu.get(p.make.remote(3))

    @ray_tpu.remote
    class NestedConsumer:
        @ray_tpu.method(tensor_transport="device")
        def sum_nested(self, payload):
            import jax.numpy as jnp

            tree = payload["inner"][0]
            return float(jnp.sum(tree["w"]))

    n = NestedConsumer.remote()
    out = ray_tpu.get(n.sum_nested.remote({"inner": [r1]}))
    assert out == float(np.arange(3).sum())
