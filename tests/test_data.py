"""Tests for ray_tpu.data (reference test model: python/ray/data/tests/)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


def test_range_count_take(cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert rows == [{"id": i} for i in range(5)]


def test_from_items_and_schema(cluster):
    ds = rd.from_items([{"a": i, "b": float(i)} for i in range(10)])
    schema = ds.schema()
    assert set(schema) == {"a", "b"}
    assert ds.count() == 10


def test_map_filter_flatmap_fusion(cluster):
    ds = (
        rd.range(50, parallelism=4)
        .map(lambda r: {"id": r["id"] * 2})
        .filter(lambda r: r["id"] % 4 == 0)
    )
    # both stages fuse into one task stage
    assert "->" in ds.stats()
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == [i * 2 for i in range(50) if (i * 2) % 4 == 0]

    ds2 = rd.from_items([1, 2]).flat_map(lambda x: [x, x * 10])
    assert sorted(ds2.take_all()) == [1, 2, 10, 20]


def test_map_batches_tasks(cluster):
    ds = rd.range(64, parallelism=4).map_batches(
        lambda b: {"id": b["id"] + 1}, batch_size=8
    )
    assert sorted(r["id"] for r in ds.take_all()) == list(range(1, 65))


def test_map_batches_actor_pool(cluster):
    class Doubler:
        def __call__(self, batch):
            return {"id": batch["id"] * 2}

    ds = rd.range(32, parallelism=4).map_batches(
        Doubler, compute=rd.ActorPoolStrategy(size=2), batch_size=16
    )
    assert sorted(r["id"] for r in ds.take_all()) == [2 * i for i in range(32)]


def test_limit_stops_stream(cluster):
    ds = rd.range(1000, parallelism=8).limit(17)
    assert ds.count() == 17


def test_repartition_and_num_blocks(cluster):
    ds = rd.range(100, parallelism=4).repartition(7)
    assert ds.num_blocks() == 7
    assert ds.count() == 100


def test_random_shuffle_preserves_multiset(cluster):
    ds = rd.range(60, parallelism=3).random_shuffle(seed=7)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(60))
    assert vals != list(range(60))  # actually shuffled


def test_sort(cluster):
    ds = rd.from_items([{"x": i % 10, "y": i} for i in range(40)]).sort("x")
    xs = [r["x"] for r in ds.take_all()]
    assert xs == sorted(xs)
    ds_desc = rd.range(20, parallelism=2).sort("id", descending=True)
    assert [r["id"] for r in ds_desc.take_all()] == list(reversed(range(20)))


def test_groupby_aggregate(cluster):
    ds = rd.from_items(
        [{"k": i % 3, "v": float(i)} for i in range(30)]
    )
    out = ds.groupby("k").sum("v").take_all()
    by_key = {r["k"]: r["sum(v)"] for r in out}
    for k in (0, 1, 2):
        assert by_key[k] == sum(float(i) for i in range(30) if i % 3 == k)

    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}


def test_global_aggregates(cluster):
    ds = rd.range(10, parallelism=2)
    assert ds.sum("id") == 45
    assert ds.min("id") == 0
    assert ds.max("id") == 9
    assert ds.mean("id") == 4.5


def test_union_zip(cluster):
    a = rd.range(5, parallelism=1)
    b = rd.range(5, parallelism=1).map(lambda r: {"id": r["id"] + 5})
    assert sorted(r["id"] for r in a.union(b).take_all()) == list(range(10))

    left = rd.range(6, parallelism=2)
    right = rd.range(6, parallelism=2).map(lambda r: {"w": r["id"] * 10})
    rows = left.zip(right).take_all()
    assert sorted((r["id"], r["w"]) for r in rows) == [
        (i, 10 * i) for i in range(6)
    ]


def test_iter_batches_exact_sizes(cluster):
    ds = rd.range(100, parallelism=4)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [
        len(b["id"]) for b in ds.iter_batches(batch_size=32, drop_last=True)
    ]
    assert sizes == [32, 32, 32]


def test_iter_torch_batches(cluster):
    import torch

    ds = rd.range(8, parallelism=2)
    batches = list(ds.iter_torch_batches(batch_size=4))
    assert all(isinstance(b["id"], torch.Tensor) for b in batches)


def test_add_select_drop_rename(cluster):
    ds = (
        rd.range(10, parallelism=2)
        .add_column("sq", lambda b: b["id"] ** 2)
        .rename_columns({"id": "n"})
    )
    row = ds.sort("n").take(1)[0]
    assert row == {"n": 0, "sq": 0}
    assert ds.select_columns(["sq"]).schema() and ds.drop_columns(
        ["sq"]
    ).columns() == ["n"]


def test_materialize_reuse(cluster):
    ds = rd.range(20, parallelism=2).map(lambda r: {"id": r["id"] + 1})
    mat = ds.materialize()
    assert mat.count() == 20
    assert mat.count() == 20  # second consumption reuses blocks
    assert sorted(r["id"] for r in mat.take_all()) == list(range(1, 21))


def test_split(cluster):
    parts = rd.range(30, parallelism=3).split(3)
    all_vals = []
    for p in parts:
        all_vals.extend(r["id"] for r in p.take_all())
    assert sorted(all_vals) == list(range(30))


def test_streaming_split_disjoint_complete(cluster):
    its = rd.range(40, parallelism=4).streaming_split(2, equal=True)
    import threading

    results = [[], []]

    def consume(i):
        for b in its[i].iter_batches(batch_size=None):
            results[i].extend(b["id"].tolist())

    threads = [
        threading.Thread(target=consume, args=(i,)) for i in (0, 1)
    ]
    [t.start() for t in threads]
    [t.join(timeout=60) for t in threads]
    assert sorted(results[0] + results[1]) == list(range(40))
    assert results[0] and results[1]


def test_csv_json_roundtrip(cluster, tmp_path):
    ds = rd.from_items([{"a": i, "b": i * 0.5} for i in range(12)])
    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    back = rd.read_csv(csv_dir)
    assert back.count() == 12
    assert back.sum("a") == sum(range(12))

    json_dir = str(tmp_path / "json")
    ds.write_json(json_dir)
    back = rd.read_json(json_dir)
    assert back.count() == 12


def test_numpy_roundtrip(cluster, tmp_path):
    arr = np.arange(24, dtype=np.float32).reshape(6, 4)
    ds = rd.from_numpy(arr)
    out_dir = str(tmp_path / "npy")
    ds.write_numpy(out_dir)
    back = rd.read_numpy(out_dir)
    total = sum(b["data"].sum() for b in back.iter_batches(batch_size=None))
    assert float(total) == float(arr.sum())


def test_random_sample(cluster):
    ds = rd.range(1000, parallelism=4).random_sample(0.1, seed=3)
    n = ds.count()
    assert 40 < n < 250


def test_device_put_batches(cluster):
    import jax

    ds = rd.range_tensor(8, shape=(4,), parallelism=2)
    batches = list(ds.iter_batches(batch_size=4, device_put=True))
    assert all(isinstance(b["data"], jax.Array) for b in batches)


def test_join_inner(cluster):
    left = rd.from_items([{"id": i, "a": i * 10} for i in range(8)])
    right = rd.from_items([{"id": i, "b": i * 100} for i in range(4, 12)])
    rows = left.join(right, on="id").take_all()
    rows.sort(key=lambda r: r["id"])
    assert [r["id"] for r in rows] == [4, 5, 6, 7]
    assert all(r["b"] == r["id"] * 100 and r["a"] == r["id"] * 10 for r in rows)


def test_join_left_right_full(cluster):
    left = rd.from_items([{"id": i, "a": i} for i in range(4)])
    right = rd.from_items([{"id": i, "b": i} for i in range(2, 6)])
    lrows = left.join(right, on="id", join_type="left").take_all()
    assert sorted(r["id"] for r in lrows) == [0, 1, 2, 3]
    assert {r["id"]: r["b"] for r in lrows}[0] is None
    rrows = left.join(right, on="id", join_type="right").take_all()
    assert sorted(r["id"] for r in rrows) == [2, 3, 4, 5]
    frows = left.join(right, on="id", join_type="full").take_all()
    assert sorted(r["id"] for r in frows) == [0, 1, 2, 3, 4, 5]


def test_join_duplicate_columns_suffixed(cluster):
    left = rd.from_items([{"id": 1, "v": "L"}])
    right = rd.from_items([{"id": 1, "v": "R"}])
    rows = left.join(right, on="id").take_all()
    assert rows[0]["v"] == "L" and rows[0]["v_r"] == "R"


def test_join_many_to_many(cluster):
    left = rd.from_items([{"id": 1, "a": i} for i in range(3)])
    right = rd.from_items([{"id": 1, "b": j} for j in range(2)])
    rows = left.join(right, on="id").take_all()
    assert len(rows) == 6


def test_read_text(cluster, tmp_path):
    p1 = tmp_path / "a.txt"
    p1.write_text("hello\nworld\n\nfoo\n")
    p2 = tmp_path / "b.txt"
    p2.write_text("bar\n")
    ds = rd.read_text(str(tmp_path))
    rows = ds.take_all()
    assert sorted(r["text"] for r in rows) == ["bar", "foo", "hello", "world"]
    # keep empty lines when asked
    ds2 = rd.read_text(str(p1), drop_empty_lines=False)
    assert ds2.count() == 4


def test_read_binary_files(cluster, tmp_path):
    (tmp_path / "x.bin").write_bytes(b"\x00\x01\x02")
    (tmp_path / "y.bin").write_bytes(b"abc")
    ds = rd.read_binary_files(str(tmp_path), include_paths=True)
    rows = ds.take_all()
    assert len(rows) == 2
    by_path = {r["path"].rsplit("/", 1)[-1]: r["bytes"] for r in rows}
    assert by_path["x.bin"] == b"\x00\x01\x02"
    assert by_path["y.bin"] == b"abc"


@pytest.mark.slow
def test_iter_tf_batches(cluster):
    import ray_tpu.data as rd

    ds = rd.from_items([{"x": float(i), "y": i * 2} for i in range(10)])
    batches = list(ds.iter_tf_batches(batch_size=4))
    import tensorflow as tf

    assert len(batches) == 3
    assert all(isinstance(b["x"], tf.Tensor) for b in batches)
    total = sum(int(tf.reduce_sum(b["y"])) for b in batches)
    assert total == sum(i * 2 for i in range(10))
