"""ray_tpu.kvcache tests: paged, prefix-reusing KV-cache plane.

Three layers, bottom-up: the refcounted BlockAllocator (pure Python), the
PrefixIndex radix tree (match / insert / LRU evict), the KVCacheManager
lease lifecycle over a synthetic cache pytree (commit, assemble, COW,
backpressure), then end-to-end: the paged ContinuousBatchingEngine must be
token-for-token identical to the dense engine under greedy decoding —
including a second request that shares a prefix with the first and
prefills only its uncached suffix.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.kvcache import BlockAllocator, KVCacheManager, PrefixIndex


# ---------------------------------------------------------------------------
# BlockAllocator


class TestBlockAllocator:
    def test_allocate_release_accounting(self):
        a = BlockAllocator(4)
        assert a.capacity == 4 and a.num_free == 4
        bids = [a.allocate() for _ in range(4)]
        assert sorted(bids) == [0, 1, 2, 3]
        assert a.num_free == 0 and a.num_allocated == 4
        assert a.allocate() is None  # exhausted, no raise
        a.release(bids[0])
        assert a.num_free == 1
        assert a.allocate() == bids[0]  # returned to the free list

    def test_refcount_lifecycle(self):
        a = BlockAllocator(2)
        b = a.allocate()
        assert a.refcount(b) == 1
        a.ref(b)
        assert a.refcount(b) == 2
        a.release(b)
        assert a.refcount(b) == 1 and a.num_allocated == 1
        a.release(b)
        assert a.num_allocated == 0

    def test_release_free_block_raises(self):
        a = BlockAllocator(1)
        b = a.allocate()
        a.release(b)
        with pytest.raises(ValueError):
            a.release(b)

    def test_ref_free_block_raises(self):
        a = BlockAllocator(1)
        with pytest.raises(ValueError):
            a.ref(0)

    def test_cow_exclusive_reuses_block(self):
        copies = []
        a = BlockAllocator(2)
        b = a.allocate()
        out = a.copy_on_write(b, copy_fn=lambda s, d: copies.append((s, d)))
        assert out == b  # rc==1: writable in place, no copy
        assert copies == []

    def test_cow_shared_copies_and_moves_ref(self):
        copies = []
        a = BlockAllocator(2)
        b = a.allocate()
        a.ref(b)  # shared: rc == 2
        out = a.copy_on_write(b, copy_fn=lambda s, d: copies.append((s, d)))
        assert out is not None and out != b
        assert copies == [(b, out)]
        # the caller's ref moved: source back to rc 1, copy owned by caller
        assert a.refcount(b) == 1
        assert a.refcount(out) == 1

    def test_cow_exhausted_returns_none(self):
        a = BlockAllocator(1)
        b = a.allocate()
        a.ref(b)
        assert a.copy_on_write(b, copy_fn=lambda s, d: None) is None
        assert a.refcount(b) == 2  # rolled back, no ref leaked


# ---------------------------------------------------------------------------
# PrefixIndex


def _index(num_blocks=8, block_size=4):
    a = BlockAllocator(num_blocks)
    return PrefixIndex(block_size, a), a


class TestPrefixIndex:
    def test_match_walks_full_blocks_only(self):
        idx, a = _index(block_size=4)
        toks = list(range(10))  # 2 full blocks + 2-token tail
        n1 = idx.insert_child(idx.root, tuple(toks[0:4]), a.allocate())
        idx.insert_child(n1, tuple(toks[4:8]), a.allocate())
        matched = idx.match(toks, max_blocks=8)
        assert len(matched) == 2
        assert matched[0] is n1
        # divergent second block stops the walk after one match
        assert len(idx.match(toks[:4] + [99] * 4, max_blocks=8)) == 1
        assert idx.match([7] * 8, max_blocks=8) == []

    def test_match_respects_cap(self):
        idx, a = _index(block_size=2)
        node = idx.root
        for i in range(3):
            node = idx.insert_child(
                node, (2 * i, 2 * i + 1), a.allocate()
            )
        assert len(idx.match(list(range(6)), max_blocks=1)) == 1

    def test_insert_takes_its_own_ref(self):
        idx, a = _index()
        bid = a.allocate()
        idx.insert_child(idx.root, (1, 2, 3, 4), bid)
        # caller's allocate ref + the index's ref
        assert a.refcount(bid) == 2

    def test_evict_lru_releases_and_prefers_oldest(self):
        idx, a = _index(num_blocks=4, block_size=2)
        old = idx.insert_child(idx.root, (1, 2), a.allocate())
        new = idx.insert_child(idx.root, (3, 4), a.allocate())
        for n in (old, new):  # drop caller refs; index refs remain
            a.release(n.block_id)
        idx.touch(new)
        assert idx.evict_lru(1) == 1
        assert idx.child(idx.root, (1, 2)) is None  # oldest gone
        assert idx.child(idx.root, (3, 4)) is new
        assert a.num_allocated == 1

    def test_evict_skips_referenced_and_interior(self):
        idx, a = _index(num_blocks=4, block_size=2)
        parent = idx.insert_child(idx.root, (1, 2), a.allocate())
        leaf = idx.insert_child(parent, (3, 4), a.allocate())
        a.release(parent.block_id)  # interior: childless is false anyway
        # leaf keeps the caller ref => rc 2 => not evictable
        assert idx.evict_lru(1) == 0
        a.release(leaf.block_id)
        # now the leaf goes first, which unblocks the parent
        assert idx.evict_lru(2) == 2
        assert a.num_allocated == 0
        assert idx.num_evictions == 2


# ---------------------------------------------------------------------------
# KVCacheManager over a synthetic cache pytree (no model needed)


S, D = 32, 4  # max_seq_len, head_dim
BS = 8  # block_size


def _row(fill_fn):
    """A two-leaf fake decode cache: one KV leaf (1, 2, S, D) whose value
    at [0, h, t, d] is fill_fn(h, t, d), plus a write-position index."""
    h = jnp.arange(2).reshape(2, 1, 1)
    t = jnp.arange(S).reshape(1, S, 1)
    d = jnp.arange(D).reshape(1, 1, D)
    k = jnp.broadcast_to(
        jnp.asarray(fill_fn(h, t, d), jnp.float32), (2, S, D)
    )
    return {
        "k": k[None],
        "cache_index": jnp.zeros((1,), jnp.int32),
    }


def _mk_manager(num_blocks=4):
    m = KVCacheManager(num_blocks=num_blocks, block_size=BS)
    m.initialize(_row(lambda h, t, d: h * 0.0 + t * 0.0 + d * 0.0))
    return m


class TestKVCacheManager:
    def test_commit_assemble_roundtrip(self):
        m = _mk_manager()
        toks = list(range(20))  # 2 full blocks + tail
        lease = m.acquire(toks)
        assert lease is not None and lease.num_cached_tokens == 0
        assert len(lease.reserved) == 2
        m.commit(lease, toks, _row(lambda h, t, d: 100 * h + t + 0.01 * d))
        m.release(lease)

        lease2 = m.acquire(toks)
        assert lease2.num_cached_tokens == 16
        row = m.assemble(lease2)
        assert int(row["cache_index"][0]) == 16
        k = np.asarray(row["k"])[0]
        h, t, d = np.ogrid[0:2, 0:16, 0:D]
        np.testing.assert_allclose(k[:, :16], 100 * h + t + 0.01 * d)
        # past the cached region the row is zero padding
        assert not k[:, 16:].any()
        m.release(lease2)

    def test_acquire_never_matches_whole_prompt(self):
        m = _mk_manager()
        toks = list(range(16))  # exactly 2 blocks
        lease = m.acquire(toks)
        m.commit(lease, toks, _row(lambda h, t, d: t))
        m.release(lease)
        again = m.acquire(toks)
        # at least one token must be prefilled for first-token logits
        assert again.num_cached_tokens == 8
        m.release(again)

    def test_backpressure_blocks_then_resumes(self):
        m = _mk_manager(num_blocks=2)
        toks = list(range(16))
        holder = m.acquire(toks)
        m.commit(holder, toks, _row(lambda h, t, d: t))  # pool now full, pinned
        blocked = m.acquire([50 + i for i in range(16)])
        assert blocked is None  # no crash, no OOM: admission gate
        assert m.stats()["admission_blocked"] == 1
        m.release(holder)  # blocks become evictable
        resumed = m.acquire([50 + i for i in range(16)])
        assert resumed is not None and len(resumed.reserved) == 2
        assert m.stats()["evictions"] == 2
        m.release(resumed)

    def test_oversized_prompt_degrades_to_uncacheable(self):
        m = _mk_manager(num_blocks=2)
        toks = list(range(32))  # 4 blocks > capacity
        lease = m.acquire(toks)
        assert lease is not None and lease.cacheable is False
        assert m.commit(lease, toks, _row(lambda h, t, d: t)) == 0
        m.release(lease)
        assert m.blocks_in_use == 0

    def test_update_block_cow_preserves_shared_prefix(self):
        m = _mk_manager()
        toks = list(range(16))
        lease = m.acquire(toks)
        m.commit(lease, toks, _row(lambda h, t, d: 1.0 * t))
        shared = lease.pinned[0]
        # index holds a ref too => shared => COW must copy
        new_id = m.update_block(
            shared, _row(lambda h, t, d: -1.0 * t), tok_offset=0
        )
        assert new_id is not None and new_id != shared
        lease.pinned[lease.pinned.index(shared)] = new_id
        m.release(lease)

        # the index's original block is untouched
        lease2 = m.acquire(toks)
        k = np.asarray(m.assemble(lease2)["k"])[0]
        np.testing.assert_allclose(
            k[0, :8], np.broadcast_to(np.arange(8.0).reshape(8, 1), (8, D))
        )
        m.release(lease2)

    def test_decode_tail_commit_is_best_effort(self):
        m = _mk_manager(num_blocks=2)
        toks = list(range(16))
        lease = m.acquire(toks)
        m.commit(lease, toks, _row(lambda h, t, d: t))
        # pool exhausted: committing more full blocks silently stops
        longer = toks + list(range(100, 108))
        n = m.commit(lease, longer, _row(lambda h, t, d: t), pin=False)
        assert n == 0
        m.release(lease)

    def test_stats_shape(self):
        m = _mk_manager()
        s = m.stats()
        for key in (
            "requests", "hits", "misses", "prefix_hit_tokens",
            "prefill_tokens_computed", "admission_blocked", "capacity",
            "block_size", "blocks_in_use", "blocks_free", "evictions",
            "index_nodes",
        ):
            assert key in s


# ---------------------------------------------------------------------------
# End-to-end: paged engine == dense engine, token for token


@pytest.fixture(scope="module")
def paged_setup():
    from ray_tpu.llm.engine import ContinuousBatchingEngine, LLMEngine
    from ray_tpu.models.llama import LlamaConfig, init_params
    from ray_tpu.parallel.sharding import unbox_params

    cfg = LlamaConfig.tiny(max_seq_len=128)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    dense = LLMEngine(cfg, params, max_batch_size=4, seed=7)
    kv = KVCacheManager(num_blocks=32, block_size=16)
    paged = ContinuousBatchingEngine(
        cfg, params, num_slots=4, kv_cache=kv, seed=7
    )
    return dense, paged, kv


class TestPagedEngineEquality:
    def test_mixed_lengths_match_dense(self, paged_setup):
        from ray_tpu.llm.engine import GenerationRequest

        dense, paged, _ = paged_setup
        prompts = [
            list(range(5, 40)),  # 2 full blocks + tail
            list(range(100, 117)),  # 1 block + 1 token
            list(range(3, 10)),  # shorter than a block
        ]
        reqs = [
            GenerationRequest(token_ids=p, max_new_tokens=8, temperature=0.0)
            for p in prompts
        ]
        d = dense.generate(reqs)
        p = paged.generate(reqs)
        for i, (a, b) in enumerate(zip(d, p)):
            assert a.token_ids == b.token_ids, f"prompt {i} diverged"
            assert b.finished_reason == a.finished_reason

    def test_shared_prefix_second_request(self, paged_setup):
        """The warm path: a second request sharing the first's prefix must
        (a) hit the radix tree and prefill only the suffix, (b) still be
        token-identical to the dense engine."""
        from ray_tpu.llm.engine import GenerationRequest

        dense, paged, kv = paged_setup
        prefix = list(range(5, 40))  # cached by test_mixed_lengths (35 toks)
        prompt = prefix + [77, 78, 79]
        before = kv.stats()
        d = dense.generate(
            [GenerationRequest(token_ids=prompt, max_new_tokens=8,
                               temperature=0.0)]
        )[0]
        p = paged.generate(
            [GenerationRequest(token_ids=prompt, max_new_tokens=8,
                               temperature=0.0)]
        )[0]
        after = kv.stats()
        assert p.token_ids == d.token_ids
        hit = after["prefix_hit_tokens"] - before["prefix_hit_tokens"]
        computed = (
            after["prefill_tokens_computed"]
            - before["prefill_tokens_computed"]
        )
        assert hit == 32  # two 16-token blocks served from cache
        assert computed == len(prompt) - 32

    def test_eos_and_slot_reuse_with_cache(self, paged_setup):
        from ray_tpu.llm.engine import GenerationRequest

        dense, paged, _ = paged_setup
        prompt = list(range(40, 60))
        ref = dense.generate(
            [GenerationRequest(token_ids=prompt, max_new_tokens=6,
                               temperature=0.0)]
        )[0]
        eos = ref.token_ids[1]
        out = paged.generate(
            [GenerationRequest(token_ids=prompt, max_new_tokens=6,
                               temperature=0.0, eos_token_id=eos)]
        )[0]
        assert out.finished_reason == "eos"
        assert out.token_ids == ref.token_ids[:2]
        # no leaked slots or leases
        assert paged.num_active == 0
        assert not paged._slots


def test_memory_gated_admission_end_to_end():
    """A pool too small for two prompts at once: the second request stays
    pending (admission blocked, no OOM) until the first finishes, then
    admits and completes — and the totals balance at the end."""
    from ray_tpu.llm.engine import (
        ContinuousBatchingEngine,
        GenerationRequest,
    )
    from ray_tpu.models.llama import LlamaConfig, init_params
    from ray_tpu.parallel.sharding import unbox_params

    cfg = LlamaConfig.tiny(max_seq_len=128)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    kv = KVCacheManager(num_blocks=2, block_size=16)
    eng = ContinuousBatchingEngine(
        cfg, params, num_slots=4, kv_cache=kv, seed=3
    )
    r1 = eng.add_request(
        GenerationRequest(token_ids=list(range(5, 38)), max_new_tokens=4,
                          temperature=0.0)
    )
    r2 = eng.add_request(
        GenerationRequest(token_ids=list(range(60, 93)), max_new_tokens=4,
                          temperature=0.0)
    )
    eng.step()
    # r1 holds both blocks; r2 must be waiting, not crashed
    assert kv.stats()["admission_blocked"] >= 1
    assert eng.num_active == 2
    results = eng.run_until_complete()
    assert set(results) == {r1, r2}
    assert all(len(r.token_ids) == 4 for r in results.values())
    assert eng.num_active == 0


def test_kvcache_metrics_visible_in_state(cluster):
    """kvcache_* counters flow through the metrics pusher into
    state.metrics_summary() (and therefore the CLI/dashboard)."""
    import time

    from ray_tpu.util import state
    from ray_tpu.util.metrics import (
        record_kvcache_blocked,
        record_kvcache_prefill,
        record_kvcache_ttft,
        set_kvcache_blocks,
    )

    record_kvcache_prefill(48, 16)
    record_kvcache_blocked()
    set_kvcache_blocks(3, 64)
    record_kvcache_ttft(0.025, hit=True)
    record_kvcache_ttft(0.110, hit=False)

    deadline = time.time() + 20
    summary = {}
    while time.time() < deadline:
        summary = state.metrics_summary().get("kvcache", {})
        if summary.get("prefix_hit_tokens", 0) >= 48:
            break
        time.sleep(1)
    assert summary.get("prefix_hit_tokens", 0) >= 48
    assert summary.get("prefill_tokens_computed", 0) >= 16
    assert summary.get("admission_blocked", 0) >= 1
    assert summary.get("blocks_capacity") == 64
    ttft = summary.get("ttft_ms", {})
    assert ttft.get("hit", {}).get("count", 0) >= 1
    assert ttft.get("miss", {}).get("count", 0) >= 1
