"""Versioned delta resource sync (reference: RaySyncer ray_syncer.h:89 —
versioned, delta-suppressed resource views instead of full snapshots at the
report rate)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _spy_reports(gcs):
    """Re-register the GCS report handler with a capturing wrapper."""
    captured = []
    orig = gcs.handle_report_resources_delta

    async def spy(node_id, version, base_version, changed=None, removed=None,
                  demands=None):
        captured.append(
            dict(
                node_id=node_id, version=version, base_version=base_version,
                changed=changed, removed=removed, demands=demands,
            )
        )
        return await orig(
            node_id, version, base_version, changed=changed,
            removed=removed, demands=demands,
        )

    gcs.server.register("report_resources_delta", spy)
    return captured


def test_steady_state_reports_are_empty_deltas(cluster):
    """The wire cost claim: once availability settles, every periodic report
    is a pure heartbeat — no resource payload, version unchanged."""
    cluster.connect()

    @ray_tpu.remote
    def touch():
        return 1

    assert ray_tpu.get(touch.remote(), timeout=60) == 1
    # let post-task availability settle: the cached worker lease returns
    # after the idle TTL (lease reuse), and that return is itself a delta
    time.sleep(2.5)

    captured = _spy_reports(cluster.head_node.gcs)
    time.sleep(2.0)  # several report periods
    assert len(captured) >= 2, "reports stopped (heartbeats lost)"
    for report in captured:
        assert report["changed"] is None, report
        assert report["removed"] is None, report
        assert report["demands"] is None, report
        assert report["version"] == report["base_version"], report


def test_change_ships_only_touched_keys_and_bumps_version(cluster):
    cluster.connect()

    @ray_tpu.remote
    def warm():
        return 0

    ray_tpu.get(warm.remote(), timeout=60)
    time.sleep(2.5)  # warm's cached lease expires back -> availability settles
    captured = _spy_reports(cluster.head_node.gcs)

    # a different scheduling class than warm's (CPU:2), so this acquisition
    # cannot ride warm's cached lease and must show up as a resource delta
    @ray_tpu.remote(num_cpus=2)
    def hold():
        time.sleep(1.5)
        return 2

    ref = hold.remote()
    assert ray_tpu.get(ref, timeout=60) == 2
    time.sleep(2.5)  # hold's lease expires back -> view converges to idle

    deltas = [r for r in captured if r["changed"] is not None]
    assert deltas, "a CPU acquisition produced no delta"
    for report in deltas:
        # a delta carries only the touched keys (CPU here), never the
        # node's whole resource map with unchanged entries
        assert report["version"] == report["base_version"] + 1
        assert set(report["changed"]) <= {"CPU", "memory", "object_store_memory"}

    # and the GCS's applied view converged back to the idle availability
    gcs = cluster.head_node.gcs
    node_id = cluster.head_node.node_id
    avail = gcs._node_available[node_id]
    assert avail.get("CPU") == 2.0, avail


def test_gcs_resync_after_version_mismatch(cluster):
    """Lost state on the GCS (restart without durable store keeps the node
    table here — simulate by clearing the sync version) forces one full
    snapshot, then steady state goes quiet again."""
    cluster.connect()

    @ray_tpu.remote
    def warm():
        return 0

    ray_tpu.get(warm.remote(), timeout=60)
    time.sleep(2.5)  # settle: cached lease returned, reports gone quiet

    gcs = cluster.head_node.gcs
    node_id = cluster.head_node.node_id
    # simulate the GCS losing the sync stream state
    gcs._node_sync_versions[node_id] = -1
    gcs._node_available[node_id] = {}

    captured = _spy_reports(gcs)
    time.sleep(2.5)
    fulls = [r for r in captured if r["base_version"] is None]
    assert fulls, "no full snapshot after version mismatch"
    # the snapshot restored the availability view
    assert gcs._node_available[node_id].get("CPU") == 2.0
    # and afterwards reports went back to empty heartbeats
    after_full = captured[captured.index(fulls[-1]) + 1:]
    assert after_full and all(r["changed"] is None for r in after_full)
