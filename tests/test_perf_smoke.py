"""Microbenchmark suite smoke (reference: _private/ray_perf.py metrics run
in release/microbenchmark) — correctness of the harness, not speed."""

import pytest

import ray_tpu
from ray_tpu._internal.perf import run_microbenchmarks


def test_microbenchmarks_produce_all_metrics(shutdown_only):
    results = run_microbenchmarks(small=True)
    expected = {
        "single_client_put_1kb",
        "single_client_get_1kb",
        "single_client_put_get_gb_s",
        "single_client_tasks_sync",
        "single_client_tasks_async",
        "one_to_one_actor_calls_sync",
        "one_to_one_actor_calls_async",
        "single_client_wait_100_refs_s",
        "rpcs_per_task_sync",
        "lease_rpcs_per_task_sync",
        "weights_publish_mb_s",
        "weights_subscribe_x1_mb_s",
        "weights_subscribe_x2_mb_s",
    }
    assert expected <= set(results)
    for metric, value in results.items():
        if "per_task" in metric:
            # ratios where 0 is the optimum (warm lease cache -> 0 lease
            # RPCs); the push itself keeps rpcs_per_task >= 1
            assert value >= 0, (metric, value)
        else:
            assert value > 0, (metric, value)
    assert results["rpcs_per_task_sync"] >= 1
    assert not ray_tpu.is_initialized()  # the suite cleans up after itself


def test_microbenchmark_json_output(shutdown_only):
    """The CLI's machine-readable mode (BENCH_LOG.md appends): every metric
    carries a unit, and the per-method RPC latency histograms ride along."""
    import json

    from ray_tpu._internal.perf import json_results, metric_unit

    results = run_microbenchmarks(small=True)
    doc = json.loads(json_results(results))
    assert set(doc["metrics"]) == set(results)
    for name, entry in doc["metrics"].items():
        assert entry["unit"] == metric_unit(name)
    lat = doc["rpc_latency_ms"]
    assert "push_task" in lat and lat["push_task"]["count"] > 0
    assert "buckets" in lat["push_task"]


def test_warm_stream_lease_rpcs_regression_guard(shutdown_only):
    """Regression guard for lease reuse (counter-based, stable on a 1-core
    box): a warm same-class task stream must issue at most one lease RPC
    total — NOT one per task."""
    from ray_tpu.util import metrics

    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def noop(i):
        return i

    ray_tpu.get(noop.remote(0))  # warm: acquire + cache the lease
    before = metrics.rpc_calls_by_method()
    n = 25
    for i in range(n):
        assert ray_tpu.get(noop.remote(i)) == i
    after = metrics.rpc_calls_by_method()
    lease_delta = after.get("request_worker_lease", 0.0) - before.get(
        "request_worker_lease", 0.0
    )
    push_delta = after.get("push_task", 0.0) - before.get("push_task", 0.0)
    assert lease_delta <= 1, f"{lease_delta} lease RPCs for {n} warm tasks"
    assert push_delta == n


def test_tracing_disabled_overhead_guard(shutdown_only, monkeypatch):
    """The tracing plane must never silently tax the hot path: with
    RAY_TPU_TRACE unset, tasks_sync throughput stays within 5% of an
    untraced baseline (driver-side tracing hooks stubbed to no-ops), and
    zero spans are recorded anywhere."""
    import time as _time

    monkeypatch.delenv("RAY_TPU_TRACE", raising=False)
    from ray_tpu.util import tracing

    tracing._enabled = False
    assert not tracing.is_tracing_enabled()
    tracing.clear_spans()
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def noop(i):
        return i

    def measure(n=150):
        t0 = _time.perf_counter()
        for i in range(n):
            ray_tpu.get(noop.remote(i))
        return n / (_time.perf_counter() - t0)

    measure(40)  # warm the lease cache + code paths

    real_enabled = tracing.is_tracing_enabled
    real_inject = tracing.inject_context

    def baseline_throughput():
        tracing.is_tracing_enabled = lambda: False
        tracing.inject_context = lambda: None
        try:
            return measure()
        finally:
            tracing.is_tracing_enabled = real_enabled
            tracing.inject_context = real_inject

    # interleave measurements; pass when any attempt is within tolerance
    # (single-box timing noise dwarfs the one-boolean-check difference)
    ratios = []
    for _ in range(4):
        base = baseline_throughput()
        real = measure()
        ratios.append(real / base)
        if real >= 0.95 * base:
            break
    assert ratios[-1] >= 0.95, (
        f"disabled-tracing path slower than untraced baseline: {ratios}"
    )
    assert tracing.get_spans() == []  # plane fully dormant when disabled


def test_serve_tracing_disabled_overhead_guard(shutdown_only, monkeypatch):
    """The serve request path carries the same guarantee as tasks_sync:
    with tracing off, handle round-trip throughput stays within 5% of a
    baseline with the tracing hooks stubbed out, and the whole request
    (handle -> replica) emits zero spans anywhere."""
    import time as _time

    monkeypatch.delenv("RAY_TPU_TRACE", raising=False)
    from ray_tpu import serve
    from ray_tpu.util import tracing

    tracing._enabled = False
    assert not tracing.is_tracing_enabled()
    tracing.clear_spans()
    ray_tpu.init(num_cpus=4)

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind(), name="perfguard", _proxy=False)
    try:

        def measure(n=40):
            t0 = _time.perf_counter()
            for i in range(n):
                assert handle.remote(i).result(timeout_s=30) == i
            return n / (_time.perf_counter() - t0)

        measure(15)  # warm the router table + replica

        real_enabled = tracing.is_tracing_enabled
        real_inject = tracing.inject_context

        def baseline_throughput():
            tracing.is_tracing_enabled = lambda: False
            tracing.inject_context = lambda: None
            try:
                return measure()
            finally:
                tracing.is_tracing_enabled = real_enabled
                tracing.inject_context = real_inject

        # interleave; pass when any attempt is within tolerance (single-box
        # timing noise dwarfs the per-request None-check difference)
        ratios = []
        for _ in range(4):
            base = baseline_throughput()
            real = measure()
            ratios.append(real / base)
            if real >= 0.95 * base:
                break
        assert ratios[-1] >= 0.95, (
            f"disabled-tracing serve path slower than baseline: {ratios}"
        )
        # zero spans: none recorded driver-side, none flushed from the
        # replica to the GCS span store (its pusher runs on a 1s cadence)
        assert tracing.get_spans() == []
        _time.sleep(1.5)
        cluster_spans = [
            s for s in tracing.timeline() if s.get("span_id")
        ]
        assert cluster_spans == [], cluster_spans
    finally:
        serve.shutdown()


def test_router_pick_fast_allocates_no_dicts():
    """The per-request routing pick runs tens of thousands of times a
    second per proxy at saturation; it must stay index arithmetic over the
    precomputed view — building a dict per request is the regression this
    guards against. dis-based so it fails on the allocation being
    *reintroduced*, not on a timing artifact of a noisy box."""
    import dis

    from ray_tpu.serve.handle import Router

    banned = {"BUILD_MAP", "MAP_ADD", "DICT_MERGE", "DICT_UPDATE",
              "BUILD_CONST_KEY_MAP"}
    ops = {ins.opname for ins in dis.get_instructions(Router._pick_fast)}
    assert not (ops & banned), ops & banned


@pytest.mark.slow
def test_multiproxy_tracing_disabled_overhead_guard(shutdown_only,
                                                    monkeypatch):
    """The multi-proxy data plane must not tax the single-proxy request
    path: with tracing off, per-request HTTP round-trip throughput through
    a 2-proxy SO_REUSEPORT ingress stays within 5% of a 1-proxy ingress
    (same port semantics, persistent connection — the per-request work is
    identical; only the listener count differs)."""
    import http.client
    import json as _json
    import time as _time

    monkeypatch.delenv("RAY_TPU_TRACE", raising=False)
    from ray_tpu import serve
    from ray_tpu.util import tracing

    tracing._enabled = False
    assert not tracing.is_tracing_enabled()
    ray_tpu.init(num_cpus=4)
    port = 18290

    def start(n):
        serve.shutdown()
        serve.start(http_port=port, num_proxies=n)

        @serve.deployment
        class Echo:
            def __call__(self, x):
                return x

        serve.run(Echo.bind(), name="mpguard", route_prefix="/")

    def measure_once(n_requests=40):
        body = _json.dumps({"x": 1}).encode()
        headers = {"Content-Type": "application/json"}
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            # warm the connection + routing table off the clock
            for _ in range(5):
                conn.request("POST", "/", body, headers)
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 200
            t0 = _time.perf_counter()
            for _ in range(n_requests):
                conn.request("POST", "/", body, headers)
                resp = conn.getresponse()
                resp.read()
            return n_requests / (_time.perf_counter() - t0)
        finally:
            conn.close()

    def measure():
        # best-of-3: the work per request is identical across samples, so
        # the max is the sample least perturbed by scheduler noise
        return max(measure_once() for _ in range(3))

    try:
        # interleave 1-proxy / 2-proxy rounds; pass when any round is
        # within tolerance (single-box timing noise dwarfs the per-request
        # difference, which should be zero)
        ratios = []
        for _ in range(4):
            start(1)
            base = measure()
            start(2)
            multi = measure()
            ratios.append(multi / base)
            if multi >= 0.95 * base:
                break
        assert max(ratios) >= 0.95, (
            f"multi-proxy request path slower than single-proxy: {ratios}"
        )
    finally:
        serve.shutdown()


def test_prefix_cache_prefill_computes_only_suffix():
    """Perf guard for the KV-cache plane (CPU-safe, counter-based): a
    repeated prompt must prefill ONLY the tokens past its cached prefix —
    the counters are what bench.py's llm_prefix_cache TTFT win rests on,
    and a silent full-prefill regression would keep outputs correct while
    erasing the speedup."""
    import jax

    from ray_tpu.kvcache import KVCacheManager
    from ray_tpu.llm.engine import ContinuousBatchingEngine, GenerationRequest
    from ray_tpu.models.llama import LlamaConfig, init_params
    from ray_tpu.parallel.sharding import unbox_params

    cfg = LlamaConfig.tiny(max_seq_len=128)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    kv = KVCacheManager(num_blocks=16, block_size=16)
    eng = ContinuousBatchingEngine(cfg, params, num_slots=2, kv_cache=kv)
    prompt = list(range(7, 7 + 56))  # 3 full blocks + 8-token tail

    eng.generate([GenerationRequest(token_ids=prompt, max_new_tokens=2,
                                    temperature=0.0)])
    s0 = kv.stats()
    assert s0["prefill_tokens_computed"] == len(prompt)  # cold: everything

    eng.generate([GenerationRequest(token_ids=prompt, max_new_tokens=2,
                                    temperature=0.0)])
    s1 = kv.stats()
    computed = s1["prefill_tokens_computed"] - s0["prefill_tokens_computed"]
    hit = s1["prefix_hit_tokens"] - s0["prefix_hit_tokens"]
    assert hit == 48, f"expected 3 cached blocks (48 tokens), hit {hit}"
    assert computed == len(prompt) - 48, (
        f"fully-cached prefix recomputed {computed} tokens, "
        f"expected only the {len(prompt) - 48}-token suffix"
    )


def test_chunked_prefill_respects_step_budget():
    """Perf guard for the chunked-prefill scheduler (CPU-safe,
    counter-based): with prefill_chunk_tokens set, NO engine step may
    compute more prefill tokens than the budget — the whole point is
    bounding the per-step stall a long prompt can impose on in-flight
    decodes. Also pins the floor: the prompt must take at least
    ceil(plen / budget) steps to admit (no silent budget bypass)."""
    import math

    import jax

    from ray_tpu.kvcache import KVCacheManager
    from ray_tpu.llm.engine import ContinuousBatchingEngine, GenerationRequest
    from ray_tpu.models.llama import LlamaConfig, init_params
    from ray_tpu.parallel.sharding import unbox_params

    budget = 16
    cfg = LlamaConfig.tiny(max_seq_len=256)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    kv = KVCacheManager(num_blocks=32, block_size=16)
    eng = ContinuousBatchingEngine(
        cfg, params, num_slots=2, kv_cache=kv,
        prefill_chunk_tokens=budget,
    )
    plen = 100
    eng.add_request(GenerationRequest(
        token_ids=list(range(plen)), max_new_tokens=2, temperature=0.0,
    ))
    steps = 0
    while eng.num_active:
        eng.step()
        steps += 1
        assert eng.last_step_prefill_tokens <= budget, (
            f"step computed {eng.last_step_prefill_tokens} prefill "
            f"tokens, budget is {budget}"
        )
        assert steps < 100
    assert steps >= math.ceil(plen / budget)


def test_scale_smoke_queued_tasks(shutdown_only):
    """Queue-depth envelope smoke (BASELINE.md 'tasks queued on a single
    node'): hundreds of queued no-op tasks on 2 workers all complete
    correctly. (Sized for the 1-core CI box; the envelope itself is
    documented in BASELINE.md.)"""
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(400)]
    out = ray_tpu.get(refs, timeout=600)
    assert out == list(range(400))


@pytest.mark.slow
def test_scale_smoke_many_actors(shutdown_only):
    """Actor-count envelope smoke: 16 concurrently alive zero-cpu actors
    (sized for the 1-core CI box; the reference envelope is BASELINE.md's)."""
    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    actors = [A.remote(i) for i in range(16)]
    assert ray_tpu.get([a.who.remote() for a in actors], timeout=600) == list(
        range(16)
    )
    for a in actors:
        ray_tpu.kill(a)


def test_scale_100_virtual_nodes(shutdown_only):
    """Scalability quantification (BASELINE.md's 2,000-node envelope,
    scaled to a 1-core CI box): a 100-raylet in-process cluster must
    register quickly, serve O(n) cluster views fast, and dispatch work
    across the full node set. Prints timings for BENCH_LOG.md."""
    import time

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args=dict(num_cpus=1))
    t0 = time.perf_counter()
    for i in range(99):
        cluster.add_node(num_cpus=1, resources={f"node{i}": 1.0})
    register_s = time.perf_counter() - t0
    cluster.connect()
    try:
        import ray_tpu as rt

        deadline = time.time() + 60
        while time.time() < deadline:
            if len(rt.nodes()) >= 100:
                break
            time.sleep(0.2)
        nodes = rt.nodes()
        assert len(nodes) == 100, len(nodes)

        t0 = time.perf_counter()
        for _ in range(20):
            res = rt.cluster_resources()
        view_ms = (time.perf_counter() - t0) / 20 * 1000
        assert res.get("CPU", 0) == 100.0

        # dispatch across distinct far nodes via custom-resource pinning
        @rt.remote(num_cpus=0)
        def where():
            import os
            return os.getpid()

        t0 = time.perf_counter()
        refs = [
            where.options(resources={f"node{i * 12}": 1.0}).remote()
            for i in range(8)
        ]
        pids = rt.get(refs, timeout=300)
        dispatch_s = time.perf_counter() - t0
        assert len(set(pids)) == 8  # eight distinct nodes executed

        print(
            f"scale100: register_99_nodes={register_s:.2f}s "
            f"cluster_view={view_ms:.2f}ms "
            f"8_cross_node_dispatch={dispatch_s:.2f}s"
        )
        assert register_s < 120
        assert view_ms < 200
    finally:
        import ray_tpu

        ray_tpu.shutdown()
        cluster.shutdown()
