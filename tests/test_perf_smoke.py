"""Microbenchmark suite smoke (reference: _private/ray_perf.py metrics run
in release/microbenchmark) — correctness of the harness, not speed."""

import ray_tpu
from ray_tpu._internal.perf import run_microbenchmarks


def test_microbenchmarks_produce_all_metrics(shutdown_only):
    results = run_microbenchmarks(small=True)
    expected = {
        "single_client_put_1kb",
        "single_client_get_1kb",
        "single_client_put_get_gb_s",
        "single_client_tasks_sync",
        "single_client_tasks_async",
        "one_to_one_actor_calls_sync",
        "one_to_one_actor_calls_async",
        "single_client_wait_100_refs_s",
    }
    assert expected <= set(results)
    for metric, value in results.items():
        assert value > 0, (metric, value)
    assert not ray_tpu.is_initialized()  # the suite cleans up after itself


def test_scale_smoke_queued_tasks(shutdown_only):
    """Queue-depth envelope smoke (BASELINE.md 'tasks queued on a single
    node'): hundreds of queued no-op tasks on 2 workers all complete
    correctly. (Sized for the 1-core CI box; the envelope itself is
    documented in BASELINE.md.)"""
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(400)]
    out = ray_tpu.get(refs, timeout=600)
    assert out == list(range(400))


def test_scale_smoke_many_actors(shutdown_only):
    """Actor-count envelope smoke: 16 concurrently alive zero-cpu actors
    (sized for the 1-core CI box; the reference envelope is BASELINE.md's)."""
    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    actors = [A.remote(i) for i in range(16)]
    assert ray_tpu.get([a.who.remote() for a in actors], timeout=600) == list(
        range(16)
    )
    for a in actors:
        ray_tpu.kill(a)
