"""Remote debugger: set_trace in a task, session discovery, attach bridge,
post-mortem on failure.

Reference behavior: ray.util.rpdb / `ray debug` — a breakpoint in remote code
advertises a TCP pdb server that the CLI attaches to; post-mortem entry is
env-gated (RAY_DEBUG_POST_MORTEM).
"""

import io
import time

import pytest


@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    yield ray_tpu
    ray_tpu.shutdown()


def _wait_for_session(debug, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        sessions = debug.list_sessions()
        if sessions:
            return sessions
        time.sleep(0.2)
    return {}


def test_set_trace_attach_inspect_continue(cluster):
    ray_tpu = cluster

    @ray_tpu.remote
    def buggy():
        x = 41  # noqa: F841 — inspected through the debugger
        from ray_tpu.util import debug

        debug.set_trace()
        return x + 1

    ref = buggy.remote()
    from ray_tpu.util import debug

    sessions = _wait_for_session(debug)
    assert sessions, "debug session never advertised in GCS KV"
    (sid,) = sessions
    assert sessions[sid]["reason"] == "breakpoint"

    out = io.StringIO()
    assert debug.attach(sid, stdin=io.StringIO("p x\nc\n"), stdout=out)
    assert ray_tpu.get(ref, timeout=60) == 42
    assert "41" in out.getvalue()
    # the session key is cleaned up after the client attaches
    assert _wait_for_nothing(debug)


def _wait_for_nothing(debug, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not debug.list_sessions():
            return True
        time.sleep(0.2)
    return False


def test_post_mortem_env_gated(cluster):
    ray_tpu = cluster

    @ray_tpu.remote(
        runtime_env={
            "env_vars": {
                "RAY_TPU_POSTMORTEM": "1",
                "RAY_TPU_DEBUGGER_TIMEOUT_S": "60",
            }
        }
    )
    def exploder():
        secret = 1234  # noqa: F841
        raise ValueError("boom-for-postmortem")

    ref = exploder.remote()
    from ray_tpu.util import debug

    sessions = _wait_for_session(debug)
    assert sessions, "post-mortem session never advertised"
    (sid,) = sessions
    assert sessions[sid]["reason"] == "post-mortem"

    out = io.StringIO()
    assert debug.attach(sid, stdin=io.StringIO("p secret\nq\n"), stdout=out)
    with pytest.raises(Exception, match="boom-for-postmortem"):
        ray_tpu.get(ref, timeout=60)
    assert "1234" in out.getvalue()


def test_debugger_rejects_wrong_token_without_losing_session(
    shutdown_only_with_token,
):
    """With cluster auth on, the pdb socket requires the token as a first
    line. A wrong-token client is rejected WITHOUT consuming the one-shot
    session — the worker keeps listening, and a legitimate attach (which
    sends the token automatically) still gets the breakpoint."""
    import io
    import socket

    ray_tpu = shutdown_only_with_token

    @ray_tpu.remote
    def guarded():
        x = 55  # noqa: F841
        from ray_tpu.util import debug

        debug.set_trace()
        return "survived"

    ref = guarded.remote()
    from ray_tpu.util import debug

    sessions = _wait_for_session(debug)
    assert sessions
    (sid,) = sessions
    info = sessions[sid]
    conn = socket.create_connection((info["host"], info["port"]), timeout=10)
    conn.sendall(b"wrong-token\n")
    reply = conn.recv(4096)
    conn.close()
    assert b"authentication failed" in reply
    # the session survives the intruder: a real attach still works
    out = io.StringIO()
    assert debug.attach(sid, stdin=io.StringIO("p x\nc\n"), stdout=out)
    assert ray_tpu.get(ref, timeout=60) == "survived"
    assert "55" in out.getvalue()
