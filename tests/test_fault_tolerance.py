"""Fault tolerance: task retries, actor restart, node death
(reference test model: tests/test_actor_failures.py, ResourceKillerActor
patterns in _private/test_utils.py:1372)."""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, GetTimeoutError, TaskError


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_task_retry_on_worker_death(cluster):
    """A task that kills its worker mid-run is retried on a fresh worker
    (reference: max_retries on system failure)."""

    @ray_tpu.remote(max_retries=2)
    def die_once(marker_path):
        if not os.path.exists(marker_path):
            open(marker_path, "w").close()
            os._exit(1)  # simulate worker crash
        return "survived"

    marker = f"/tmp/rtpu_die_once_{os.getpid()}"
    if os.path.exists(marker):
        os.remove(marker)
    try:
        assert ray_tpu.get(die_once.remote(marker), timeout=180) == "survived"
    finally:
        if os.path.exists(marker):
            os.remove(marker)


def test_task_no_retry_exhausted(cluster):
    from ray_tpu.exceptions import WorkerCrashedError

    @ray_tpu.remote(max_retries=0)
    def always_die():
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(always_die.remote(), timeout=180)


def test_actor_restart(cluster):
    @ray_tpu.remote(max_restarts=1, max_task_retries=1)
    class Phoenix:
        def __init__(self):
            self.count = 0

        def pid(self):
            return os.getpid()

        def inc(self):
            self.count += 1
            return self.count

    p = Phoenix.remote()
    pid1 = ray_tpu.get(p.pid.remote(), timeout=120)
    assert ray_tpu.get(p.inc.remote(), timeout=120) == 1
    os.kill(pid1, signal.SIGKILL)
    # restarted actor: fresh state, new pid; retried call succeeds
    deadline = time.time() + 120
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(p.pid.remote(), timeout=30)
            break
        except (ActorDiedError, GetTimeoutError):
            time.sleep(0.5)
    assert pid2 is not None and pid2 != pid1
    assert ray_tpu.get(p.inc.remote(), timeout=120) == 1  # state reset


def test_actor_max_restarts_exhausted(cluster):
    @ray_tpu.remote(max_restarts=0)
    class Mortal:
        def pid(self):
            return os.getpid()

    m = Mortal.remote()
    pid = ray_tpu.get(m.pid.remote(), timeout=120)
    os.kill(pid, signal.SIGKILL)
    with pytest.raises(ActorDiedError):
        deadline = time.time() + 120
        while time.time() < deadline:
            ray_tpu.get(m.pid.remote(), timeout=60)
            time.sleep(0.2)


def test_node_death_detection():
    """Killing a non-head node flips it dead in the GCS and restartable
    actors migrate (reference: NodeKiller chaos tests)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    # the knob must go to Cluster(), not connect() — the GCS reads its
    # config when the head node is created, before the driver attaches
    cluster = Cluster(
        head_node_args=dict(num_cpus=2),
        _system_config={"health_check_timeout_s": 3.0},
    )
    extra = cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        extra_id = extra.node_id.hex()

        @ray_tpu.remote(max_restarts=1, max_task_retries=1)
        class Pinned:
            def where(self):
                return os.environ.get("RAY_TPU_NODE_ID")

        a = Pinned.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=extra_id, soft=True
            )
        ).remote()
        assert ray_tpu.get(a.where.remote(), timeout=120) == extra_id

        cluster.remove_node(extra, graceful=False)
        deadline = time.time() + 60
        while time.time() < deadline:
            alive = {n["NodeID"] for n in ray_tpu.nodes() if n["Alive"]}
            if extra_id not in alive:
                break
            time.sleep(0.5)
        assert extra_id not in {n["NodeID"] for n in ray_tpu.nodes() if n["Alive"]}

        # soft affinity is not implemented for restart; actor restarts on the
        # surviving node because the strategy node is gone -> scheduler falls
        # back to any feasible node
        deadline = time.time() + 120
        new_home = None
        while time.time() < deadline:
            try:
                new_home = ray_tpu.get(a.where.remote(), timeout=30)
                break
            except (ActorDiedError, GetTimeoutError):
                time.sleep(0.5)
        assert new_home is not None and new_home != extra_id
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_gcs_group_epoch_sweep_reclaims_leaked_keys(shutdown_only):
    """A collective epoch that dies without destroy() leaks its rendezvous
    and membership keys in the GCS KV; rank 0 of the next epoch sweeps every
    dead epoch's keys at init (the elastic re-form path)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.collective.cpu_group import GcsStoreGroup, _kv_call

    ray_tpu.init(num_cpus=2)
    g0 = GcsStoreGroup(1, 0, "sweep", epoch=0)
    for _ in range(3):
        g0.allreduce(np.ones(2))
    # simulate a crash: no destroy() — the lagged-cleanup scheme leaves the
    # last ops' keys and the membership record behind
    assert _kv_call("kv_keys", "col:sweep:0:")
    assert _kv_call("kv_get", "colmember:sweep:0:0") is not None

    g1 = GcsStoreGroup(1, 0, "sweep", epoch=1)
    assert not _kv_call("kv_keys", "col:sweep:0:")
    assert not _kv_call("kv_keys", "colmember:sweep:0:")
    # the new epoch still works and registered itself
    out = g1.allreduce(np.ones(2))
    assert float(out[0]) == 1.0
    assert _kv_call("kv_get", "colmember:sweep:1:0") is not None
    g1.destroy()
    assert _kv_call("kv_get", "colmember:sweep:1:0") is None


def test_abort_epoch_is_scoped_to_older_epochs(shutdown_only):
    """colabort applies to epochs <= the written mark: a re-formed gang at a
    higher epoch is not poisoned by the old abort."""
    import numpy as np

    import ray_tpu
    from ray_tpu.collective.cpu_group import (
        GcsStoreGroup,
        read_abort_epoch,
        write_abort,
    )
    from ray_tpu.exceptions import CollectiveAbortedError

    ray_tpu.init(num_cpus=2)
    g0 = GcsStoreGroup(1, 0, "scoped", epoch=0)
    write_abort("scoped", 0, reason="test kill")
    assert read_abort_epoch("scoped") == 0
    with pytest.raises(CollectiveAbortedError):
        g0.allreduce(np.ones(2))
    # the next epoch ignores the stale abort mark — no key deletion needed
    g1 = GcsStoreGroup(1, 0, "scoped", epoch=1)
    out = g1.allreduce(np.ones(2))
    assert float(out[0]) == 1.0
    g1.destroy()
