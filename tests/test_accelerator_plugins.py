"""Accelerator plugin ABC + heterogeneous clusters (reference:
_private/accelerators/accelerator.py:18 — one interface, many families)."""

import pytest

import ray_tpu
from ray_tpu._internal import accelerators as acc


def test_registry_contains_tpu_and_gpu():
    names = {m.get_resource_name() for m in acc.all_accelerator_managers()}
    assert {"TPU", "GPU"} <= names


def test_detection_folds_registered_plugins(monkeypatch):
    """A registered plugin's count/labels/extra resources land in the node
    detection result; zero-count plugins contribute nothing."""

    class FakeNpu(acc.AcceleratorManager):
        @staticmethod
        def get_resource_name():
            return "NPU"

        @staticmethod
        def get_current_node_num_accelerators():
            return 3

        @staticmethod
        def get_current_node_labels():
            return {"ray.io/npu-flavor": "test"}

        @staticmethod
        def get_current_node_additional_resources():
            return {"NPU-head": 1.0}

    acc.register_accelerator_manager(FakeNpu)
    try:
        monkeypatch.setattr(
            acc.TpuAcceleratorManager, "detect_num_chips", staticmethod(lambda: 0)
        )
        monkeypatch.setattr(
            acc.GpuAcceleratorManager,
            "get_current_node_num_accelerators",
            staticmethod(lambda: 0),
        )
        resources, labels = acc.detect_node_accelerators()
        assert resources == {"NPU": 3.0, "NPU-head": 1.0}
        assert labels == {"ray.io/npu-flavor": "test"}
    finally:
        acc._ACCELERATOR_MANAGERS.remove(FakeNpu)


def test_gpu_plugin_visibility_env_and_cuda_devices(monkeypatch):
    monkeypatch.setenv("CUDA_VISIBLE_DEVICES", "0,1,2")
    assert acc.GpuAcceleratorManager.get_current_node_num_accelerators() == 3
    env = acc.GpuAcceleratorManager.get_visibility_env([1, 2])
    assert env == {"CUDA_VISIBLE_DEVICES": "1,2"}
    monkeypatch.setenv("CUDA_VISIBLE_DEVICES", "")
    assert acc.GpuAcceleratorManager.get_current_node_num_accelerators() == 0


def test_tpu_plugin_labels_and_head_resource(monkeypatch):
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
    monkeypatch.setenv("TPU_NAME", "my-slice")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-8")
    resources = acc.TpuAcceleratorManager.get_current_node_additional_resources()
    assert resources == {"TPU-v4-8-head": 1.0}
    labels = acc.TpuAcceleratorManager.get_current_node_labels()
    assert labels[acc.TPU_SLICE_NAME_LABEL] == "my-slice"
    assert acc.TpuAcceleratorManager.get_current_node_num_accelerators() == 4
    # worker 1 carries no head resource
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    assert acc.TpuAcceleratorManager.get_current_node_additional_resources() == {}


def test_heterogeneous_cpu_rollout_tpu_learner_cluster():
    """The framework's own RL story: CPU-only rollout nodes next to a TPU
    learner node in ONE cluster, each actor landing on the right node kind
    with correct per-node resources."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 1, "resources": {"CPU": 1.0}},
    )
    cluster.add_node(resources={"CPU": 2.0})  # rollout node A
    cluster.add_node(resources={"CPU": 2.0})  # rollout node B
    cluster.add_node(  # TPU learner node
        resources={"CPU": 1.0, "TPU": 4.0},
        labels={"ray.io/tpu-pod-type": "v5e-4"},
    )
    cluster.connect()
    try:
        nodes = ray_tpu.nodes()
        tpu_nodes = [n for n in nodes if n["Resources"].get("TPU")]
        cpu_only = [
            n for n in nodes
            if not n["Resources"].get("TPU") and not n["IsHead"]
        ]
        assert len(tpu_nodes) == 1 and len(cpu_only) == 2
        assert tpu_nodes[0]["Labels"]["ray.io/tpu-pod-type"] == "v5e-4"

        @ray_tpu.remote(num_cpus=2)
        class Rollout:
            def where(self):
                import ray_tpu as rt

                return rt.get_runtime_context().get_node_id()

        @ray_tpu.remote(num_tpus=4)
        class Learner:
            def where(self):
                import ray_tpu as rt

                return rt.get_runtime_context().get_node_id()

        rollouts = [Rollout.remote() for _ in range(2)]
        learner = Learner.remote()
        rollout_nodes = set(
            ray_tpu.get([r.where.remote() for r in rollouts], timeout=120)
        )
        learner_node = ray_tpu.get(learner.where.remote(), timeout=120)
        # the learner landed on THE TPU node; rollouts on the CPU nodes
        assert learner_node == tpu_nodes[0]["NodeID"]
        assert learner_node not in rollout_nodes
        assert len(rollout_nodes) == 2  # one per 2-CPU node
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_explicit_tpu_opt_out_suppresses_plugin_extras(monkeypatch):
    """num_tpus=0 on a TPU VM: the node must not leak the slice-head
    resource or slice labels (reserve_tpu_slice would otherwise pick a
    chipless head)."""
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
    monkeypatch.setenv("TPU_NAME", "optout-slice")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-8")
    resources, labels = acc.detect_node_accelerators(exclude={"TPU"})
    assert "TPU" not in resources
    assert not any(k.endswith("-head") for k in resources)
    assert acc.TPU_SLICE_NAME_LABEL not in labels


def test_gpu_visibility_remaps_through_parent_mask(monkeypatch):
    """Logical ids must map through an existing CUDA_VISIBLE_DEVICES mask."""
    monkeypatch.setenv("CUDA_VISIBLE_DEVICES", "2,3")
    env = acc.GpuAcceleratorManager.get_visibility_env([0, 1])
    assert env == {"CUDA_VISIBLE_DEVICES": "2,3"}
    env = acc.GpuAcceleratorManager.get_visibility_env([1])
    assert env == {"CUDA_VISIBLE_DEVICES": "3"}
    monkeypatch.delenv("CUDA_VISIBLE_DEVICES")
    assert acc.GpuAcceleratorManager.get_visibility_env([0, 1]) == {
        "CUDA_VISIBLE_DEVICES": "0,1"
    }


def test_throwing_plugin_is_fault_isolated(monkeypatch):
    class Broken(acc.AcceleratorManager):
        @staticmethod
        def get_resource_name():
            return "BROKEN"

        @staticmethod
        def get_current_node_num_accelerators():
            return 1

        @staticmethod
        def get_current_node_labels():
            raise RuntimeError("metadata server down")

    acc.register_accelerator_manager(Broken)
    try:
        monkeypatch.setattr(
            acc.TpuAcceleratorManager, "detect_num_chips", staticmethod(lambda: 0)
        )
        monkeypatch.setattr(
            acc.GpuAcceleratorManager,
            "get_current_node_num_accelerators",
            staticmethod(lambda: 0),
        )
        resources, labels = acc.detect_node_accelerators()
        assert "BROKEN" not in resources  # partial contribution rolled back
        assert labels == {}
    finally:
        acc._ACCELERATOR_MANAGERS.remove(Broken)
