"""North-star Train example: Llama LoRA fine-tune via JaxTrainer
(reference: BASELINE.json configs[2] — Llama-2-7B LoRA via JaxTrainer;
tiny-scale here, the 7b flag is the flagship config)."""

import os
import pickle

import pytest


@pytest.mark.slow
def test_llama_lora_jaxtrainer_end_to_end(cluster):
    from ray_tpu.train.examples.llama_lora import make_trainer

    result = make_trainer(
        num_workers=1,
        train_config={
            "model": "tiny", "epochs": 2, "steps_per_epoch": 3,
            "batch_per_worker": 2, "seq": 64,
        },
    ).fit()
    assert result.error is None
    assert result.metrics["epoch"] == 1
    losses = [m["loss"] for m in result.metrics_history]
    assert len(losses) == 2 and all(l == l for l in losses)  # finite

    # the LoRA-only checkpoint landed and round-trips
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        with open(os.path.join(d, "lora.pkl"), "rb") as f:
            saved = pickle.load(f)
    assert saved["epoch"] == 1
    assert any(k[-1] in ("lora_a", "lora_b") for k in saved["lora"])
