"""ray_dask_get: dask graph-protocol scheduler over ray_tpu tasks.

Reference behavior: ray.util.dask.ray_dask_get — executes a dask graph dict
as distributed tasks; works on plain graphs without dask installed.
"""

from operator import add

import pytest


@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    yield ray_tpu
    ray_tpu.shutdown()


def test_graph_with_deps_and_nested_keys(cluster):
    from ray_tpu.util.dask import ray_dask_get

    def total(xs):
        return sum(xs)

    dsk = {
        "a": 1,
        "b": (add, "a", 2),        # 3
        "c": (add, "b", "b"),      # 6
        "d": (total, ["a", "b", "c"]),  # 10
        "alias": "d",
    }
    assert ray_dask_get(dsk, "d") == 10
    assert ray_dask_get(dsk, ["a", ["b", "c"], "alias"]) == [1, [3, 6], 10]


def test_cycle_detection(cluster):
    from ray_tpu.util.dask import ray_dask_get

    dsk = {"x": (add, "y", 1), "y": (add, "x", 1)}
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get(dsk, "x")


def test_literals_pass_through(cluster):
    from ray_tpu.util.dask import ray_dask_get

    def cat(a, b):
        return f"{a}{b}"

    dsk = {"s": (cat, "not-a-key", "a"), "a": "!"}
    assert ray_dask_get(dsk, "s") == "not-a-key!"


def test_deep_linear_chain_and_literal_fast_path(cluster):
    """Iterative toposort handles chains past the recursion limit; literal
    and alias entries resolve without scheduler round-trips."""
    import sys
    from operator import add

    from ray_tpu.util.dask import _toposort, ray_dask_get

    n = max(2000, sys.getrecursionlimit() + 500)
    dsk = {"k0": 0}
    for i in range(1, n):
        dsk[f"k{i}"] = (add, f"k{i-1}", 1)
    dsk["alias"] = f"k{n-1}"
    # the structural property under test: a chain deeper than the
    # interpreter recursion limit must order without RecursionError
    order = _toposort(dsk)
    assert order.index("k0") < order.index(f"k{n-1}") < order.index("alias")

    # literals/aliases short-circuit (no task per no-op entry) and a short
    # chain computes end-to-end
    assert ray_dask_get(
        {"lit": 41, "out": (add, "lit", 1), "a2": "lit"},
        ["out", "a2", "lit"],
    ) == [42, 41, 41]
