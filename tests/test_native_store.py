"""Tests for the C++ native object store (reference model:
src/ray/object_manager/plasma/ store tests)."""

import ctypes
import os

import numpy as np
import pytest

from ray_tpu._native.lib import load
from ray_tpu._internal.ids import ObjectID


@pytest.fixture(scope="module")
def lib():
    lib = load()
    assert lib is not None, "native store must build in this environment"
    return lib


@pytest.fixture
def arena(lib):
    path = f"/dev/shm/rt_test_{os.getpid()}"
    h = lib.rt_store_open(path.encode(), 1 << 20)
    assert h >= 0
    yield lib, h, path
    lib.rt_store_close(h)
    assert not os.path.exists(path)


def _get(lib, h, key):
    off = ctypes.c_uint64()
    size = ctypes.c_uint64()
    rc = lib.rt_get(h, key, ctypes.byref(off), ctypes.byref(size))
    return rc, off.value, size.value


def test_create_seal_get_release_free(arena):
    lib, h, _ = arena
    off = lib.rt_create(h, b"a", 100)
    assert off >= 0
    rc, _, _ = _get(lib, h, b"a")
    assert rc == -2  # unsealed
    assert lib.rt_seal(h, b"a") == 0
    rc, o, s = _get(lib, h, b"a")
    assert rc == 0 and o == off and s >= 100
    lib.rt_release(h, b"a")
    assert lib.rt_contains(h, b"a") == 1
    assert lib.rt_free(h, b"a") == 0
    assert lib.rt_contains(h, b"a") == 0
    assert lib.rt_used(h) == 0


def test_duplicate_create_rejected(arena):
    lib, h, _ = arena
    assert lib.rt_create(h, b"dup", 10) >= 0
    assert lib.rt_create(h, b"dup", 10) == -2


def test_free_list_coalescing(arena):
    """free a+b adjacent blocks, then a block of a+b size must fit."""
    lib, h, _ = arena
    cap = 1 << 20
    a = lib.rt_create(h, b"a", cap // 2 - 64)
    b = lib.rt_create(h, b"b", cap // 2 - 64)
    assert a >= 0 and b >= 0
    # no room for anything big now
    assert lib.rt_create(h, b"c", cap // 2) == -1
    lib.rt_free(h, b"a")
    lib.rt_free(h, b"b")
    # coalesced: nearly the whole arena is one block again
    assert lib.rt_create(h, b"c", cap - 128) >= 0


def test_lru_eviction_and_pin_protection(arena):
    lib, h, _ = arena
    for i in range(8):
        key = f"o{i}".encode()
        assert lib.rt_create(h, key, 100 * 1024) >= 0
        lib.rt_seal(h, key)
    # touch o0 so o1 becomes LRU
    _get(lib, h, b"o0")
    lib.rt_release(h, b"o0")
    # pin o1 — it must survive even as LRU
    _get(lib, h, b"o1")
    big = lib.rt_create(h, b"big", 300 * 1024)
    assert big >= 0
    assert lib.rt_contains(h, b"o1") == 1  # pinned survived
    assert lib.rt_contains(h, b"o0") == 1  # recently used survived


def test_primary_pin_never_evicted(arena):
    lib, h, _ = arena
    assert lib.rt_create(h, b"prim", 100 * 1024) >= 0
    lib.rt_seal(h, b"prim")
    lib.rt_pin_primary(h, b"prim")
    for i in range(12):
        key = f"f{i}".encode()
        r = lib.rt_create(h, key, 90 * 1024)
        if r >= 0:
            lib.rt_seal(h, key)
    assert lib.rt_contains(h, b"prim") == 1


def test_oversized_allocation_fails_cleanly(arena):
    lib, h, _ = arena
    assert lib.rt_create(h, b"toobig", (1 << 20) + 1) == -1


def test_native_wrapper_and_cross_view():
    """NativeObjectStore + StoreClient see the same bytes via the arena."""
    from ray_tpu._native.lib import load as _load
    from ray_tpu.runtime.object_store.native_store import NativeObjectStore
    from ray_tpu.runtime.object_store.store import StoreClient

    lib = _load()
    store = NativeObjectStore(1 << 20, f"t{os.getpid()}", lib)
    try:
        oid = ObjectID.from_random()
        payload = np.arange(1000, dtype=np.int64).tobytes()
        ref = store.create_and_write(oid, payload)
        assert ref.startswith("arena:")
        assert store.contains(oid)
        client = StoreClient()
        view = client.read(ref, len(payload))
        assert bytes(view) == payload
        # write through the raylet-side view (transfer path)
        oid2 = ObjectID.from_random()
        store.create(oid2, 8)
        store.write_view(oid2)[:] = b"abcdefgh"
        store.seal(oid2)
        assert bytes(store.read_local(oid2)) == b"abcdefgh"
        client.close()
    finally:
        store.shutdown()


def test_cluster_uses_native_store():
    import ray_tpu

    ray_tpu.init(num_cpus=2, resources={"TPU": 1})
    try:
        node = ray_tpu._worker_api.get_node()
        stats = node.raylet.store.stats()
        assert stats.get("native") is True, stats

        # large object round-trip through the arena (> inline threshold)
        arr = np.random.default_rng(0).normal(size=(512, 512))
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref)
        np.testing.assert_array_equal(arr, out)

        @ray_tpu.remote
        def bounce(x):
            return x.sum()

        assert abs(ray_tpu.get(bounce.remote(arr)) - arr.sum()) < 1e-9
    finally:
        ray_tpu.shutdown()


def test_spill_and_restore_under_pressure():
    """Live primary copies beyond capacity spill to disk and restore on get
    (reference: LocalObjectManager spill/restore, local_object_manager.h)."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, resources={"TPU": 1}, object_store_memory=8 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def make(i):
            return np.full((256, 1024), i, dtype=np.float64)  # 2 MB each

        refs = [make.remote(i) for i in range(8)]  # 16 MB > 8 MB store
        import time
        time.sleep(1)
        for i, r in enumerate(refs):
            out = ray_tpu.get(r)
            assert (out == i).all()
        node = ray_tpu._worker_api.get_node()
        stats = node.raylet.store.stats()
        assert stats["used"] <= stats["capacity"]
    finally:
        ray_tpu.shutdown()


def test_spilled_objects_held_as_live_views():
    """Holding more zero-copy results than the arena fits: spilled objects
    that cannot be restored into the (pinned-full) arena are served inline
    from the spill file instead of raising ObjectLostError."""
    import ray_tpu

    ray_tpu.init(num_cpus=1, object_store_memory=20_000_000)
    try:
        refs = [
            ray_tpu.put(np.full((1_000_000,), i, dtype=np.float64))  # 8 MB
            for i in range(8)
        ]
        vals = [ray_tpu.get(r, timeout=60) for r in refs]  # all kept alive
        for i, v in enumerate(vals):
            assert v[0] == i and v.shape == (1_000_000,)
    finally:
        ray_tpu.shutdown()


def test_fetch_spilled_object_from_remote_node():
    """A spilled primary copy is still fetchable by a remote node: the
    serving raylet reads chunks from the spill file (advisor finding:
    handle_fetch_object previously returned None for spilled objects)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        head_node_args=dict(num_cpus=1, object_store_memory=12_000_000)
    )
    cluster.add_node(num_cpus=1, object_store_memory=64_000_000)
    cluster.connect()
    try:
        # fill the head store so early puts spill (driver runs on head)
        refs = [
            ray_tpu.put(np.full((500_000,), i, dtype=np.float64))  # 4 MB
            for i in range(6)
        ]

        @ray_tpu.remote(num_cpus=1)
        def first_elem(x):
            return float(x[0])

        # the remote node's worker must pull every ref from the head,
        # including ones that only exist in the head's spill dir
        outs = ray_tpu.get(
            [first_elem.options(resources={"CPU": 1}).remote(r) for r in refs],
            timeout=120,
        )
        assert outs == [float(i) for i in range(6)]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_external_spill_storage_tier():
    """With spill_storage_uri configured, spilled primary copies land on the
    external store (fsspec memory:// here; S3/GCS via the same URI scheme)
    and restore transparently on get (reference: the external storage tier,
    _private/external_storage.py:399)."""
    import fsspec

    import ray_tpu

    uri = "memory://ray_tpu_spill_test"
    ray_tpu.init(
        num_cpus=2, resources={"TPU": 1},
        object_store_memory=8 * 1024 * 1024,
        _system_config={"spill_storage_uri": uri},
    )
    try:
        @ray_tpu.remote
        def make(i):
            return np.full((256, 1024), i, dtype=np.float64)  # 2 MB each

        refs = [make.remote(i) for i in range(8)]  # 16 MB > 8 MB store
        ready, _ = ray_tpu.wait(
            refs, num_returns=len(refs), timeout=120, fetch_local=False
        )
        assert len(ready) == 8
        node = ray_tpu._worker_api.get_node()
        # pressure must have pushed copies to the EXTERNAL tier
        spilled = dict(node.raylet._spilled)
        assert spilled, "nothing spilled under 2x-capacity pressure"
        assert all(ref.startswith("memory://") for ref in spilled.values())
        fs = fsspec.filesystem("memory")
        assert any(fs.ls("/ray_tpu_spill_test")), "no external spill objects"
        # every value restores from the external tier intact
        for i, r in enumerate(refs):
            out = ray_tpu.get(r)
            assert (out == i).all()
    finally:
        ray_tpu.shutdown()
