"""ViT model family: sharded training + parity of attention modes."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models.vit import ViT, ViTConfig, classification_loss, init_params
from ray_tpu.parallel.mesh import make_mesh
from ray_tpu.parallel.sharding import param_shardings, unbox_params


def test_forward_shapes():
    cfg = ViTConfig.tiny()
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    imgs = jnp.zeros((2, 32, 32, 3))
    logits = ViT(cfg).apply({"params": params}, imgs)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


@pytest.mark.slow
def test_sharded_training_learns():
    cfg = ViTConfig.tiny()
    boxed = init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(num_devices=8, fsdp=2, tp=2, dp=2)
    params = jax.jit(lambda p: p, out_shardings=param_shardings(mesh, boxed))(
        unbox_params(boxed)
    )
    tx = optax.adamw(1e-3)
    opt = jax.jit(tx.init)(params)

    @jax.jit
    def step(p, s, images, labels):
        loss, g = jax.value_and_grad(
            lambda p_: classification_loss(cfg, mesh, p_, images, labels)
        )(p)
        u, s2 = tx.update(g, s, p)
        return optax.apply_updates(p, u), s2, loss

    imgs = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    labels = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)
    first = last = None
    for _ in range(6):
        params, opt, loss = step(params, opt, imgs, labels)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first


def test_remat_matches_no_remat():
    imgs = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    outs = []
    for remat in (False, True):
        cfg = ViTConfig.tiny(remat=remat)
        params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
        outs.append(np.asarray(ViT(cfg).apply({"params": params}, imgs)))
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # 9s: remat parity stays tier-1 via test_remat_matches_no_remat
def test_remat_with_dropout_trains():
    """remat + dropout: deterministic must be static under nn.remat."""
    cfg = ViTConfig.tiny(remat=True, dropout=0.1)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    imgs = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))

    @jax.jit
    def loss_fn(p, key):
        logits = ViT(cfg).apply(
            {"params": p}, imgs, deterministic=False,
            rngs={"dropout": key},
        )
        return jnp.mean(logits**2)

    loss, grads = jax.value_and_grad(loss_fn)(params, jax.random.PRNGKey(2))
    assert jnp.isfinite(loss)
    assert all(jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads))
