"""Test configuration.

Multi-chip behavior is tested on a virtual 8-device CPU mesh (the driver
separately dry-runs the multichip path); env must be set before jax imports.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    """Single-node cluster, torn down after the test (reference:
    tests/conftest.py ray_start_regular)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    import ray_tpu

    yield
    ray_tpu.shutdown()
