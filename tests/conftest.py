"""Test configuration.

Multi-chip behavior is tested on a virtual 8-device CPU mesh (the driver
separately dry-runs the multichip path on real topologies). The axon TPU
plugin registers itself in sitecustomize at interpreter startup and ignores
the JAX_PLATFORMS env var, but jax.config.update("jax_platforms") still wins
if applied before backend initialization — so it must run here, before any
test imports jax.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
# worker subprocesses spawned by the runtime during tests pick this up
# (worker_main applies it at startup)
os.environ["RAY_TPU_JAX_PLATFORM"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running coverage excluded from the budgeted tier-1 lane "
        "(-m 'not slow'); run explicitly or without the marker filter",
    )


@pytest.fixture
def ray_start_regular():
    """Single-node cluster, torn down after the test (reference:
    tests/conftest.py ray_start_regular)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    import ray_tpu

    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only_with_token():
    """Cluster with RPC auth on; clears the process-global token after."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, _system_config={"cluster_auth_token": "tok-dbg"})
    yield ray_tpu
    ray_tpu.shutdown()
    from ray_tpu._internal.rpc import set_auth_token

    set_auth_token(None)


@pytest.fixture
def cluster():
    """Default 2-CPU local cluster; yields the ray_tpu module."""
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    yield ray_tpu
    ray_tpu.shutdown()
