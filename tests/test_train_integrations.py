"""Framework-trainer integrations: transformers bridge (real run) and
import-gated Lightning/TF/XGBoost constructors.

Reference behavior: ray.train.huggingface.transformers.prepare_trainer +
RayTrainReportCallback forward HF Trainer logs/checkpoints into the Train
session; LightningTrainer/TensorflowTrainer/XGBoostTrainer exist as entry
points (their runtimes aren't in this image, so they gate at construction).
"""

import pytest


def _hf_train_loop(config):
    import tempfile

    import torch
    import transformers

    from ray_tpu import train as rt_train

    class TinyRegressor(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.linear = torch.nn.Linear(4, 1)

        def forward(self, x=None, labels=None):
            pred = self.linear(x).squeeze(-1)
            loss = torch.nn.functional.mse_loss(pred, labels)
            return {"loss": loss, "logits": pred}

    class Data(torch.utils.data.Dataset):
        def __len__(self):
            return 32

        def __getitem__(self, i):
            g = torch.Generator().manual_seed(i)
            x = torch.randn(4, generator=g)
            return {"x": x, "labels": x.sum()}

    args = transformers.TrainingArguments(
        output_dir=tempfile.mkdtemp(prefix="hf_out_"),
        per_device_train_batch_size=8,
        num_train_epochs=2,
        logging_steps=2,
        save_steps=4,
        report_to=[],
        use_cpu=True,
    )
    trainer = transformers.Trainer(
        model=TinyRegressor(), args=args, train_dataset=Data()
    )
    trainer = rt_train.huggingface.prepare_trainer(trainer)
    # idempotent: preparing twice must not double the callback
    trainer = rt_train.huggingface.prepare_trainer(trainer)
    n_bridges = sum(
        isinstance(cb, rt_train.huggingface.RayTrainReportCallback)
        for cb in trainer.callback_handler.callbacks
    )
    assert n_bridges == 1
    trainer.train()


@pytest.mark.slow  # 14s: full HF-shim session; the gating test stays tier-1
def test_transformers_trainer_reports_through_session(cluster):
    from ray_tpu import train as rt_train

    result = rt_train.TorchTrainer(
        _hf_train_loop,
        scaling_config=rt_train.ScalingConfig(num_workers=1),
        run_config=rt_train.RunConfig(name="hf"),
    ).fit()
    assert result.error is None, result.error
    # HF logging flowed into Train metrics
    assert any("loss" in m for m in result.metrics_history)
    # and an HF checkpoint directory was registered
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        import os

        assert any(
            name.startswith(("model", "training_args"))
            for name in os.listdir(d)
        )


def test_unavailable_framework_trainers_gate_cleanly():
    from ray_tpu import train as rt_train

    for trainer_cls, lib in [
        (rt_train.LightningTrainer, "lightning"),
        (rt_train.XGBoostTrainer, "xgboost"),
        (rt_train.LightGBMTrainer, "lightgbm"),
    ]:
        with pytest.raises(ImportError, match=lib):
            trainer_cls(lambda config: None)


def _tf_train_loop(config):
    import json
    import os

    import numpy as np
    import tensorflow as tf

    from ray_tpu import train as rt_train

    tf_config = json.loads(os.environ["TF_CONFIG"])
    strategy = tf.distribute.MultiWorkerMirroredStrategy()
    # keras-3 fit() no longer supports MWMS; a custom strategy.run step is
    # the supported route and proves the collective ring for real (variable
    # updates aggregate across the 2 worker processes)
    with strategy.scope():
        w = tf.Variable(
            tf.zeros([4, 1]),
            aggregation=tf.VariableAggregation.MEAN,
        )

    x = np.random.RandomState(0).randn(32, 4).astype("float32")
    y = x.sum(axis=1, keepdims=True)
    ds = tf.data.Dataset.from_tensor_slices((x, y)).batch(8)
    dist_ds = strategy.experimental_distribute_dataset(ds)

    @tf.function
    def train_step(batch):
        bx, by = batch

        def step(sx, sy):
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean((tf.matmul(sx, w) - sy) ** 2)
            g = tape.gradient(loss, w)
            w.assign_sub(0.05 * g)
            return loss

        per_replica = strategy.run(step, args=(bx, by))
        return strategy.reduce(
            tf.distribute.ReduceOp.MEAN, per_replica, axis=None
        )

    losses = [float(train_step(b)) for b in dist_ds]
    rt_train.report(
        {
            "replicas_in_sync": int(strategy.num_replicas_in_sync),
            "cluster_size": len(tf_config["cluster"]["worker"]),
            "task_index": tf_config["task"]["index"],
            "loss": losses[-1],
            "improved": losses[-1] < losses[0],
        }
    )


@pytest.mark.slow
def test_tensorflow_trainer_multiworker_cluster(cluster):
    """TensorflowTrainer: the TF_CONFIG backend must form a real 2-worker
    MultiWorkerMirroredStrategy ring (reference: TensorflowConfig)."""
    from ray_tpu import train as rt_train

    result = rt_train.TensorflowTrainer(
        _tf_train_loop,
        scaling_config=rt_train.ScalingConfig(num_workers=2),
        run_config=rt_train.RunConfig(name="tf"),
    ).fit()
    assert result.error is None, result.error
    by_rank = {m["task_index"]: m for m in result.metrics_history}
    assert set(by_rank) == {0, 1}
    for m in by_rank.values():
        assert m["cluster_size"] == 2
        assert m["replicas_in_sync"] == 2
        assert m["loss"] == m["loss"]
