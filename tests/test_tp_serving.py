"""Tensor-parallel serving plane (PR 13): partition-plan validation,
tp=2 paged-vs-dense temperature-0 parity (cold + shared-prefix warm),
sharded KV pool accounting, mesh-tagged spans, and the weight plane's
pull-each-shard-once guarantee.

Runs entirely on host devices — conftest forces
``--xla_force_host_platform_device_count=8`` so a 2-way mesh exists on
any CPU box."""

import pickle

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu import exceptions
from ray_tpu.kvcache import KVCacheManager
from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import (
    ContinuousBatchingEngine,
    GenerationRequest,
    LLMEngine,
)
from ray_tpu.models.llama import LlamaConfig, init_params
from ray_tpu.parallel.plan import (
    DEFAULT_LLM_RULES,
    PartitionPlan,
    match_partition_rules,
    validate_mesh_for_model,
)
from ray_tpu.parallel.sharding import unbox_params
from ray_tpu.util import tracing

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >=2 (host) devices"
)


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = LlamaConfig.tiny(max_seq_len=128)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    return cfg, params


@pytest.fixture(scope="module")
def tp2(tiny_setup):
    """ONE shared tp=2 paged engine: jit compiles dominate this file's
    wall-clock, so the parity/accounting/span tests reuse the same sharded
    programs (tests that need fresh KV state measure stats() deltas)."""
    cfg, params = tiny_setup
    plan = PartitionPlan.for_model(cfg, 2)
    kv = KVCacheManager(num_blocks=32, block_size=16, plan=plan)
    eng = ContinuousBatchingEngine(
        cfg, params, plan.mesh, num_slots=4, kv_cache=kv, seed=7, plan=plan,
    )
    return eng, kv, plan


# -- partition plan ----------------------------------------------------------


def test_partition_rules_cover_llama_params(tiny_setup):
    cfg, params = tiny_setup
    plan = PartitionPlan.for_model(cfg, 2)
    specs = match_partition_rules(DEFAULT_LLM_RULES, params)
    leaves = jax.tree_util.tree_leaves_with_path(params)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    )
    assert len(spec_leaves) == len(leaves)
    # at least the attention/MLP kernels actually shard (not all-replicated)
    assert any("tp" in tuple(s) for s in spec_leaves)
    # every matched spec maps onto the mesh: shard_params must not raise
    sharded = plan.shard_params(params)
    flat = jax.tree_util.tree_leaves(sharded)
    assert all(isinstance(leaf, jax.Array) for leaf in flat)


def test_mesh_validation_typed_errors():
    with pytest.raises(exceptions.MeshValidationError):
        validate_mesh_for_model(3, 8)  # tp does not divide devices
    with pytest.raises(exceptions.MeshValidationError):
        validate_mesh_for_model(0, 8)  # non-positive tp
    with pytest.raises(exceptions.MeshValidationError):
        # tp divides devices but not the head counts
        validate_mesh_for_model(8, 8, n_heads=4, n_kv_heads=4)
    cfg = LlamaConfig.tiny()
    with pytest.raises(exceptions.MeshValidationError) as ei:
        PartitionPlan.for_model(cfg, 3)
    # typed + picklable: serve deployment errors cross process boundaries
    err = ei.value
    clone = pickle.loads(pickle.dumps(err))
    assert isinstance(clone, exceptions.MeshValidationError)
    assert isinstance(clone, ValueError)
    assert str(clone) == str(err)


def test_llmconfig_mesh_field_wins_and_validates():
    lc = LLMConfig(model_id="m", mesh={"tp": 4})
    assert lc.effective_parallelism() == (4, 1)
    lc2 = LLMConfig(model_id="m", tensor_parallel_size=2)
    assert lc2.effective_parallelism() == (2, 1)
    # mesh dict wins over the scalar fields
    lc3 = LLMConfig(model_id="m", tensor_parallel_size=2, mesh={"tp": 8})
    assert lc3.effective_parallelism() == (8, 1)
    with pytest.raises(exceptions.MeshValidationError):
        LLMConfig(model_id="m", mesh={"pp": 2})  # unknown axis
    with pytest.raises(exceptions.MeshValidationError):
        LLMConfig(model_id="m", mesh={"tp": 0})  # non-positive size


# -- parity ------------------------------------------------------------------


def test_tp2_paged_matches_dense_temperature0(tiny_setup, tp2):
    """The acceptance bar: a tp=2 sharded paged replica is token-identical
    to the dense single-device engine at temperature 0, for cold prompts
    AND a warm request that rides the shared-prefix cache."""
    cfg, params = tiny_setup
    dense = LLMEngine(cfg, params, max_batch_size=4, seed=7)
    paged, kv, _ = tp2

    rng = np.random.RandomState(0)
    prompts = [list(map(int, rng.randint(0, 256, size=n)))
               for n in (17, 33, 21)]
    d = dense.generate([GenerationRequest(list(p), max_new_tokens=8)
                        for p in prompts])
    p = paged.generate([GenerationRequest(list(p), max_new_tokens=8)
                        for p in prompts])
    for i, (a, b) in enumerate(zip(d, p)):
        assert a.token_ids == b.token_ids, (i, a.token_ids, b.token_ids)

    # warm request: first 32 tokens (2 blocks) shared with prompts[1]
    warm = prompts[1][:32] + list(map(int, rng.randint(0, 256, size=5)))
    s0 = kv.stats()
    wd = dense.generate([GenerationRequest(list(warm), max_new_tokens=8)])[0]
    wp = paged.generate([GenerationRequest(list(warm), max_new_tokens=8)])[0]
    s1 = kv.stats()
    assert wd.token_ids == wp.token_ids
    # the warm request really hit the cache: 32 cached, 5 computed
    assert s1["prefix_hit_tokens"] - s0["prefix_hit_tokens"] == 32
    assert (s1["prefill_tokens_computed"]
            - s0["prefill_tokens_computed"]) == len(warm) - 32


# -- sharded KV pools --------------------------------------------------------


def test_kv_pools_sharded_with_per_device_accounting(tiny_setup, tp2):
    cfg, params = tiny_setup
    paged, kv, plan = tp2
    # force pool creation + a resident sequence
    paged.generate([GenerationRequest(list(range(40)), max_new_tokens=2)])

    pool = kv._pools[0]
    # head axis (axis 1) is split across the mesh: each device holds half
    # the kv heads for every block
    shard_shapes = {tuple(s.data.shape) for s in pool.addressable_shards}
    assert shard_shapes == {(32, cfg.n_kv_heads // 2, 16, cfg.head_dim)}

    stats = kv.stats()
    assert stats["mesh"] == "tp=2"
    assert stats["num_devices"] == 2
    assert stats["heads_per_device"] == cfg.n_kv_heads // 2
    assert stats["kv_pool_bytes_total"] == sum(p.nbytes for p in kv._pools)
    assert (stats["kv_pool_bytes_per_device"] * 2
            == stats["kv_pool_bytes_total"])

    acct = kv.pool_accounting()
    assert acct["kv_pool_bytes_per_device"] == stats["kv_pool_bytes_per_device"]


def test_unsharded_manager_accounting_still_works():
    kv = KVCacheManager(num_blocks=4, block_size=8)
    acct = kv.pool_accounting()
    assert acct == {
        "kv_pool_bytes_total": 0,
        "kv_pool_bytes_per_device": 0,
        "heads_per_device": 0,
    }
    assert kv.stats()["mesh"] == "tp=1"


# -- observability -----------------------------------------------------------


def test_engine_spans_carry_mesh_tag(tp2, monkeypatch):
    monkeypatch.setattr(tracing, "flush_spans", lambda: None)
    paged, _, _ = tp2

    tracing.enable_tracing()
    tracing.clear_spans()
    try:
        ctx = tracing.new_trace_context()
        with tracing.request_span("test.request", ctx):
            paged.generate([GenerationRequest(list(range(3, 40)),
                                              max_new_tokens=2,
                                              temperature=0.0)])
        spans = [s for s in tracing.get_spans()
                 if s["trace_id"] == ctx["trace_id"]]
        tagged = [s for s in spans
                  if s["name"] in ("engine.prefill", "engine.decode")]
        assert tagged, "no engine spans recorded"
        assert all(s["args"]["mesh"] == "tp=2" for s in tagged)
    finally:
        tracing._enabled = False
        tracing.clear_spans()


# -- weight plane: each shard's bytes pulled once ----------------------------


def test_weight_chunks_pulled_once_into_sharded_layout(cluster):
    """A subscriber resolving a manifest into a sharded layout pulls every
    chunk exactly once (counter-asserted) — no second fetch, no replicated
    staging pull — and the pinned tree is served from cache afterwards."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.util.state import list_weights
    from ray_tpu.weights import WeightPublisher, WeightSubscriber

    mesh = make_mesh(2, tp=2, fsdp=1)

    def shardings(tree):
        return jax.tree_util.tree_map(
            lambda x: NamedSharding(
                mesh, P("tp") if x.ndim == 1 and x.shape[0] % 2 == 0 else P()
            ),
            tree,
        )

    pub = WeightPublisher("t/tp-shards", chunk_size=128 * 1024)
    params = {f"layer{i}": np.full(50_000, i, np.float32) for i in range(4)}
    pub.publish(params)
    n_chunks = {r["name"]: r for r in list_weights()}["t/tp-shards"][
        "num_chunks"
    ]
    assert n_chunks >= 2

    sub = WeightSubscriber("t/tp-shards")
    assert sub.chunk_pulls == 0
    _, got = sub.get(sharding=shardings)
    assert sub.chunk_pulls == n_chunks
    assert sub.bytes_pulled > 0
    for i in range(4):
        leaf = got[f"layer{i}"]
        assert isinstance(leaf, jax.Array)
        assert leaf.sharding.spec == P("tp")
        # each device holds half the leaf — the shard, not a replica
        assert {s.data.shape for s in leaf.addressable_shards} == {(25_000,)}
        np.testing.assert_array_equal(np.asarray(leaf), params[f"layer{i}"])

    # cached path: a second get() pulls zero additional chunks
    _, again = sub.get(sharding=shardings)
    assert sub.chunk_pulls == n_chunks
    assert jax.tree_util.tree_leaves(again)[0] is not None
    sub.release()
