"""Multi-node scheduling, placement groups, TPU slice reservation
(reference test model: tests using cluster_utils.Cluster, tests/accelerators/
test_tpu.py)."""

import pytest

import ray_tpu
from ray_tpu._internal.accelerators import (
    TPU_POD_TYPE_LABEL,
    TPU_SLICE_NAME_LABEL,
    TPU_WORKER_ID_LABEL,
)
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


@pytest.fixture(scope="module")
def tpu_cluster():
    """Head + a fake 2-host v5e-16 slice (8 chips per host)."""
    cluster = Cluster(head_node_args=dict(num_cpus=2))
    for worker_id in range(2):
        labels = {
            TPU_SLICE_NAME_LABEL: "slice-a",
            TPU_WORKER_ID_LABEL: str(worker_id),
            TPU_POD_TYPE_LABEL: "v5e-16",
        }
        resources = {"TPU": 8.0, "CPU": 2.0}
        if worker_id == 0:
            resources["TPU-v5e-16-head"] = 1.0
        cluster.add_node(resources=resources, labels=labels)
    cluster.connect()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_cluster_sees_all_nodes(tpu_cluster):
    assert len([n for n in ray_tpu.nodes() if n["Alive"]]) == 3
    total = ray_tpu.cluster_resources()
    assert total["TPU"] == 16.0
    assert total["TPU-v5e-16-head"] == 1.0


def test_remote_node_execution(tpu_cluster):
    @ray_tpu.remote(num_cpus=0, num_tpus=1)
    def which_node():
        import os

        return os.environ.get("RAY_TPU_NODE_ID")

    # requires TPU -> must run on a slice host, not the head
    node_env = ray_tpu.get(which_node.remote(), timeout=120)
    tpu_nodes = {
        n["NodeID"] for n in ray_tpu.nodes() if n["Resources"].get("TPU")
    }
    assert node_env in tpu_nodes


def test_node_affinity(tpu_cluster):
    nodes = [n for n in ray_tpu.nodes() if n["Resources"].get("TPU")]
    target = nodes[1]["NodeID"]

    @ray_tpu.remote(num_cpus=0)
    def whoami():
        import os

        return os.environ.get("RAY_TPU_NODE_ID")

    got = ray_tpu.get(
        whoami.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=target)
        ).remote(),
        timeout=120,
    )
    assert got == target


def test_label_selector(tpu_cluster):
    @ray_tpu.remote(num_cpus=0, label_selector={TPU_WORKER_ID_LABEL: "1"})
    def on_worker_1():
        import os

        return os.environ.get("RAY_TPU_NODE_ID")

    got = ray_tpu.get(on_worker_1.remote(), timeout=120)
    by_id = {n["NodeID"]: n for n in ray_tpu.nodes()}
    assert by_id[got]["Labels"][TPU_WORKER_ID_LABEL] == "1"


def test_placement_group_strict_spread(tpu_cluster):
    pg = placement_group(
        [{"TPU": 4.0}, {"TPU": 4.0}],
        strategy="STRICT_SPREAD",
        bundle_label_selector=[
            {TPU_SLICE_NAME_LABEL: "slice-a"},
            {TPU_SLICE_NAME_LABEL: "slice-a"},
        ],
    )
    assert pg.ready(timeout=60)
    node_ids = pg.bundle_node_ids()
    assert len(set(node_ids)) == 2

    @ray_tpu.remote(num_cpus=0, num_tpus=2)
    def in_bundle():
        import os

        return os.environ.get("RAY_TPU_NODE_ID")

    got = ray_tpu.get(
        in_bundle.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=1
            )
        ).remote(),
        timeout=120,
    )
    assert got == node_ids[1]
    remove_placement_group(pg)


def test_placement_group_infeasible_strict_pack(tpu_cluster):
    # 16 chips cannot strictly pack on one 8-chip host
    pg = placement_group([{"TPU": 16.0}], strategy="STRICT_PACK")
    assert not pg.ready(timeout=2)
    remove_placement_group(pg)


def test_reserve_tpu_slice(tpu_cluster):
    from ray_tpu.util.tpu import reserve_tpu_slice

    reservation = reserve_tpu_slice("v5e-16", timeout=60)
    assert reservation.slice_name == "slice-a"
    assert reservation.num_hosts == 2
    assert reservation.chips_per_host == 8
    # whole slice reserved: another reservation must time out
    with pytest.raises(TimeoutError):
        reserve_tpu_slice("v5e-16", timeout=2)
    reservation.release()
    # after release it works again
    again = reserve_tpu_slice("v5e-16", timeout=60)
    assert again.slice_name == "slice-a"
    again.release()


def test_cross_node_object_transfer(tpu_cluster):
    import numpy as np

    nodes = [n for n in ray_tpu.nodes() if n["Resources"].get("TPU")]

    @ray_tpu.remote(num_cpus=0)
    def produce():
        return np.full((600, 600), 7.0)

    @ray_tpu.remote(num_cpus=0)
    def consume(arr):
        return float(arr.sum())

    ref = produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=nodes[0]["NodeID"])
    ).remote()
    out = ray_tpu.get(
        consume.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=nodes[1]["NodeID"]
            )
        ).remote(ref),
        timeout=120,
    )
    assert out == 7.0 * 600 * 600
