"""Cluster-wide KV prefix tier + disaggregated prefill/decode serving.

The four load-bearing scenarios from the serving plane's contract:

1. Disaggregated (prefill replica ships KV -> decode replica adopts)
   equals fused, token for token, at temperature 0.
2. A fresh scale-up replica serves its first warm-prefix request by
   peer-pulling the blocks — ZERO prefill-computed tokens, asserted on
   the kvcache counters, with the tier counters showing the pull.
3. int8-shipped KV decodes to the same tokens, at ~0.25x wire bytes on
   an f32 KV cache.
4. A SIGKILLed holder degrades to recompute: the request still succeeds
   with identical tokens, and the fallback is visible as a recompute.

Everything runs clusterless: ``LocalTierBackend`` wraps the REAL
``GcsKVTierRegistry`` (same register/resolve/lease/evict/notice protocol
the GCS serves) over an inline chunk store, so two engines in one
process are two replicas in every way except the byte transport.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.kvcache import KVCacheManager
from ray_tpu.kvtier import (
    KVShipment,
    KVTierClient,
    LocalTierBackend,
    block_fingerprints,
)
from ray_tpu.llm.engine import ContinuousBatchingEngine, GenerationRequest
from ray_tpu.models.llama import LlamaConfig, init_params
from ray_tpu.parallel.sharding import unbox_params
from ray_tpu.util.metrics import kvcache_counters, kvtier_counters

BLOCK = 8


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(max_seq_len=128)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    return cfg, params


@pytest.fixture(scope="module")
def tiny_f32():
    # f32 KV shows the int8 codec's real compression (1B codes + per-256
    # scales over 4B elements ~= 0.25x); bf16 KV only reaches ~0.52x
    cfg = dataclasses.replace(
        LlamaConfig.tiny(max_seq_len=128), dtype=jnp.float32
    )
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    return cfg, params


def _engine(cfg, params, backend=None, holder=None, codec="raw",
            num_blocks=64):
    tier = None
    if backend is not None:
        tier = KVTierClient(
            model=cfg.__class__.__name__, backend=backend,
            block_size=BLOCK, codec=codec, holder_id=holder,
        )
    kv = KVCacheManager(num_blocks=num_blocks, block_size=BLOCK)
    eng = ContinuousBatchingEngine(
        cfg, params, num_slots=4, kv_cache=kv, seed=7, kv_tier=tier
    )
    return eng, tier


def _req(prompt, n=8):
    return GenerationRequest(
        token_ids=list(prompt), max_new_tokens=n, temperature=0.0
    )


# ---------------------------------------------------------- fingerprints


class TestFingerprints:
    def test_full_blocks_only_and_deterministic(self):
        toks = list(range(1, 21))  # 20 tokens, block 8 -> 2 full blocks
        fps = block_fingerprints(toks, 8)
        assert len(fps) == 2
        assert fps == block_fingerprints(toks, 8)
        assert all(len(fp) == 32 for fp in fps)

    def test_chained_prefix_property(self):
        a = list(range(1, 25))
        b = list(a)
        b[10] = 99  # mutate block 1
        fa, fb = block_fingerprints(a, 8), block_fingerprints(b, 8)
        assert fa[0] == fb[0]  # block 0 untouched
        assert fa[1] != fb[1]
        assert fa[2] != fb[2]  # chained: the change propagates forward


# ------------------------------------------------------- registry protocol


def _registry(max_entries=4096, lease_s=60.0):
    return LocalTierBackend(max_entries=max_entries, lease_s=lease_s).registry


def _register(reg, fps, holder="h1", model="m", entry_bytes=None):
    return reg.register(
        model, fps, holder, ("node", 1), entry_bytes or b"blob",
        meta={"nblocks": len(fps), "wire_bytes": 10, "logical_bytes": 20},
    )


class TestKVTierRegistry:
    def test_resolve_longest_first(self):
        reg = _registry()
        _register(reg, ["aa", "bb", "cc"])
        got = reg.resolve("m", ["cc", "bb", "aa"])  # caller sends longest-first
        assert got is not None and got["fp"] == "cc" and got["fp_rank"] == 0
        got = reg.resolve("m", ["zz", "bb"])
        assert got["fp"] == "bb"
        assert reg.resolve("m", ["zz"]) is None
        assert reg.resolve("other-model", ["cc"]) is None

    def test_fingerprint_takeover_fresher_holder_wins(self):
        reg = _registry()
        e1 = _register(reg, ["aa", "bb"], holder="h1")["entry_id"]
        e2 = _register(reg, ["aa", "bb", "cc"], holder="h2")["entry_id"]
        # h2 took over both shared fps; h1's entry covers nothing and was
        # evicted with a notice queued for h1
        assert reg.resolve("m", ["bb"])["entry_id"] == e2
        assert reg.collect("h1")["released"] == [e1]

    def test_capacity_lru_skips_leased(self):
        reg = _registry(max_entries=2)
        e1 = _register(reg, ["aa"], holder="h1")["entry_id"]
        assert reg.lease(e1, "pull-1")
        e2 = _register(reg, ["bb"], holder="h1")["entry_id"]
        _register(reg, ["cc"], holder="h2")
        # over cap: e1 is oldest but leased (a puller mid-transfer), so
        # e2 is the one LRU evicts
        assert reg.resolve("m", ["aa"]) is not None
        assert reg.resolve("m", ["bb"]) is None
        assert e2 in reg.collect("h1")["released"]
        # release + another register: back at cap, and the true LRU
        # ("cc", untouched since insert) goes — "aa" survives because the
        # resolve above refreshed its last_used
        reg.release(e1, "pull-1")
        _register(reg, ["dd"], holder="h2")
        assert reg.stats()["entries"] == 2
        assert reg.resolve("m", ["cc"]) is None
        assert reg.resolve("m", ["aa"]) is not None

    def test_notices_drained_once_by_register(self):
        reg = _registry(max_entries=1)
        e1 = _register(reg, ["aa"], holder="h1")["entry_id"]
        reply = _register(reg, ["bb"], holder="h1")
        # h1's next register drains the eviction notice for e1
        assert reply["released"] == [e1]
        assert reg.collect("h1")["released"] == []

    def test_holder_evict_requires_ownership(self):
        reg = _registry()
        e1 = _register(reg, ["aa"], holder="h1")["entry_id"]
        assert reg.evict([e1], holder_id="h2") == 0  # not the holder
        assert reg.resolve("m", ["aa"]) is not None
        assert reg.evict([e1], holder_id="h1") == 1
        assert reg.resolve("m", ["aa"]) is None
        # holder-initiated: no notice queued back at the initiator
        assert reg.collect("h1")["released"] == []

    def test_node_death_sweeps_holder_entries(self):
        reg = _registry()
        _register(reg, ["aa"], holder="h1")
        reg.register("m", ["bb"], "h2", ("other", 2), b"x", meta={})
        reg.on_node_death(("node", 1))
        assert reg.resolve("m", ["aa"]) is None  # swept with the node
        assert reg.resolve("m", ["bb"]) is not None
        assert reg.stats()["dead_holder_sweeps"] == 1

    def test_lease_on_gone_entry_fails(self):
        reg = _registry()
        e1 = _register(reg, ["aa"], holder="h1")["entry_id"]
        assert reg.evict([e1], holder_id="h1") == 1
        assert not reg.lease(e1, "pull-1")
        assert reg.stats()["lease_conflicts"] == 1


# ----------------------------------------- scenario 2: scale-up peer pull


def test_scale_up_first_request_zero_prefill(tiny):
    """A fresh replica's FIRST warm-prefix request peer-pulls the whole
    prefix (plus the first token) and computes zero prefill tokens."""
    cfg, params = tiny
    backend = LocalTierBackend()
    warm, _ = _engine(cfg, params, backend, "warm-replica")
    prompt = list(range(1, 25))  # 3 full blocks
    base = warm.generate_one(_req(prompt))

    fresh, _ = _engine(cfg, params, backend, "scale-up")
    t0, k0 = kvtier_counters(), kvcache_counters()
    out = fresh.generate_one(_req(prompt))
    t1, k1 = kvtier_counters(), kvcache_counters()

    assert out.token_ids == base.token_ids
    assert k1["prefill_tokens_computed"] - k0["prefill_tokens_computed"] == 0
    assert t1["hit"] - t0["hit"] == 1
    assert t1["peer_pull"] - t0["peer_pull"] == 1
    assert t1["recompute"] - t0["recompute"] == 0
    assert t1["transfer_wire_bytes"] > t0["transfer_wire_bytes"]


def test_partial_prefix_pull_then_suffix_prefill(tiny):
    """A longer prompt sharing only the first blocks adopts the pulled
    prefix and prefills just the suffix."""
    cfg, params = tiny
    backend = LocalTierBackend()
    warm, _ = _engine(cfg, params, backend, "warm")
    shared = list(range(1, 17))  # 2 full blocks
    warm.generate_one(_req(shared))

    fresh, _ = _engine(cfg, params, backend, "fresh")
    longer = shared + [40, 41, 42, 43, 44, 45, 46, 47, 48, 49]
    k0 = kvcache_counters()
    t0 = kvtier_counters()
    out = fresh.generate_one(_req(longer))
    k1 = kvcache_counters()
    t1 = kvtier_counters()
    computed = k1["prefill_tokens_computed"] - k0["prefill_tokens_computed"]
    assert t1["peer_pull"] - t0["peer_pull"] == 1
    # adopted 2 blocks (16 tokens) of a 26-token prompt: only the suffix
    # (and at most one block-boundary remainder) is computed
    assert 0 < computed <= len(longer) - 16
    # parity: the warm engine computes the same prompt through its own
    # radix-cached prefix — an independent KV lineage for the same tokens
    assert out.token_ids == warm.generate_one(_req(longer)).token_ids


# -------------------------------------- scenario 1: disagg == fused parity


def test_disagg_handoff_matches_fused(tiny):
    """prefill_only on one engine -> directed shipment -> generate_one on
    another equals the fused engine, token for token (temperature 0)."""
    cfg, params = tiny
    backend = LocalTierBackend()
    pre, pre_tier = _engine(cfg, params, backend, "prefill-replica")
    dec, dec_tier = _engine(cfg, params, backend, "decode-replica")

    for prompt in (list(range(50, 77)),
                   [1, 2, 3]):  # sub-block prompt: ships tail only
        shipment = pre.prefill_only(_req(prompt))
        assert shipment is not None
        # blob round-trip, as it crosses the ingress wire
        shipment = KVShipment.from_blob(shipment.to_blob())
        payload = dec_tier.fetch_shipment(shipment)
        assert payload is not None
        k0 = kvcache_counters()
        out = dec.generate_one(_req(prompt), shipment=(shipment, payload))
        k1 = kvcache_counters()
        assert (k1["prefill_tokens_computed"]
                - k0["prefill_tokens_computed"]) == 0
        # parity reference: the prefill engine decodes from its OWN
        # locally-computed blocks — an independent exact-KV lineage
        assert out.token_ids == pre.generate_one(_req(prompt)).token_ids


# --------------------------------------------- scenario 3: int8 shipments


def test_int8_shipment_parity_and_wire_ratio(tiny_f32):
    cfg, params = tiny_f32
    backend = LocalTierBackend()
    pre, _ = _engine(cfg, params, backend, "pre8", codec="int8")
    dec, dec_tier = _engine(cfg, params, backend, "dec8", codec="int8")

    prompt = list(range(3, 35))  # 4 full blocks, f32 KV
    shipment = pre.prefill_only(_req(prompt))
    assert shipment is not None and shipment.codec == "int8"
    assert shipment.wire_bytes <= 0.51 * shipment.logical_bytes
    t0 = kvtier_counters()
    payload = dec_tier.fetch_shipment(shipment)
    t1 = kvtier_counters()
    wire = t1["transfer_wire_bytes"] - t0["transfer_wire_bytes"]
    logical = t1["transfer_logical_bytes"] - t0["transfer_logical_bytes"]
    assert 0 < wire <= 0.51 * logical
    out = dec.generate_one(_req(prompt), shipment=(shipment, payload))
    # int8-adopted KV vs the prefill engine's exact f32 KV lineage
    assert out.token_ids == pre.generate_one(_req(prompt)).token_ids


# -------------------------------------- scenario 4: dead-holder fallback


def test_dead_holder_falls_back_to_recompute(tiny):
    """Both dead-holder degradations on one SIGKILLed peer: a tier
    resolve against the stale registry entry recomputes (no peer_pull),
    and a directed handoff whose chunks died fetches None and decodes
    fused-style — identical tokens on both paths."""
    cfg, params = tiny
    backend = LocalTierBackend()
    warm, _ = _engine(cfg, params, backend, "doomed")
    prompt = list(range(1, 25))
    base = warm.generate_one(_req(prompt))
    shipment = warm.prefill_only(_req(prompt))
    assert shipment is not None

    backend.kill_holder("doomed")  # chunks gone, registry entry stale

    fresh, fresh_tier = _engine(cfg, params, backend, "survivor")
    # directed handoff: the shipment's chunks are gone — visible failure,
    # the decode side falls back to computing the prefill itself
    assert fresh_tier.fetch_shipment(shipment) is None
    t0 = kvtier_counters()
    out = fresh.generate_one(_req(prompt), shipment=None)  # must not raise
    t1 = kvtier_counters()
    assert out.token_ids == base.token_ids
    assert t1["recompute"] - t0["recompute"] >= 1
    assert t1["peer_pull"] - t0["peer_pull"] == 0


# ------------------------------------------------- serve-level local mode


def test_serve_local_disagg_roles(tiny):
    """roles={'prefill','decode'} through the serve layer (local mode):
    ingress routes the handoff, decode computes zero prefill tokens,
    output matches a fused deployment."""
    from ray_tpu.llm.config import LLMConfig
    from ray_tpu.llm.serving import build_llm_deployment
    from ray_tpu.serve.local_mode import run_local

    backend = LocalTierBackend()
    disagg_cfg = LLMConfig(
        model_id="llama-tiny", max_seq_len=64, max_new_tokens=6,
        kv_cache_blocks=64, kv_block_size=8,
        roles={"prefill": 1, "decode": 1},
    )
    fused_cfg = dataclasses.replace(disagg_cfg, roles=None)
    disagg = run_local(
        build_llm_deployment(disagg_cfg, tier_backend=backend),
        name="disagg",
    )
    fused = run_local(build_llm_deployment(fused_cfg), name="fused")

    request = {"token_ids": list(range(1, 21)), "max_new_tokens": 6}
    k0 = kvcache_counters()
    got = disagg.remote(dict(request)).result()
    want = fused.remote(dict(request)).result()
    assert got["token_ids"] == want["token_ids"]

    # the decode replica adopted every block the prefill replica shipped
    decode = disagg._instances["llama-tiny-decode"]
    stats = decode.kvcache_stats()
    assert stats["adopted_blocks"] >= 2
    tier_stats = decode.kvtier_stats()
    assert tier_stats["role"] == "decode"
    prefill = disagg._instances["llama-tiny-prefill"]
    assert prefill.kvtier_stats()["role"] == "prefill"


def test_llm_config_validation():
    from ray_tpu.llm.config import LLMConfig

    with pytest.raises(ValueError, match="kv_cache_blocks"):
        LLMConfig(roles={"prefill": 1, "decode": 1})
    with pytest.raises(ValueError, match="positive int"):
        LLMConfig(roles={"prefill": 1}, kv_cache_blocks=64)
    with pytest.raises(ValueError, match="roles keys"):
        LLMConfig(roles={"prefill": 1, "verify": 1}, kv_cache_blocks=64)
    with pytest.raises(ValueError, match="kv_ship_codec"):
        LLMConfig(kv_ship_codec="fp4", kv_cache_blocks=64)
    with pytest.raises(ValueError, match="kv_cache_blocks"):
        LLMConfig(kv_tier=True)


# ------------------------------------------------------ metrics rollup


def test_kvtier_summary_rollup():
    from ray_tpu.util.metrics import kvtier_summary

    payloads = [{
        "metrics": [
            {"name": "kvtier_hit_total", "tag_keys": ["model"],
             "values": {'["m"]': 3.0}},
            {"name": "kvtier_peer_pull_total", "tag_keys": ["model"],
             "values": {'["m"]': 2.0}},
            {"name": "kvtier_recompute_total", "tag_keys": ["model"],
             "values": {'["m"]': 1.0}},
            {"name": "kvtier_transfer_bytes_total",
             "tag_keys": ["model", "kind"],
             "values": {'["m", "logical"]': 1000.0, '["m", "wire"]': 260.0}},
            {"name": "kvcache_ttft_ms",
             "tag_keys": ["cache", "mesh", "tier"],
             "boundaries": [1, 10, 100],
             "counts": {'["hit", "tp=1", "peer"]': [0, 2, 0, 0],
                        '["miss", "tp=1", "miss"]': [0, 0, 1, 0]},
             "values": {'["hit", "tp=1", "peer"]': 12.0,
                        '["miss", "tp=1", "miss"]': 80.0}},
        ],
    }]
    out = kvtier_summary(payloads)
    assert out["hit"] == 3.0
    assert out["peer_pull"] == 2.0
    assert out["recompute"] == 1.0
    assert out["transfer_bytes"] == {"logical": 1000.0, "wire": 260.0}
    peer = out["ttft_ms_by_tier"]["peer"]
    assert peer["count"] == 2.0 and peer["mean_ms"] == 6.0
    assert out["ttft_ms_by_tier"]["miss"]["count"] == 1.0
