"""Dashboard REST API + job submission tests.

Models the reference's dashboard/job tests
(python/ray/dashboard/modules/job/tests/test_job_manager.py and the state
head endpoint tests): REST state endpoints against a live cluster, job
submit/status/logs/stop through the SDK, and the Prometheus scrape target.
"""

import json
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.job_submission import JobStatus, JobSubmissionClient


@pytest.fixture
def dashboard_cluster():
    ray_tpu.init(num_cpus=4, resources={"TPU": 4}, include_dashboard=True)
    from ray_tpu import _worker_api

    node = _worker_api.get_node()
    yield node.dashboard
    ray_tpu.shutdown()


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def test_version_and_nodes(dashboard_cluster):
    dash = dashboard_cluster
    assert _get_json(dash.url + "/api/version")["api_version"] == "1"
    nodes = _get_json(dash.url + "/api/nodes")
    assert len(nodes) == 1
    assert nodes[0]["alive"] is True


def test_state_endpoints(dashboard_cluster):
    dash = dashboard_cluster

    @ray_tpu.remote
    class Sleeper:
        def ping(self):
            return 1

    a = Sleeper.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    time.sleep(1.5)  # task-event flush
    actors = _get_json(dash.url + "/api/actors")
    assert len(actors) >= 1
    tasks = _get_json(dash.url + "/api/tasks")
    assert isinstance(tasks, list)
    status = _get_json(dash.url + "/api/cluster_status")
    assert "resource_state" in status
    assert any(n["alive"] for n in status["resource_state"]["nodes"])


def test_metrics_endpoint(dashboard_cluster):
    dash = dashboard_cluster
    with urllib.request.urlopen(dash.url + "/metrics", timeout=10) as resp:
        body = resp.read().decode()
    assert resp.status == 200 or body is not None


def test_job_submit_and_wait(dashboard_cluster):
    dash = dashboard_cluster
    client = JobSubmissionClient(dash.url)
    script = (
        "import os, ray_tpu; "
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS']); "
        "print('job-output:', ray_tpu.get(ray_tpu.remote(lambda: 40 + 2).remote()))"
    )
    sid = client.submit_job(entrypoint=f'{sys.executable} -c "{script}"')
    status = client.wait_until_finished(sid, timeout=120)
    logs = client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, logs
    assert "job-output: 42" in logs


def test_job_failure_status(dashboard_cluster):
    client = JobSubmissionClient(dashboard_cluster.url)
    sid = client.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(sid, timeout=60) == JobStatus.FAILED
    info = client.get_job_info(sid)
    assert "code 3" in info["message"]


def test_job_stop(dashboard_cluster):
    client = JobSubmissionClient(dashboard_cluster.url)
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'"
    )
    time.sleep(0.5)
    assert client.stop_job(sid) is True
    assert client.wait_until_finished(sid, timeout=60) == JobStatus.STOPPED


def test_job_list_and_unknown(dashboard_cluster):
    client = JobSubmissionClient(dashboard_cluster.url)
    sid = client.submit_job(entrypoint="true")
    client.wait_until_finished(sid, timeout=60)
    jobs = client.list_jobs()
    assert any(j["submission_id"] == sid for j in jobs)
    with pytest.raises(RuntimeError, match="404"):
        client.get_job_status("raysubmit_doesnotexist")


def test_index_page_served(dashboard_cluster):
    """The browser UI page (role of dashboard/client) serves at /."""
    dash = dashboard_cluster
    with urllib.request.urlopen(dash.url + "/") as resp:
        body = resp.read().decode()
        assert resp.headers["Content-Type"].startswith("text/html")
    assert "ray_tpu dashboard" in body
    assert "/api/cluster_resources" in body


def test_timeline_endpoint_and_ui_panels(dashboard_cluster):
    """/api/timeline serves chrome-trace events for executed tasks, and the
    HTML UI carries the timeline/sparkline/placement-group panels
    (scope-reduced role of the React timeline + metrics views)."""
    dash = dashboard_cluster

    @ray_tpu.remote
    def traced(x):
        time.sleep(0.05)
        return x

    assert ray_tpu.get(traced.remote(5), timeout=60) == 5
    # task events flush to the GCS about once a second
    deadline = time.time() + 20
    events = []
    while time.time() < deadline:
        events = _get_json(dash.url + "/api/timeline")["traceEvents"]
        if any(e["name"] == "traced" for e in events):
            break
        time.sleep(0.5)
    mine = [e for e in events if e["name"] == "traced"]
    assert mine, events[:3]
    ev = mine[0]
    assert ev["ph"] == "X" and ev["dur"] >= 0.05 * 1e6 * 0.5
    assert ev["args"]["state"] in ("FINISHED", "RUNNING")

    with urllib.request.urlopen(dash.url + "/", timeout=10) as resp:
        html = resp.read().decode()
    for anchor in ('id="timeline"', 'id="sparklines"', 'id="pgs"',
                   "/api/timeline", "renderSparklines"):
        assert anchor in html, anchor


def test_train_endpoint(dashboard_cluster):
    """/api/train serves live run records plus the cluster fault-tolerance
    rollup (resizes/restarts/aborts/recovery + collective overlap split)."""
    dash = dashboard_cluster
    out = _get_json(dash.url + "/api/train")
    assert out["runs"] == []  # nothing training in this cluster
    ft = out["fault_tolerance"]
    assert set(ft) == {
        "resizes", "restarts", "aborts", "recoveries", "recovery_mean_s",
        "collective_exposed_s", "collective_overlapped_s", "overlap_fraction",
        "stragglers", "straggler_verdicts",
    }
    assert ft["overlap_fraction"] == 0.0  # no overlapped collectives yet
    assert ft["stragglers"] == []  # timeseries join present, nobody slow


def test_autoscale_endpoint(dashboard_cluster):
    """/api/autoscale serves the SLO-autoscaler decision log (empty when
    no policy deployment has acted) plus the autoscale_* metric rollup."""
    dash = dashboard_cluster
    out = _get_json(dash.url + "/api/autoscale")
    assert out["events"] == []  # no autoscaled deployments in this cluster
    summary = out["summary"]
    assert summary["scale_ups"] == 0.0 and summary["scale_downs"] == 0.0
    assert summary["decision_p50_s"] is None


def test_events_endpoint(dashboard_cluster):
    """/api/events serves the cluster flight recorder — the same GCS event
    store `ray_tpu events` reads post-mortem."""
    from ray_tpu.util import events

    dash = dashboard_cluster
    events.record_event(events.REPLICA_STATE, state="DASH_PROBE")
    events.flush_events()  # deterministic: skip the 1s pusher tick
    out = _get_json(dash.url + "/api/events")
    assert isinstance(out["events"], list)
    assert any(e.get("state") == "DASH_PROBE" for e in out["events"])
