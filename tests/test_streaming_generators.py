"""Streaming generators (reference: num_returns="streaming" ->
ObjectRefGenerator backed by ObjectRefStream, task_manager.h:67 and
ReportGeneratorItemReturns, core_worker.proto:507)."""

import time

import pytest

import ray_tpu
from ray_tpu.object_ref import ObjectRefGenerator


def test_basic_stream(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    g = gen.remote(6)
    assert isinstance(g, ObjectRefGenerator)
    out = [ray_tpu.get(ref, timeout=60) for ref in g]
    assert out == [i * i for i in range(6)]


def test_items_stream_before_task_finishes(ray_start_regular):
    """The first item is consumable while the producer still runs."""
    @ray_tpu.remote
    def warm():
        return True

    ray_tpu.get(warm.remote(), timeout=60)  # absorb worker-spawn latency

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        yield "first"
        time.sleep(3.0)
        yield "second"

    g = slow_gen.remote()
    t0 = time.time()
    first = ray_tpu.get(next(g), timeout=60)
    first_latency = time.time() - t0
    assert first == "first"
    assert first_latency < 2.5  # did not wait for the full 3s producer
    assert ray_tpu.get(next(g), timeout=60) == "second"
    with pytest.raises(StopIteration):
        next(g)


def test_large_items_via_plasma(ray_start_regular):
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def big_gen():
        for i in range(3):
            yield np.full((300_000,), i, np.float32)  # > inline threshold

    vals = [ray_tpu.get(r, timeout=120) for r in big_gen.remote()]
    assert [float(v[0]) for v in vals] == [0.0, 1.0, 2.0]
    assert all(v.shape == (300_000,) for v in vals)


def test_mid_stream_error_after_yields(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def bad_gen():
        yield 1
        yield 2
        raise RuntimeError("stream broke")

    g = bad_gen.remote()
    assert ray_tpu.get(next(g), timeout=60) == 1
    assert ray_tpu.get(next(g), timeout=60) == 2
    with pytest.raises(Exception, match="stream broke"):
        next(g)


def test_non_generator_function_errors(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def not_a_gen():
        return 42

    g = not_a_gen.remote()
    with pytest.raises(Exception, match="generator"):
        next(g)


def test_actor_streaming_unsupported_is_clear(ray_start_regular):
    @ray_tpu.remote
    class A:
        def gen(self):
            yield 1

    a = A.remote()
    with pytest.raises(NotImplementedError, match="streaming"):
        a.gen.options(num_returns="streaming").remote()
