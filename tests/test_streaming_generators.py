"""Streaming generators (reference: num_returns="streaming" ->
ObjectRefGenerator backed by ObjectRefStream, task_manager.h:67 and
ReportGeneratorItemReturns, core_worker.proto:507)."""

import time

import pytest

import ray_tpu
from ray_tpu.object_ref import ObjectRefGenerator


def test_basic_stream(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    g = gen.remote(6)
    assert isinstance(g, ObjectRefGenerator)
    out = [ray_tpu.get(ref, timeout=60) for ref in g]
    assert out == [i * i for i in range(6)]


def test_items_stream_before_task_finishes(ray_start_regular):
    """The first item is consumable while the producer still runs."""
    @ray_tpu.remote
    def warm():
        return True

    ray_tpu.get(warm.remote(), timeout=60)  # absorb worker-spawn latency

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        yield "first"
        time.sleep(3.0)
        yield "second"

    g = slow_gen.remote()
    t0 = time.time()
    first = ray_tpu.get(next(g), timeout=60)
    first_latency = time.time() - t0
    assert first == "first"
    assert first_latency < 2.5  # did not wait for the full 3s producer
    assert ray_tpu.get(next(g), timeout=60) == "second"
    with pytest.raises(StopIteration):
        next(g)


def test_large_items_via_plasma(ray_start_regular):
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def big_gen():
        for i in range(3):
            yield np.full((300_000,), i, np.float32)  # > inline threshold

    vals = [ray_tpu.get(r, timeout=120) for r in big_gen.remote()]
    assert [float(v[0]) for v in vals] == [0.0, 1.0, 2.0]
    assert all(v.shape == (300_000,) for v in vals)


def test_mid_stream_error_after_yields(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def bad_gen():
        yield 1
        yield 2
        raise RuntimeError("stream broke")

    g = bad_gen.remote()
    assert ray_tpu.get(next(g), timeout=60) == 1
    assert ray_tpu.get(next(g), timeout=60) == 2
    with pytest.raises(Exception, match="stream broke"):
        next(g)


def test_non_generator_function_errors(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def not_a_gen():
        return 42

    g = not_a_gen.remote()
    with pytest.raises(Exception, match="generator"):
        next(g)


# -- actor streaming generators (reference: python/ray/actor.py:516-548) ----


def test_actor_basic_stream(ray_start_regular):
    @ray_tpu.remote
    class A:
        def gen(self, n):
            for i in range(n):
                yield i * i

    a = A.remote()
    g = a.gen.options(num_returns="streaming").remote(6)
    assert isinstance(g, ObjectRefGenerator)
    out = [ray_tpu.get(ref, timeout=60) for ref in g]
    assert out == [i * i for i in range(6)]


def test_actor_items_stream_before_method_finishes(ray_start_regular):
    @ray_tpu.remote
    class A:
        def ping(self):
            return True

        def slow_gen(self):
            yield "first"
            time.sleep(3.0)
            yield "second"

    a = A.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)  # absorb worker-spawn latency
    g = a.slow_gen.options(num_returns="streaming").remote()
    t0 = time.time()
    first = ray_tpu.get(next(g), timeout=60)
    first_latency = time.time() - t0
    assert first == "first"
    assert first_latency < 2.5
    assert ray_tpu.get(next(g), timeout=60) == "second"
    with pytest.raises(StopIteration):
        next(g)


def test_actor_stream_interleaves_with_state(ray_start_regular):
    """Streams run in the actor's seq order and see its mutable state;
    ordinary calls after a stream observe the generator's effects."""
    @ray_tpu.remote
    class Accum:
        def __init__(self):
            self.total = 0

        def add_stream(self, n):
            for i in range(n):
                self.total += i
                yield self.total

        def get_total(self):
            return self.total

    a = Accum.remote()
    g = a.add_stream.options(num_returns="streaming").remote(4)
    later = a.get_total.remote()
    assert [ray_tpu.get(r, timeout=60) for r in g] == [0, 1, 3, 6]
    assert ray_tpu.get(later, timeout=60) == 6


def test_actor_large_items_via_plasma(ray_start_regular):
    import numpy as np

    @ray_tpu.remote
    class A:
        def big_gen(self):
            for i in range(3):
                yield np.full((300_000,), i, np.float32)

    a = A.remote()
    g = a.big_gen.options(num_returns="streaming").remote()
    vals = [ray_tpu.get(r, timeout=120) for r in g]
    assert [float(v[0]) for v in vals] == [0.0, 1.0, 2.0]


def test_actor_mid_stream_error_after_yields(ray_start_regular):
    @ray_tpu.remote
    class A:
        def bad_gen(self):
            yield 1
            yield 2
            raise RuntimeError("stream broke")

    a = A.remote()
    g = a.bad_gen.options(num_returns="streaming").remote()
    assert ray_tpu.get(next(g), timeout=60) == 1
    assert ray_tpu.get(next(g), timeout=60) == 2
    with pytest.raises(Exception, match="stream broke"):
        next(g)


def test_actor_non_generator_method_errors(ray_start_regular):
    @ray_tpu.remote
    class A:
        def not_a_gen(self):
            return 42

    a = A.remote()
    g = a.not_a_gen.options(num_returns="streaming").remote()
    with pytest.raises(Exception, match="generator"):
        next(g)


def test_actor_stream_survives_actor_death(shutdown_only):
    """Mid-stream actor death surfaces as an error on the NEXT read; items
    already delivered stay readable (task-side parity), and with retries the
    resent call re-runs the generator on the restarted incarnation."""
    import os
    import signal

    node = ray_tpu.init(num_cpus=2)

    @ray_tpu.remote(max_restarts=2, max_task_retries=2)
    class A:
        def gen(self, n):
            for i in range(n):
                yield i

    a = A.remote()
    # a completed stream first, so the actor is warm
    g1 = a.gen.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r, timeout=60) for r in g1] == [0, 1, 2]
    # SIGKILL the actor's worker from outside (an in-actor os._exit would be
    # re-executed by the retry, burning every restart — at-least-once): one
    # kill, one restart; the next streaming call rides the restart path
    pids = [lease.worker.pid for lease in node.raylet._leases.values()]
    assert pids
    for pid in pids:
        os.kill(pid, signal.SIGKILL)
    time.sleep(0.5)
    g2 = a.gen.options(num_returns="streaming").remote(4)
    assert [ray_tpu.get(r, timeout=120) for r in g2] == [0, 1, 2, 3]
