"""GCE TPU queued-resources provider against a mocked HTTP API
(reference: autoscaler/_private/gcp/node_provider.py:63 — create ->
pending -> ready/failed, quota errors, eventual consistency, chaos through
the reconciler)."""

import threading

import pytest

from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalingConfig,
    GceTpuQueuedResourceProvider,
    NodeTypeConfig,
    QuotaExceededError,
)


class MockGceApi:
    """In-memory queuedResources API with scriptable failure behaviors."""

    def __init__(self):
        self.resources = {}  # name -> dict(state, node_count, ready_node_count)
        self.lock = threading.Lock()
        self.quota_failures_remaining = 0
        self.consistency_lag_polls = 0  # GETs that 404 after create
        self.delete_failures_remaining = 0
        self.provision_after_polls = 0  # GETs until WAITING -> ACTIVE
        self.fail_instead_of_active = False
        self.calls = []

    def __call__(self, method, path, body):
        with self.lock:
            self.calls.append((method, path))
            name = path.rsplit("/", 1)[-1].split("?")[0]
            if method == "POST":
                name = path.split("queued_resource_id=")[-1]
                if self.quota_failures_remaining > 0:
                    self.quota_failures_remaining -= 1
                    return 429, {"error": "QUOTA_EXCEEDED"}
                self.resources[name] = {
                    "state": "WAITING_FOR_RESOURCES",
                    "node_count": (body or {}).get("tpu", {})
                    .get("node_spec", {}).get("node_count", 1),
                    "ready_node_count": 0,
                    "polls": 0,
                    "lag": self.consistency_lag_polls,
                }
                return 200, {"name": name}
            if method == "GET":
                res = self.resources.get(name)
                if res is None:
                    return 404, {}
                if res["lag"] > 0:
                    res["lag"] -= 1
                    return 404, {}
                res["polls"] += 1
                if (
                    res["state"] == "WAITING_FOR_RESOURCES"
                    and res["polls"] > self.provision_after_polls
                ):
                    if self.fail_instead_of_active:
                        res["state"] = "FAILED"
                    else:
                        res["state"] = "ACTIVE"
                        res["ready_node_count"] = res["node_count"]
                return 200, dict(res)
            if method == "DELETE":
                if self.delete_failures_remaining > 0:
                    self.delete_failures_remaining -= 1
                    return 503, {"error": "transient"}
                return (200, {}) if self.resources.pop(name, None) else (404, {})
        raise AssertionError(f"unexpected {method} {path}")


def _config(min_workers=0, group_size=4):
    return AutoscalingConfig(
        node_types=[
            NodeTypeConfig(
                name="v5e-16",
                resources={"TPU": 4.0, "CPU": 2.0},
                labels={"ray.io/tpu-pod-type": "v5litepod-16"},
                min_workers=min_workers,
                max_workers=4,
                group_size=group_size,
            )
        ],
        idle_timeout_s=9999,
        update_interval_s=0.01,
    )


def _provider(api, config=None, **kw):
    sleeps = []
    provider = GceTpuQueuedResourceProvider(
        config or _config(),
        api,
        sleep=sleeps.append,
        consistency_grace_s=30.0,
        **kw,
    )
    return provider, sleeps


def test_create_pending_then_active():
    api = MockGceApi()
    api.provision_after_polls = 2
    provider, _ = _provider(api)
    inst = provider.create_node("v5e-16")
    assert inst.status == "PENDING"
    # stays pending while the API still reports WAITING_FOR_RESOURCES
    assert provider.non_terminated_nodes()[0].status == "PENDING"
    assert provider.non_terminated_nodes()[0].status == "PENDING"
    # third poll crosses provision_after_polls
    assert provider.non_terminated_nodes()[0].status == "ACTIVE"


def test_quota_backoff_then_success():
    api = MockGceApi()
    api.quota_failures_remaining = 2
    provider, sleeps = _provider(api)
    inst = provider.create_node("v5e-16")
    assert inst is not None
    # two 429s -> two exponential backoffs before the successful attempt
    assert len(sleeps) == 2 and sleeps[1] == 2 * sleeps[0]


def test_quota_exhaustion_raises():
    api = MockGceApi()
    api.quota_failures_remaining = 99
    provider, sleeps = _provider(api, create_retries=3)
    with pytest.raises(QuotaExceededError):
        provider.create_node("v5e-16")
    # backoff only BETWEEN attempts: 3 attempts -> 2 sleeps
    assert len(sleeps) == 2


def test_eventual_consistency_grace():
    """A fresh resource 404s for a few polls; the provider must NOT drop it."""
    api = MockGceApi()
    api.consistency_lag_polls = 2
    provider, _ = _provider(api)
    provider.create_node("v5e-16")
    assert len(provider.non_terminated_nodes()) == 1  # 404 #1: tolerated
    assert len(provider.non_terminated_nodes()) == 1  # 404 #2: tolerated
    assert provider.non_terminated_nodes()[0].status in ("PENDING", "ACTIVE")


def test_vanished_after_first_sighting_is_dropped():
    api = MockGceApi()
    api.provision_after_polls = 100  # stays WAITING (PENDING here)
    provider, _ = _provider(api)
    inst = provider.create_node("v5e-16")
    provider.non_terminated_nodes()  # first successful GET (first_seen)
    with api.lock:
        del api.resources[inst.instance_id]  # resource vanishes server-side
    assert provider.non_terminated_nodes() == []


def test_partial_slice_stays_pending():
    """ACTIVE with ready_node_count < node_count is not usable yet."""
    api = MockGceApi()
    provider, _ = _provider(api)
    inst = provider.create_node("v5e-16")
    with api.lock:
        api.resources[inst.instance_id].update(
            state="ACTIVE", ready_node_count=2
        )
    assert provider.non_terminated_nodes()[0].status == "PENDING"
    with api.lock:
        api.resources[inst.instance_id]["ready_node_count"] = 4
    assert provider.non_terminated_nodes()[0].status == "ACTIVE"


def test_failed_provision_deletes_and_frees_slot():
    api = MockGceApi()
    api.fail_instead_of_active = True
    provider, _ = _provider(api)
    inst = provider.create_node("v5e-16")
    assert provider.non_terminated_nodes() == []
    with api.lock:
        assert inst.instance_id not in api.resources  # DELETEd remotely


def test_terminate_retries_transient_failures():
    api = MockGceApi()
    provider, sleeps = _provider(api)
    inst = provider.create_node("v5e-16")
    api.delete_failures_remaining = 2
    provider.terminate_node(inst.instance_id)
    assert len(sleeps) == 2
    with api.lock:
        assert inst.instance_id not in api.resources


def test_preempted_active_slice_is_dropped():
    api = MockGceApi()
    provider, _ = _provider(api)
    inst = provider.create_node("v5e-16")
    assert provider.non_terminated_nodes()[0].status == "ACTIVE"
    with api.lock:
        api.resources[inst.instance_id]["state"] = "FAILED"
    assert provider.check_preemptions() == [inst.instance_id]
    assert provider.non_terminated_nodes() == []


# -- reconciler chaos ---------------------------------------------------------


def _stub_gcs_state():
    """Cluster state with an unmet TPU demand, to make the scheduler want
    one v5e-16 slice."""
    return {
        "nodes": [],
        "pending_demands": [
            {"resources": {"TPU": 4.0}, "label_selector": {}, "count": 1}
        ],
        "pending_placement_groups": [],
    }


def test_reconciler_relaunches_after_failed_provision():
    """Chaos: the first slice FAILS mid-provision; the next reconcile tick
    must notice the freed slot and relaunch."""
    api = MockGceApi()
    api.fail_instead_of_active = True
    provider, _ = _provider(api)
    reports = []
    autoscaler = Autoscaler(
        _config(), provider,
        lambda method, *a: _stub_gcs_state()
        if method == "get_cluster_resource_state" else reports.append(a),
    )
    r1 = autoscaler.update()
    assert len(r1["launched"]) == 1
    # tick 2: the poll discovers FAILED, deletes the resource, and with the
    # slot free the still-unmet demand relaunches in the same tick
    r2 = autoscaler.update()
    assert len(r2["launched"]) == 1
    # the replacement provisions cleanly once the API stops failing
    api.fail_instead_of_active = False
    nodes = provider.non_terminated_nodes()
    assert len(nodes) == 1 and nodes[0].status == "ACTIVE"


def test_reconciler_survives_provider_raising_mid_scale_up():
    """Chaos: create_node raises (quota hard-exhausted) mid-reconcile —
    the tick completes, reports the failure, and later ticks recover."""
    api = MockGceApi()
    api.quota_failures_remaining = 99
    provider, _ = _provider(api, create_retries=2)
    autoscaler = Autoscaler(
        _config(), provider,
        lambda method, *a: _stub_gcs_state()
        if method == "get_cluster_resource_state" else None,
    )
    r1 = autoscaler.update()  # must not raise
    assert r1["launched"] == []
    api.quota_failures_remaining = 0
    r2 = autoscaler.update()
    assert len(r2["launched"]) == 1


def test_reconciler_does_not_double_launch_while_pending():
    """A PENDING (still provisioning) slice counts against demand — the
    reconciler must not stack a second launch on the same unmet demand."""
    api = MockGceApi()
    api.provision_after_polls = 100  # never becomes ACTIVE in this test
    provider, _ = _provider(api)
    autoscaler = Autoscaler(
        _config(), provider,
        lambda method, *a: _stub_gcs_state()
        if method == "get_cluster_resource_state" else None,
    )
    r1 = autoscaler.update()
    assert len(r1["launched"]) == 1
    r2 = autoscaler.update()
    assert r2["launched"] == []
    assert len(provider.non_terminated_nodes()) == 1
