"""Object-store-budget backpressure for the data executor (reference:
resource_manager.py:47 + resource_budget_backpressure_policy.py).
Separate module: needs its own small-arena cluster."""

import ray_tpu
import ray_tpu.data as rd


def test_store_budget_backpressure(shutdown_only):
    """A wide map over blocks totaling ~4x the arena completes with peak
    usage bounded by the store budget: admission pauses while completed
    blocks wait for the consumer instead of forcing eviction of pinned
    blocks (reference: resource_manager.py:47 +
    resource_budget_backpressure_policy.py)."""
    import numpy as np

    from ray_tpu import _worker_api
    from ray_tpu.data.executor import DataContext

    node = ray_tpu.init(num_cpus=4, object_store_memory=32 * 1024 * 1024)
    ctx = DataContext.get_current()
    old_fraction = ctx.store_memory_fraction
    ctx.store_memory_fraction = 0.5
    try:
        # 32 blocks x ~4 MB = 128 MB through a 32 MB arena
        ds = rd.range_tensor(32, shape=(1024, 1024), parallelism=32)
        ds = ds.map_batches(lambda b: {"data": b["data"] * 2})
        peak = 0
        total_rows = 0
        for batch in ds.iter_batches(batch_size=None):
            total_rows += len(batch["data"])
            stats = node.raylet.store.stats()
            peak = max(peak, stats["used"])
        assert total_rows == 32
        # bounded well under the arena: the budget held admission back
        capacity = node.raylet.store.stats()["capacity"]
        assert peak <= capacity, (peak, capacity)
        assert peak <= 0.9 * capacity, f"budget did not bound peak: {peak}"
    finally:
        ctx.store_memory_fraction = old_fraction
