"""Chaos tests: workloads complete correctly under random worker kills and
RPC failure injection (reference: the chaos suites driven by
_private/test_utils killers and RAY_testing_rpc_failure), and the elastic
training plane recovers from deterministic rank kills."""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.testing import WorkerKiller


def test_tasks_survive_worker_killer(shutdown_only):
    node = ray_tpu.init(num_cpus=4)

    @ray_tpu.remote(max_retries=5)
    def work(i):
        time.sleep(0.2)
        return i * i

    with WorkerKiller([node], interval_s=0.4, max_kills=3, busy_only=True) as k:
        refs = [work.remote(i) for i in range(24)]
        out = ray_tpu.get(refs, timeout=180)
    assert out == [i * i for i in range(24)]
    # the killer must actually have done damage for this test to mean much
    assert len(k.kills) >= 1


def test_actor_survives_worker_killer(shutdown_only):
    node = ray_tpu.init(num_cpus=4)

    @ray_tpu.remote(max_restarts=10, max_task_retries=10)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            time.sleep(0.1)
            return self.n

    c = Counter.remote()
    # warm up first: the chaos window targets steady-state calls, not the
    # creation lease (that path is test_actor_restart's job)
    assert ray_tpu.get(c.incr.remote(), timeout=120) == 1
    with WorkerKiller([node], interval_s=0.5, max_kills=2, busy_only=True):
        # sequential increments; restarts reset state, so just require
        # every call to eventually succeed (reference: restart semantics
        # lose actor state unless checkpointed). Generous timeout: restarts
        # under load (1-core box) take seconds each.
        values = [ray_tpu.get(c.incr.remote(), timeout=120) for _ in range(20)]
    assert len(values) == 20
    assert all(v >= 1 for v in values)


def test_rpc_chaos_injection(shutdown_only):
    """Deterministic RPC failure injection (reference: rpc_chaos.h /
    RAY_testing_rpc_failure): submission paths retry through injected
    faults."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "testing_rpc_failure": '{"get_object": 0.2}'
        },
    )

    @ray_tpu.remote
    def consume(xs):
        return sum(xs)

    # a by-reference argument forces the worker onto the owner's get_object
    # path — the method the chaos spec injects failures into
    big = ray_tpu.put(list(range(200_000)))  # > inline threshold
    for _ in range(5):
        assert ray_tpu.get(consume.remote(big), timeout=120) == sum(
            range(200_000)
        )


@pytest.mark.slow
def test_tasks_survive_node_removal():
    """Tasks scheduled onto a node that dies are retried on survivors
    (reference: chaos node-kill suites)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.testing import NodeKiller

    cluster = Cluster(head_node_args={"num_cpus": 2})
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2)
        cluster.connect()

        @ray_tpu.remote(max_retries=5, num_cpus=1)
        def work(i):
            time.sleep(0.3)
            return i + 1000

        with NodeKiller(cluster, interval_s=1.0, max_kills=1) as killer:
            refs = [work.remote(i) for i in range(18)]
            out = ray_tpu.get(refs, timeout=240)
        assert out == [i + 1000 for i in range(18)]
        assert len(killer.killed) == 1
    finally:
        try:
            ray_tpu.shutdown()
        finally:
            cluster.shutdown()


def test_actor_task_rpc_chaos_exactly_once(shutdown_only):
    """Injected actor_task RPC failures (dropped before execution) are
    retried with their ORIGINAL sequence number: every call executes exactly
    once, in order, with no ordered-queue deadlock (reference: seq-no dedup
    in the actor scheduling queue)."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={"testing_rpc_failure": '{"actor_task": 0.3}'},
    )

    @ray_tpu.remote(max_task_retries=50)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    values = [ray_tpu.get(c.incr.remote(), timeout=60) for _ in range(30)]
    # strict: no skips (deadlock), no double-execution (duplicate applies)
    assert values == list(range(1, 31))


# ---------------------------------------------------------------------------
# Collective abort plane + elastic training (ISSUE 6)
# ---------------------------------------------------------------------------


def _make_member_cls():
    @ray_tpu.remote(max_restarts=0)
    class Member:
        def join(self, world_size, rank, group):
            from ray_tpu import collective

            collective.init_collective_group(
                world_size, rank, backend="gcs", group_name=group
            )
            return os.getpid()

        def reduce(self, group):
            import numpy as np

            from ray_tpu import collective
            from ray_tpu.exceptions import CollectiveAbortedError

            t0 = time.perf_counter()
            try:
                out = collective.allreduce(np.ones(4), group_name=group)
                return ("ok", float(out[0]), time.perf_counter() - t0)
            except CollectiveAbortedError:
                return ("aborted", 0.0, time.perf_counter() - t0)

    return Member


def test_collective_abort_unblocks_survivors(shutdown_only):
    """A rank SIGKILLed mid-allreduce unblocks the surviving ranks with
    CollectiveAbortedError within 5 s of the death (not the 120 s rendezvous
    timeout): raylet connection-loss -> GCS report_worker_death -> colabort
    key -> survivors' poll loops."""
    ray_tpu.init(num_cpus=4)
    Member = _make_member_cls()
    members = [Member.remote() for _ in range(3)]
    pids = ray_tpu.get(
        [m.join.remote(3, r, "abrt") for r, m in enumerate(members)],
        timeout=60,
    )
    # ranks 0 and 1 enter the allreduce; rank 2 never contributes
    refs = [members[0].reduce.remote("abrt"), members[1].reduce.remote("abrt")]
    time.sleep(0.5)  # let the survivors block in the rendezvous poll
    os.kill(pids[2], signal.SIGKILL)
    t_kill = time.perf_counter()
    out = ray_tpu.get(refs, timeout=30)
    unblocked_in = time.perf_counter() - t_kill
    assert [o[0] for o in out] == ["aborted", "aborted"]
    assert unblocked_in < 5.0, f"survivors took {unblocked_in:.1f}s to abort"
    # the group stays poisoned: later ops fail fast instead of hanging
    again = ray_tpu.get(members[0].reduce.remote("abrt"), timeout=30)
    assert again[0] == "aborted"
    assert again[2] < 1.0


def test_abort_collective_group_api(shutdown_only):
    """collective.abort_collective_group() (the `ray_tpu chaos abort-group`
    CLI path) unblocks members stuck in a rendezvous."""
    from ray_tpu import collective

    ray_tpu.init(num_cpus=4)
    Member = _make_member_cls()
    members = [Member.remote() for _ in range(2)]
    ray_tpu.get(
        [m.join.remote(3, r, "expl") for r, m in enumerate(members)],
        timeout=60,
    )
    refs = [m.reduce.remote("expl") for m in members]
    time.sleep(0.3)
    assert collective.abort_collective_group("expl", epoch=0, reason="test")
    out = ray_tpu.get(refs, timeout=30)
    assert [o[0] for o in out] == ["aborted", "aborted"]
    # monotonic: re-aborting the same epoch is a no-op
    assert not collective.abort_collective_group("expl", epoch=0)


def test_memory_monitor_death_report_aborts_group(shutdown_only):
    """A worker death reported through the GCS death RPC (the same path the
    memory-monitor recall kill lands on) aborts the dead rank's collective
    group."""
    import json

    from ray_tpu._internal.ids import WorkerID
    from ray_tpu.collective.cpu_group import _kv_call

    ray_tpu.init(num_cpus=4)
    Member = _make_member_cls()
    members = [Member.remote() for _ in range(3)]
    ray_tpu.get(
        [m.join.remote(3, r, "memmon") for r, m in enumerate(members)],
        timeout=60,
    )
    refs = [members[0].reduce.remote("memmon"), members[1].reduce.remote("memmon")]
    time.sleep(0.3)
    # look up rank 2's registered membership and report its death exactly
    # like the raylet memory monitor would
    raw = _kv_call("kv_get", "colmember:memmon:0:2")
    assert raw is not None, "rank 2 never registered its group membership"
    info = json.loads(bytes(raw).decode())
    t0 = time.perf_counter()
    _kv_call(
        "report_worker_death",
        WorkerID.from_hex(info["worker_id"]),
        "Task was killed due to the node running low on memory (recall)",
    )
    out = ray_tpu.get(refs, timeout=30)
    assert [o[0] for o in out] == ["aborted", "aborted"]
    assert time.perf_counter() - t0 < 5.0


def _elastic_train_loop(config):
    import time

    import numpy as np

    from ray_tpu import collective
    from ray_tpu import train as t

    ctx = t.get_context()
    state = t.restore_train_state()
    if state is None:
        step, params = 0, np.zeros(2)
    else:
        step = state["step"] + 1
        params = np.asarray(state["params"])
    while step < config["steps"]:
        # pace the loop so the controller-side chaos callback can land its
        # kill mid-run instead of after the whole loop already finished
        time.sleep(config.get("step_time", 0.0))
        # data-parallel "gradient": the allreduce hangs the survivors when a
        # rank dies, so every step exercises the abort plane
        grad = collective.allreduce(
            np.ones(2), group_name=ctx.collective_group
        )
        params = params + grad
        t.publish_train_state(params, step=step)
        t.report(
            {
                "step": step,
                "world_size": ctx.get_world_size(),
                "epoch": ctx.collective_epoch,
                "psum": float(np.sum(params)),
            }
        )
        step += 1


def test_elastic_resume_after_rank_kill(shutdown_only, tmp_path):
    """The headline elastic scenario: a 4-worker run loses rank 3 mid-step,
    the controller resizes to world_size=3 (no full respawn, no filesystem
    checkpoint), and training resumes from the weight plane with a
    continuous step count."""
    from ray_tpu import train as rt_train
    from ray_tpu.testing import KillWorkerAtStep
    from ray_tpu.util import metrics

    ray_tpu.init(num_cpus=8)
    os.environ["RAY_TPU_STORAGE_PATH"] = str(tmp_path / "results")
    try:
        killer = KillWorkerAtStep(rank=3, step=2)
        trainer = rt_train.JaxTrainer(
            _elastic_train_loop,
            train_loop_config={"steps": 6, "step_time": 0.3},
            scaling_config=rt_train.ScalingConfig(num_workers=4),
            run_config=rt_train.RunConfig(
                name="elastic-chaos",
                failure_config=rt_train.FailureConfig(
                    max_failures=0, elastic=True, min_workers=2
                ),
                callbacks=[killer],
            ),
        )
        resizes_before = metrics.train_ft_counters()["resizes"]
        result = trainer.fit()
    finally:
        os.environ.pop("RAY_TPU_STORAGE_PATH", None)

    assert result.error is None, f"elastic run failed: {result.error!r}"
    assert killer.kills and killer.kills[0]["rank"] == 3
    r0 = sorted(
        (e for e in result.metrics_history if e["_world_rank"] == 0),
        key=lambda e: e["step"],
    )
    steps = [e["step"] for e in r0]
    # continuous: every step 0..5 reported exactly once by rank 0 — the
    # weight-plane resume restarted at published step + 1, no gap, no replay
    assert steps == list(range(6)), f"step sequence broken: {steps}"
    sizes = [e["world_size"] for e in r0]
    assert sizes[0] == 4 and sizes[-1] == 3, f"world sizes: {sizes}"
    assert {4, 3} == set(sizes)
    # the re-formed gang runs at a bumped collective epoch
    assert r0[0]["epoch"] == 0 and r0[-1]["epoch"] >= 1
    # allreduce of ones sums the live world size: psum tracks 2*ws per step
    expected, total = [], 0.0
    for ws in sizes:
        total += 2.0 * ws
        expected.append(total)
    assert [e["psum"] for e in r0] == pytest.approx(expected)
    # the controller (this process) recorded the resize + recovery time
    assert metrics.train_ft_counters()["resizes"] >= resizes_before + 1
    pct = metrics.train_recovery_percentiles()
    assert pct["count"] >= 1 and pct["max_s"] > 0.0


def test_delay_collective_injection(shutdown_only):
    """`ray_tpu chaos delay-collective` backing path: a coldelay:<group> KV
    value makes every member op sleep that long at entry (TTL-cached)."""
    import numpy as np

    from ray_tpu.collective.cpu_group import GcsStoreGroup, _kv_call

    ray_tpu.init(num_cpus=2)
    _kv_call("kv_put", "coldelay:slowg", b"0.4", True)
    g = GcsStoreGroup(1, 0, "slowg", epoch=0)
    t0 = time.perf_counter()
    g.allreduce(np.ones(2))
    assert time.perf_counter() - t0 >= 0.4
    _kv_call("kv_del", "coldelay:slowg")
    g.destroy()
