"""Chaos tests: workloads complete correctly under random worker kills and
RPC failure injection (reference: the chaos suites driven by
_private/test_utils killers and RAY_testing_rpc_failure)."""

import time

import pytest

import ray_tpu
from ray_tpu.testing import WorkerKiller


def test_tasks_survive_worker_killer(shutdown_only):
    node = ray_tpu.init(num_cpus=4)

    @ray_tpu.remote(max_retries=5)
    def work(i):
        time.sleep(0.2)
        return i * i

    with WorkerKiller([node], interval_s=0.4, max_kills=3, busy_only=True) as k:
        refs = [work.remote(i) for i in range(24)]
        out = ray_tpu.get(refs, timeout=180)
    assert out == [i * i for i in range(24)]
    # the killer must actually have done damage for this test to mean much
    assert len(k.kills) >= 1


def test_actor_survives_worker_killer(shutdown_only):
    node = ray_tpu.init(num_cpus=4)

    @ray_tpu.remote(max_restarts=10, max_task_retries=10)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            time.sleep(0.1)
            return self.n

    c = Counter.remote()
    # warm up first: the chaos window targets steady-state calls, not the
    # creation lease (that path is test_actor_restart's job)
    assert ray_tpu.get(c.incr.remote(), timeout=120) == 1
    with WorkerKiller([node], interval_s=0.5, max_kills=2, busy_only=True):
        # sequential increments; restarts reset state, so just require
        # every call to eventually succeed (reference: restart semantics
        # lose actor state unless checkpointed). Generous timeout: restarts
        # under load (1-core box) take seconds each.
        values = [ray_tpu.get(c.incr.remote(), timeout=120) for _ in range(20)]
    assert len(values) == 20
    assert all(v >= 1 for v in values)


def test_rpc_chaos_injection(shutdown_only):
    """Deterministic RPC failure injection (reference: rpc_chaos.h /
    RAY_testing_rpc_failure): submission paths retry through injected
    faults."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "testing_rpc_failure": '{"get_object": 0.2}'
        },
    )

    @ray_tpu.remote
    def consume(xs):
        return sum(xs)

    # a by-reference argument forces the worker onto the owner's get_object
    # path — the method the chaos spec injects failures into
    big = ray_tpu.put(list(range(200_000)))  # > inline threshold
    for _ in range(5):
        assert ray_tpu.get(consume.remote(big), timeout=120) == sum(
            range(200_000)
        )


@pytest.mark.slow
def test_tasks_survive_node_removal():
    """Tasks scheduled onto a node that dies are retried on survivors
    (reference: chaos node-kill suites)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.testing import NodeKiller

    cluster = Cluster(head_node_args={"num_cpus": 2})
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2)
        cluster.connect()

        @ray_tpu.remote(max_retries=5, num_cpus=1)
        def work(i):
            time.sleep(0.3)
            return i + 1000

        with NodeKiller(cluster, interval_s=1.0, max_kills=1) as killer:
            refs = [work.remote(i) for i in range(18)]
            out = ray_tpu.get(refs, timeout=240)
        assert out == [i + 1000 for i in range(18)]
        assert len(killer.killed) == 1
    finally:
        try:
            ray_tpu.shutdown()
        finally:
            cluster.shutdown()


def test_actor_task_rpc_chaos_exactly_once(shutdown_only):
    """Injected actor_task RPC failures (dropped before execution) are
    retried with their ORIGINAL sequence number: every call executes exactly
    once, in order, with no ordered-queue deadlock (reference: seq-no dedup
    in the actor scheduling queue)."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={"testing_rpc_failure": '{"actor_task": 0.3}'},
    )

    @ray_tpu.remote(max_task_retries=50)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    values = [ray_tpu.get(c.incr.remote(), timeout=60) for _ in range(30)]
    # strict: no skips (deadlock), no double-execution (duplicate applies)
    assert values == list(range(1, 31))
