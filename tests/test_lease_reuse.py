"""Worker-lease reuse: warm same-class task streams amortize the lease
protocol down to one push RPC per task, idle leases expire back to the
raylet, and failed pushes invalidate the cache (reference: per-SchedulingKey
lease caching in normal_task_submitter.h + lease reclamation)."""

import time

import pytest

import ray_tpu
from ray_tpu import _worker_api
from ray_tpu.util import metrics


def _lease_rpcs():
    return metrics.rpc_calls_by_method().get("request_worker_lease", 0.0)


def test_same_class_tasks_reuse_one_lease(shutdown_only):
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def noop(i):
        return i

    assert ray_tpu.get(noop.remote(0)) == 0  # warm: acquires + caches
    before = _lease_rpcs()
    n = 30
    for i in range(n):
        assert ray_tpu.get(noop.remote(i)) == i
    # the whole warm stream reuses the one cached lease: at most one
    # re-acquire total (idle-TTL edge), never one per task
    assert _lease_rpcs() - before <= 1
    worker = _worker_api.get_core_worker()
    assert worker._lease_cache, "lease should be parked between tasks"


def test_distinct_scheduling_classes_get_distinct_leases(shutdown_only):
    ray_tpu.init(num_cpus=2, resources={"A": 1.0})

    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get(noop.remote())
    before = _lease_rpcs()
    # different resource shape -> different scheduling class -> new lease
    ray_tpu.get(noop.options(resources={"A": 1.0}).remote())
    assert _lease_rpcs() - before >= 1


def test_idle_ttl_expiry_returns_worker(shutdown_only):
    ray_tpu.init(
        num_cpus=2,
        _system_config={"worker_lease_idle_ttl_s": 0.2},
    )

    @ray_tpu.remote
    def noop():
        return 1

    assert ray_tpu.get(noop.remote()) == 1
    worker = _worker_api.get_core_worker()
    assert worker._lease_cache  # parked right after the task
    raylet = _worker_api.get_node().raylet
    deadline = time.time() + 10
    while time.time() < deadline:
        if not worker._lease_cache and not raylet._leases:
            break
        time.sleep(0.05)
    assert not worker._lease_cache, "idle lease should expire after the TTL"
    assert not raylet._leases, "raylet should get the worker back on expiry"


def test_pressure_revokes_cached_lease(shutdown_only):
    """A queued request of a different scheduling class recalls an idle
    cached lease holding the capacity it needs, well before the idle TTL."""
    ray_tpu.init(
        num_cpus=1,
        _system_config={"worker_lease_idle_ttl_s": 30.0},
    )

    @ray_tpu.remote
    def noop():
        return 1

    assert ray_tpu.get(noop.remote()) == 1  # CPU:1 lease now cached
    worker = _worker_api.get_core_worker()
    assert worker._lease_cache
    # different class (CPU:0.5): needs the CPU the cached lease holds
    t0 = time.time()
    assert ray_tpu.get(noop.options(num_cpus=0.5).remote(), timeout=60) == 1
    assert time.time() - t0 < 25, "revocation should beat the 30s idle TTL"


def test_chaos_on_push_task_invalidates_cached_lease(shutdown_only):
    """Injected push_task failures in the workers: the owner must drop the
    cached lease, re-acquire, and still run every task to completion."""
    import json

    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "testing_rpc_failure": json.dumps({"push_task": 0.3}),
        },
    )

    @ray_tpu.remote(max_retries=5)
    def noop(i):
        return i

    before = _lease_rpcs()
    n = 12
    out = ray_tpu.get([noop.remote(i) for i in range(n)], timeout=300)
    assert out == list(range(n))
    # sequential warm stream with failures mixed in
    for i in range(n):
        assert ray_tpu.get(noop.remote(i), timeout=300) == i
    # at least one re-acquire happened (a failed push returned the lease
    # as failed and the task took a fresh one)
    assert _lease_rpcs() - before >= 2
