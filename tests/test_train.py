"""ray_tpu.train tests: JaxTrainer end-to-end on a local cluster.

Models the reference's Train v2 test strategy (train/v2/tests/): real worker
actors on an in-process cluster, small MLP train loops, checkpoint/resume
and failure-policy behavior asserted through the public API.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train as rt_train


@pytest.fixture
def train_cluster(tmp_path):
    ray_tpu.init(num_cpus=8, resources={"TPU": 8})
    os.environ["RAY_TPU_STORAGE_PATH"] = str(tmp_path / "results")
    yield tmp_path
    os.environ.pop("RAY_TPU_STORAGE_PATH", None)
    ray_tpu.shutdown()


def _mlp_train_loop(config):
    """Tiny jax MLP regression loop reporting loss each epoch."""
    import jax
    import jax.numpy as jnp

    ctx = rt_train.get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    key = jax.random.PRNGKey(0)
    w1 = jax.random.normal(key, (4, 16)) * 0.1
    w2 = jax.random.normal(jax.random.PRNGKey(1), (16, 1)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(2 + rank), (64, 4))
    y = (x.sum(axis=1, keepdims=True) > 0).astype(jnp.float32)

    def loss_fn(params, x, y):
        w1, w2 = params
        h = jax.nn.relu(x @ w1)
        p = h @ w2
        return jnp.mean((p - y) ** 2)

    @jax.jit
    def step(params, x, y):
        loss, g = jax.value_and_grad(loss_fn)(params, x, y)
        return [p - 0.1 * gp for p, gp in zip(params, g)], loss

    params = [w1, w2]
    for epoch in range(config["epochs"]):
        params, loss = step(params, x, y)
        rt_train.report({"loss": float(loss), "epoch": epoch, "rank": rank})


def test_jax_trainer_basic(train_cluster):
    trainer = rt_train.JaxTrainer(
        _mlp_train_loop,
        train_loop_config={"epochs": 3},
        scaling_config=rt_train.ScalingConfig(num_workers=2),
        run_config=rt_train.RunConfig(name="basic"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["epoch"] == 2
    # both ranks reported each epoch
    ranks = {m["rank"] for m in result.metrics_history}
    assert ranks == {0, 1}
    losses = [m["loss"] for m in result.metrics_history if m["rank"] == 0]
    assert losses[-1] < losses[0]


def test_context_ranks_and_collective(train_cluster):
    def loop(config):
        ctx = rt_train.get_context()
        got = rt_train.collective.broadcast_from_rank_zero(
            {"value": ctx.get_world_rank() * 10 + 7}
        )
        rt_train.collective.barrier()
        ranks = rt_train.collective.allgather(ctx.get_world_rank())
        rt_train.report(
            {
                "rank": ctx.get_world_rank(),
                "world_size": ctx.get_world_size(),
                "bcast": got["value"],
                "ranks": sorted(ranks),
            }
        )

    result = rt_train.DataParallelTrainer(
        loop,
        scaling_config=rt_train.ScalingConfig(num_workers=3),
        run_config=rt_train.RunConfig(name="ctx"),
    ).fit()
    assert result.error is None
    by_rank = {m["rank"]: m for m in result.metrics_history}
    assert set(by_rank) == {0, 1, 2}
    for m in by_rank.values():
        assert m["world_size"] == 3
        assert m["bcast"] == 7  # rank 0's value everywhere
        assert m["ranks"] == [0, 1, 2]


def _ckpt_train_loop(config):
    import json
    import tempfile

    ctx = rt_train.get_context()
    start = 0
    ckpt = rt_train.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            with open(os.path.join(d, "state.json")) as f:
                start = json.load(f)["epoch"] + 1
    for epoch in range(start, config["epochs"]):
        if config.get("fail_at") == epoch and ctx.get_world_rank() == 0:
            # only fail on the first attempt
            marker = os.path.join(ctx.get_storage_path(), "failed_once")
            if not os.path.exists(marker):
                open(marker, "w").close()
                raise RuntimeError("injected failure")
        if ctx.get_world_rank() == 0:
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "state.json"), "w") as f:
                    json.dump({"epoch": epoch}, f)
                rt_train.report(
                    {"epoch": epoch},
                    checkpoint=rt_train.Checkpoint.from_directory(d),
                )
        else:
            rt_train.report({"epoch": epoch})


def test_checkpoint_and_top_k_retention(train_cluster):
    result = rt_train.JaxTrainer(
        _ckpt_train_loop,
        train_loop_config={"epochs": 5},
        scaling_config=rt_train.ScalingConfig(num_workers=1),
        run_config=rt_train.RunConfig(
            name="ckpt",
            checkpoint_config=rt_train.CheckpointConfig(num_to_keep=2),
        ),
    ).fit()
    assert result.error is None
    assert result.checkpoint is not None
    run_dir = result.path
    kept = sorted(
        d
        for d in os.listdir(run_dir)
        if d.startswith("checkpoint_") and os.path.isdir(os.path.join(run_dir, d))
    )
    assert len(kept) == 2
    assert result.checkpoint.path.endswith("checkpoint_000004")


def test_failure_policy_restart_resumes_from_checkpoint(train_cluster):
    result = rt_train.JaxTrainer(
        _ckpt_train_loop,
        train_loop_config={"epochs": 6, "fail_at": 3},
        scaling_config=rt_train.ScalingConfig(num_workers=1),
        run_config=rt_train.RunConfig(
            name="resume",
            failure_config=rt_train.FailureConfig(max_failures=1),
        ),
    ).fit()
    assert result.error is None
    assert result.metrics["epoch"] == 5
    # epochs 0-2 ran before the failure; after restart the loop resumed at 3,
    # so epoch 2 appears exactly once in history
    epochs = [m["epoch"] for m in result.metrics_history]
    assert epochs.count(2) == 1


def test_failure_policy_exhausted(train_cluster):
    def always_fail(config):
        raise ValueError("boom")

    result = rt_train.JaxTrainer(
        always_fail,
        scaling_config=rt_train.ScalingConfig(num_workers=1),
        run_config=rt_train.RunConfig(
            name="fail", failure_config=rt_train.FailureConfig(max_failures=1)
        ),
    ).fit()
    assert result.error is not None


def test_torch_trainer_ddp(train_cluster):
    def loop(config):
        import torch
        import torch.distributed as dist

        ctx = rt_train.get_context()
        t = torch.ones(2) * (ctx.get_world_rank() + 1)
        dist.all_reduce(t)
        rt_train.report({"sum": float(t[0]), "rank": ctx.get_world_rank()})

    result = rt_train.TorchTrainer(
        loop,
        scaling_config=rt_train.ScalingConfig(num_workers=2),
        run_config=rt_train.RunConfig(name="torch"),
    ).fit()
    assert result.error is None
    assert result.metrics["sum"] == 3.0  # 1 + 2


def test_dataset_shard_list(train_cluster):
    def loop(config):
        ctx = rt_train.get_context()
        shard = rt_train.get_dataset_shard("train")
        rt_train.report({"rank": ctx.get_world_rank(), "n": len(list(shard))})

    result = rt_train.DataParallelTrainer(
        loop,
        scaling_config=rt_train.ScalingConfig(num_workers=2),
        run_config=rt_train.RunConfig(name="ds"),
        datasets={"train": list(range(10))},
    ).fit()
    assert result.error is None
    total = sum(m["n"] for m in result.metrics_history)
    assert total == 10


def _world_size_probe(config):
    ctx = rt_train.get_context()
    rt_train.report({"world": ctx.get_world_size(), "rank": ctx.get_world_rank()})


@pytest.mark.slow
def test_elastic_scaling_shrinks_to_cluster(train_cluster):
    """num_workers=(min,max): the gang sizes itself to what the cluster can
    schedule (cluster has 8 CPUs; max 32 can never fit)."""
    trainer = rt_train.JaxTrainer(
        _world_size_probe,
        scaling_config=rt_train.ScalingConfig(num_workers=(1, 32)),
        run_config=rt_train.RunConfig(name="elastic-test"),
    )
    result = trainer.fit()
    assert result.error is None
    world = result.metrics["world"]
    assert 1 <= world < 32
    # every rank of the shrunk gang actually ran
    ranks = {m["rank"] for m in result.metrics_history}
    assert ranks == set(range(world))


def test_elastic_scaling_policy_units():
    from ray_tpu.train.scaling_policy import (
        ElasticScalingPolicy,
        FixedScalingPolicy,
        make_scaling_policy,
    )

    fixed = make_scaling_policy(rt_train.ScalingConfig(num_workers=3))
    assert isinstance(fixed, FixedScalingPolicy)
    assert fixed.decide(0).num_workers == 3

    elastic = make_scaling_policy(rt_train.ScalingConfig(num_workers=(2, 6)))
    assert isinstance(elastic, ElasticScalingPolicy)
    assert elastic.min_workers == 2 and elastic.max_workers == 6
    with pytest.raises(ValueError):
        ElasticScalingPolicy(rt_train.ScalingConfig(num_workers=1), 3, 2)


def test_lora_split_merge_and_frozen_base():
    """train/lora.py: split/merge roundtrip; grads exist only for adapter
    leaves; an optimizer step leaves the frozen base bit-identical
    (BASELINE.json config 3 — LoRA-only optimizer state)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import LlamaConfig, init_params, next_token_loss
    from ray_tpu.parallel.sharding import unbox_params
    from ray_tpu.train.lora import lora_label_fn, merge_lora, split_lora

    cfg = LlamaConfig.tiny(lora_rank=4)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    base, lora = split_lora(params)
    assert lora, "tiny(lora_rank=4) must produce adapter leaves"
    assert all(k[-1] in ("lora_a", "lora_b") for k in lora)
    merged = merge_lora(base, lora)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        merged,
    )

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)

    def loss_fn(lp):
        return next_token_loss(cfg, None, merge_lora(base, lp), tokens)

    grads = jax.grad(loss_fn)(lora)
    # lora_b initializes to zero, so lora_a grads vanish at step 0 — but
    # lora_b grads must be live (nonzero) for the adapters to train
    b_norm = sum(
        float(jnp.abs(g).sum()) for k, g in grads.items() if k[-1] == "lora_b"
    )
    assert b_norm > 0.0

    opt = optax.adamw(1e-2)
    opt_state = opt.init(lora)
    # optimizer state exists ONLY for adapter leaves (the point of the split)
    n_moment_leaves = len(jax.tree.leaves(opt_state[0].mu))
    assert n_moment_leaves == len(jax.tree.leaves(lora))
    updates, _ = opt.update(grads, opt_state, lora)
    lora2 = optax.apply_updates(lora, updates)
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(lora), jax.tree.leaves(lora2))
    )
    assert changed
    # frozen base stays bit-identical through the step: it was never handed
    # to the optimizer, and the merged tree still contains the originals
    base_after, _ = split_lora(merge_lora(base, lora2))
    assert set(base_after) == set(base)
    for k in base:
        np.testing.assert_array_equal(np.asarray(base[k]), np.asarray(base_after[k]))

    labels = lora_label_fn(params)
    from flax import traverse_util

    flat_labels = traverse_util.flatten_dict(labels)
    assert {v for v in flat_labels.values()} == {"lora", "frozen"}
    assert all(
        (v == "lora") == (k[-1] in ("lora_a", "lora_b"))
        for k, v in flat_labels.items()
    )


def test_failure_config_elastic_fields():
    fc = rt_train.FailureConfig()
    assert fc.elastic is False
    assert fc.min_workers == 1
    fc2 = rt_train.FailureConfig(max_failures=2, elastic=True, min_workers=3)
    assert fc2.elastic and fc2.min_workers == 3
    with pytest.raises(ValueError):
        rt_train.FailureConfig(min_workers=0)
    # RESIZING is a first-class run state, distinct from gang RESTARTING
    assert rt_train.RunState.RESIZING.value == "RESIZING"
    assert rt_train.RunState.RESIZING is not rt_train.RunState.RESTARTING


def test_worker_group_rank_reassignment_units():
    """_assign_ranks re-ranks survivors stably after removals: world ranks
    stay dense 0..n-1 and preserve the (node, arrival) order."""
    from ray_tpu.train.worker_group import WorkerGroup

    pairs = [
        (f"actor{i}", {"node_id": f"node{i % 2}", "pid": 100 + i, "hostname": "h"})
        for i in range(4)
    ]
    infos = WorkerGroup._assign_ranks(pairs)
    assert [w.world_rank for w in infos] == [0, 1, 2, 3]
    # drop one survivor's pair: ranks collapse to 0..2, order preserved
    survivors = [(w.actor, w.metadata) for w in infos if w.world_rank != 1]
    rebuilt = WorkerGroup._assign_ranks(survivors)
    assert [w.world_rank for w in rebuilt] == [0, 1, 2]
    assert [w.actor for w in rebuilt] == [w.actor for w in infos if w.world_rank != 1]
