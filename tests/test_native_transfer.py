"""Tests for the C++ node-to-node transfer plane (reference model:
src/ray/object_manager/ ObjectManager push/pull tests)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu._internal.ids import ObjectID
from ray_tpu._native.lib import load


@pytest.fixture
def two_stores():
    from ray_tpu.runtime.object_store.native_store import NativeObjectStore

    lib = load()
    assert lib is not None, "native store must build in this environment"
    a = NativeObjectStore(1 << 20, f"ta{os.getpid()}", lib)
    b = NativeObjectStore(1 << 20, f"tb{os.getpid()}", lib)
    yield a, b
    a.shutdown()
    b.shutdown()


def test_transfer_roundtrip(two_stores):
    src, dst = two_stores
    port = src.transfer_serve(token="secret")
    assert port and port > 0
    oid = ObjectID.from_random()
    payload = np.random.default_rng(0).bytes(200_000)
    src.create_and_write(oid, payload)

    rc, off, size = dst.transfer_fetch_raw(oid, "127.0.0.1", port, "secret")
    assert rc == 0
    assert size == len(payload)
    dst.adopt_fetched(oid, off, size)
    assert dst.contains(oid)
    assert bytes(dst.read_local(oid)) == payload


def test_transfer_missing_object(two_stores):
    src, dst = two_stores
    port = src.transfer_serve()
    rc, _, _ = dst.transfer_fetch_raw(
        ObjectID.from_random(), "127.0.0.1", port, ""
    )
    assert rc == -2


def test_transfer_auth_rejected(two_stores):
    src, dst = two_stores
    port = src.transfer_serve(token="right")
    oid = ObjectID.from_random()
    src.create_and_write(oid, b"x" * 100)
    rc, _, _ = dst.transfer_fetch_raw(oid, "127.0.0.1", port, "wrong")
    assert rc == -5
    assert not dst.contains(oid)


def test_transfer_already_present(two_stores):
    src, dst = two_stores
    port = src.transfer_serve()
    oid = ObjectID.from_random()
    src.create_and_write(oid, b"y" * 50)
    dst.create_and_write(oid, b"y" * 50)
    rc, _, _ = dst.transfer_fetch_raw(oid, "127.0.0.1", port, "")
    assert rc == -4


def test_transfer_empty_object(two_stores):
    src, dst = two_stores
    port = src.transfer_serve()
    oid = ObjectID.from_random()
    src.create_and_write(oid, b"")
    rc, off, size = dst.transfer_fetch_raw(oid, "127.0.0.1", port, "")
    assert rc == 0
    assert size == 0
    dst.adopt_fetched(oid, off, size)
    assert dst.contains(oid)


def test_transfer_peer_down(two_stores):
    _, dst = two_stores
    # nothing listens on this port
    rc, _, _ = dst.transfer_fetch_raw(
        ObjectID.from_random(), "127.0.0.1", 1, ""
    )
    assert rc == -1


def test_cross_node_pull_uses_native_plane():
    """Cluster-level: a cross-node object pull goes through the C++ TCP
    stream (native_pulls counter increments) and the payload is intact."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(head_node_args=dict(num_cpus=1))
    cluster.add_node(num_cpus=1)
    cluster.connect()
    try:
        nodes = ray_tpu.nodes()
        assert len(nodes) == 2

        @ray_tpu.remote(num_cpus=0)
        def produce():
            return np.full((400, 400), 3.0)

        @ray_tpu.remote(num_cpus=0)
        def consume(arr):
            return float(arr.sum())

        ref = produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=nodes[0]["NodeID"]
            )
        ).remote()
        out = ray_tpu.get(
            consume.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=nodes[1]["NodeID"]
                )
            ).remote(ref),
            timeout=120,
        )
        assert out == 3.0 * 400 * 400
        pulls = [n.raylet._native_pulls for n in cluster.list_nodes()]
        assert sum(pulls) >= 1, (
            f"expected at least one native pull, got {pulls}"
        )
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
