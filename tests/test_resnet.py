"""ResNet model + DataParallelTrainer example (BASELINE.json configs[1])."""

import jax
import jax.numpy as jnp
import pytest


def test_resnet_forward_and_bn_stats_update():
    from ray_tpu.models.resnet import (
        ResNetConfig, apply_train, init_train_state,
    )

    cfg = ResNetConfig.tiny()
    params, stats = init_train_state(cfg, jax.random.PRNGKey(0), image_size=32)
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits, new_stats = apply_train(cfg, params, stats, images)
    assert logits.shape == (2, cfg.num_classes)
    assert jnp.all(jnp.isfinite(logits))
    # batch stats moved
    changed = jax.tree_util.tree_map(
        lambda a, b: bool(jnp.any(a != b)), stats, new_stats
    )
    assert any(jax.tree_util.tree_leaves(changed))


def test_resnet50_param_count():
    """Sanity: the full config builds the canonical ~25.6M-param network."""
    from ray_tpu.models.resnet import ResNetConfig, ResNet

    cfg = ResNetConfig.resnet50()
    shapes = jax.eval_shape(
        lambda r: ResNet(cfg).init(
            r, jnp.zeros((1, 224, 224, 3), jnp.float32), train=False
        ),
        jax.random.PRNGKey(0),
    )
    n = sum(
        int(jnp.prod(jnp.array(l.shape)))
        for l in jax.tree_util.tree_leaves(shapes["params"])
    )
    assert 25_400_000 < n < 25_800_000, n


@pytest.mark.slow  # 10s: DP trainer loop; forward/bn + param-count stay tier-1
def test_resnet_data_parallel_trainer(cluster):
    from ray_tpu.train.examples.resnet import make_trainer

    result = make_trainer(
        num_workers=1,
        train_config={
            "model": "tiny", "epochs": 2, "steps_per_epoch": 2,
            "batch_per_worker": 4, "image_size": 32, "lr": 0.05,
        },
    ).fit()
    assert result.error is None
    assert result.metrics["epoch"] == 1
    assert all(m["loss"] == m["loss"] for m in result.metrics_history)
