"""Borrower reference-counting protocol (reference: reference_counter.h:44 —
borrower registration on deserialize, ref-removed reporting, nested-ref
containment; the owner defers frees while borrowers hold the ref, WITHOUT
relying on lineage reconstruction as a backstop)."""

import gc
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def borrower_cluster():
    ray_tpu.init(
        num_cpus=4,
        resources={"TPU": 4},
        _system_config={"borrower_probe_interval_s": 0.5},
    )
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Holder:
    """Stashes a borrowed ref in actor state; reads it later."""

    def __init__(self):
        self.ref = None

    def stash(self, container):
        self.ref = container[0]
        return True

    def read(self):
        return ray_tpu.get(self.ref, timeout=30)

    def drop(self):
        self.ref = None
        gc.collect()
        return True


def test_borrowed_put_object_survives_owner_drop(borrower_cluster):
    """The core contract: a plasma object created by ray_tpu.put (NO lineage
    — puts cannot be reconstructed) stays alive while a borrower actor holds
    a deserialized ref, even after the owner drops every local reference."""
    h = Holder.remote()
    arr = np.arange(300_000, dtype=np.float32)  # > inline threshold -> plasma
    ref = ray_tpu.put(arr)
    assert ray_tpu.get(h.stash.remote([ref]), timeout=60) is True

    # drop the owner's only local reference and let the free machinery run
    del ref
    gc.collect()
    time.sleep(1.0)

    # the borrower must still be able to read it; without the protocol the
    # owner freed the object at del (puts have no lineage to rebuild from)
    out = ray_tpu.get(h.read.remote(), timeout=60)
    np.testing.assert_array_equal(out, arr)

    # once the borrower gracefully drops too, the deferred free happens —
    # via the unregister RPC, well before any probe interval
    from ray_tpu import _worker_api

    oid = None
    worker = _worker_api.get_core_worker()
    with worker._ref_lock:
        candidates = [o for o in worker._owned if worker._borrowers.get(o)]
    assert len(candidates) == 1, candidates
    oid = candidates[0]
    assert ray_tpu.get(h.drop.remote(), timeout=30) is True
    deadline = time.time() + 15
    freed = False
    while time.time() < deadline:
        with worker._ref_lock:
            freed = oid not in worker._owned
        if freed:
            break
        time.sleep(0.25)
    assert freed, "object leaked after the last borrower unregistered"


def test_no_reconstruction_while_borrower_holds(borrower_cluster):
    """With lineage present, survival must come from the borrower protocol,
    not silent re-execution: the producing task runs exactly once."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def value(self):
            return self.n

    counter = Counter.remote()

    @ray_tpu.remote(max_retries=2)
    def produce(counter):
        ray_tpu.get(counter.incr.remote(), timeout=30)
        return np.full((200_000,), 7, np.float32)  # plasma-sized

    h = Holder.remote()
    ref = produce.remote(counter)
    np.testing.assert_array_equal(
        ray_tpu.get(ref, timeout=60), np.full((200_000,), 7, np.float32)
    )
    assert ray_tpu.get(h.stash.remote([ref]), timeout=60) is True

    del ref
    gc.collect()
    time.sleep(1.0)

    out = ray_tpu.get(h.read.remote(), timeout=60)
    assert float(out[0]) == 7.0
    # exactly one execution: object came from the preserved copy, not lineage
    assert ray_tpu.get(counter.value.remote(), timeout=30) == 1


@pytest.mark.slow
def test_dead_borrower_cannot_pin_forever(borrower_cluster):
    """Chaos variant: the owner's liveness probe prunes a crashed borrower,
    so the deferred free eventually happens instead of leaking the object."""
    from ray_tpu import _worker_api

    h = Holder.remote()
    ref = ray_tpu.put(np.zeros(300_000, np.float32))
    oid = ref.id
    assert ray_tpu.get(h.stash.remote([ref]), timeout=60) is True

    # kill the borrower outright (no graceful unregister)
    ray_tpu.kill(h)
    time.sleep(0.5)

    del ref
    gc.collect()

    worker = _worker_api.get_core_worker()
    # pruning needs 3 CONSECUTIVE failed probes (deliberately conservative —
    # one transient miss must not free a live borrower's object) and each
    # probe to a dead address can take up to the rpc connect timeout
    deadline = time.time() + 60
    while time.time() < deadline:
        with worker._ref_lock:
            freed = oid not in worker._owned
        if freed:
            break
        time.sleep(0.5)
    assert freed, "dead borrower pinned the object past the probe interval"


def test_nested_ref_pinned_in_flight(borrower_cluster):
    """Nested-ref containment: a ref inside a container arg is pinned for
    the task's flight even if the caller drops its handle immediately after
    submission (top-level args were already pinned; this covers nesting)."""

    @ray_tpu.remote
    def slow_read(container):
        time.sleep(1.0)  # widen the window: owner could free during this
        return float(ray_tpu.get(container[0], timeout=30)[0])

    ref = ray_tpu.put(np.full((200_000,), 3.5, np.float32))
    fut = slow_read.remote([ref])
    del ref  # owner's only handle gone while the task is still in flight
    gc.collect()
    assert ray_tpu.get(fut, timeout=60) == 3.5
