"""multiprocessing Pool shim, joblib backend, serializability inspector
(reference: ray.util.multiprocessing, ray.util.joblib, util/check_serialize)."""

import threading

import pytest


def _square(x):
    return x * x


def _add(a, b):
    return a + b


_init_flag = {"v": 0}


def _initializer(v):
    _init_flag["v"] = v


class TestPool:
    def test_map_and_apply(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        with Pool(processes=2) as p:
            assert p.map(_square, range(10)) == [x * x for x in range(10)]
            assert p.apply(_add, (2, 3)) == 5

    def test_async_and_starmap(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        with Pool(processes=2) as p:
            r = p.apply_async(_add, (1, 2))
            assert r.get(timeout=30) == 3
            assert r.successful()
            assert p.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]
            mr = p.map_async(_square, [1, 2, 3])
            assert mr.get(timeout=30) == [1, 4, 9]

    def test_imap(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        with Pool(processes=2) as p:
            assert list(p.imap(_square, range(6), chunksize=2)) == [
                x * x for x in range(6)
            ]
            assert sorted(p.imap_unordered(_square, range(6))) == sorted(
                x * x for x in range(6)
            )

    def test_initializer_and_close(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        p = Pool(processes=1, initializer=_initializer, initargs=(7,))
        p.close()
        p.join()
        with pytest.raises(ValueError):
            p.map(_square, [1])

    def test_error_propagates(self, ray_start_regular):
        from ray_tpu.util.multiprocessing import Pool

        def boom(x):
            raise RuntimeError("nope")

        with Pool(processes=1) as p:
            with pytest.raises(Exception):
                p.map(boom, [1])


class TestJoblib:
    def test_parallel_backend(self, ray_start_regular):
        import joblib

        from ray_tpu.util.joblib import register_ray

        register_ray()
        with joblib.parallel_backend("ray", n_jobs=2):
            out = joblib.Parallel()(joblib.delayed(_square)(i) for i in range(8))
        assert out == [i * i for i in range(8)]


class TestCheckSerialize:
    def test_ok(self):
        from ray_tpu.util import inspect_serializability

        ok, failures = inspect_serializability(_square)
        assert ok and not failures

    def test_finds_bad_closure(self):
        from ray_tpu.util import inspect_serializability

        lock = threading.Lock()

        def captures_lock():
            return lock

        ok, failures = inspect_serializability(captures_lock)
        assert not ok
        assert any("lock" in f.name for f in failures)

    def test_finds_bad_attribute(self):
        from ray_tpu.util import inspect_serializability

        class Holder:
            pass

        h = Holder()
        h.fine = 3
        h.bad = threading.Lock()
        ok, failures = inspect_serializability(h, name="holder")
        assert not ok
        assert any("bad" in f.name for f in failures)

    def test_imap_lazy_over_infinite_generator(self, ray_start_regular):
        import itertools

        from ray_tpu.util.multiprocessing import Pool

        with Pool(processes=2) as p:
            gen = (i for i in itertools.count())  # infinite
            out = list(itertools.islice(p.imap(_square, gen, chunksize=1), 5))
            assert out == [0, 1, 4, 9, 16]
