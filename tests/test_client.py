"""ray:// client mode (reference: Ray Client, python/ray/util/client/ and
ray_client.proto): the client process attaches through the client server
without joining the cluster."""

import os
import subprocess
import sys
import textwrap

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CLIENT_SCRIPT = textwrap.dedent(
    """
    import sys
    sys.path.insert(0, %(repo)r)
    import ray_tpu

    ray_tpu.init(address="ray://127.0.0.1:%(port)d")

    # objects
    ref = ray_tpu.put({"k": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"k": [1, 2, 3]}

    # tasks (with a by-reference arg)
    @ray_tpu.remote
    def add(a, b):
        return a + b

    big = ray_tpu.put(40)
    out = ray_tpu.get(add.remote(big, 2), timeout=60)
    assert out == 42, out

    # ready/not-ready split
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=10)
    assert len(ready) == 1 and not not_ready

    # actors
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(5), timeout=60) == 6
    ray_tpu.kill(c)

    # cluster introspection goes through the proxy
    nodes = ray_tpu.nodes()
    assert len(nodes) == 1 and nodes[0]["Alive"]
    res = ray_tpu.cluster_resources()
    assert res.get("CPU", 0) >= 1

    # streaming generators proxy stream reads through the client server
    # (tasks and actor methods; items pin server-side for this session)
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    assert [ray_tpu.get(r, timeout=60) for r in gen.remote(4)] == [0, 10, 20, 30]

    @ray_tpu.remote
    class Gen:
        def squares(self, n):
            for i in range(n):
                yield i * i

    gactor = Gen.remote()
    g = gactor.squares.options(num_returns="streaming").remote(4)
    assert [ray_tpu.get(r, timeout=60) for r in g] == [0, 1, 4, 9]

    ray_tpu.shutdown()
    print("CLIENT_OK")
    """
)


def test_client_mode_end_to_end(shutdown_only):
    node = ray_tpu.init(
        num_cpus=4, _system_config={"client_server_port": 0}
    )
    assert node.client_server is not None
    port = node.client_server.address[1]
    script = CLIENT_SCRIPT % {"repo": REPO, "port": port}
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "RAY_TPU_JAX_PLATFORM": "cpu"},
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "CLIENT_OK" in proc.stdout


def test_client_server_survives_client_exit(shutdown_only):
    """A second client can attach after the first disconnects."""
    node = ray_tpu.init(
        num_cpus=4, _system_config={"client_server_port": 0}
    )
    port = node.client_server.address[1]
    quick = textwrap.dedent(
        """
        import sys
        sys.path.insert(0, %(repo)r)
        import ray_tpu
        ray_tpu.init(address="ray://127.0.0.1:%(port)d")
        assert ray_tpu.get(ray_tpu.put(11)) == 11
        ray_tpu.shutdown()
        print("OK")
        """
    ) % {"repo": REPO, "port": port}
    for _ in range(2):
        proc = subprocess.run(
            [sys.executable, "-c", quick],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "RAY_TPU_JAX_PLATFORM": "cpu"},
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "OK" in proc.stdout
