"""Mesh, sharding rules, ring attention, sharded model parity — on the
virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.models.llama import Llama, LlamaConfig, init_params, next_token_loss
from ray_tpu.ops.flash_attention import reference_attention
from ray_tpu.parallel.mesh import MeshSpec, make_mesh, mesh_axis_size
from ray_tpu.parallel.ring_attention import ring_attention
from ray_tpu.parallel.sharding import logical_to_spec, param_shardings, unbox_params
from ray_tpu._internal.jax_compat import shard_map

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def test_mesh_spec_resolution():
    spec = MeshSpec(dp=2, fsdp=-1, tp=2)
    sizes = spec.resolved_sizes(8)
    assert sizes == {
        "dcn": 1, "pp": 1, "dp": 2, "fsdp": 2, "ep": 1, "sp": 1, "tp": 2,
    }
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolved_sizes(8)


def test_make_mesh_and_axis_sizes():
    mesh = make_mesh(dp=1, fsdp=2, sp=2, tp=2)
    assert mesh_axis_size(mesh, "fsdp") == 2
    assert mesh_axis_size(mesh, "tp") == 2


def test_logical_to_spec():
    assert logical_to_spec(("batch", "embed")) == P(("dcn", "dp", "fsdp"), "fsdp")
    assert logical_to_spec((None, "mlp")) == P(None, "tp")


def test_ring_attention_matches_reference():
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    b, h, s, d = 2, 2, 256, 32
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (b, h, s, d), jnp.float32)
        for i in range(3)
    )
    ring = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None),
            check_vma=False,
        )
    )
    out = ring(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    assert float(jnp.abs(out - ref).max()) < 2e-2


def test_ring_attention_grads_match():
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    b, h, s, d = 1, 2, 256, 32
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (b, h, s, d), jnp.float32)
        for i in range(3)
    )
    ring = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None),
            check_vma=False,
        )
    )
    g1 = jax.grad(lambda q, k, v: (ring(q, k, v) ** 2).sum(), argnums=(0, 1, 2))(
        q, k, v
    )
    g2 = jax.grad(
        lambda q, k, v: (reference_attention(q, k, v, causal=True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g1, g2):
        rel = float(jnp.abs(a - b_).max()) / (float(jnp.abs(b_).max()) + 1e-9)
        assert rel < 2e-2, rel


@pytest.mark.slow
def test_llama_sharded_matches_single_device():
    cfg = LlamaConfig.tiny()
    mesh = make_mesh(dp=1, fsdp=2, sp=2, tp=2)
    boxed = init_params(cfg, jax.random.PRNGKey(0))
    raw = unbox_params(boxed)
    shardings = param_shardings(mesh, boxed)
    sharded = jax.jit(lambda p: p, out_shardings=shardings)(raw)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 128), 0, cfg.vocab_size)
    loss_sharded = jax.jit(lambda p, t: next_token_loss(cfg, mesh, p, t))(
        sharded, tokens
    )
    loss_single = jax.jit(lambda p, t: next_token_loss(cfg, None, p, t))(raw, tokens)
    assert abs(float(loss_sharded) - float(loss_single)) < 2e-2


@pytest.mark.slow
def test_llama_lora_params_exist():
    cfg = LlamaConfig.tiny(lora_rank=4)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    attn = params["layer_0"]["attn"]["wq"]
    assert "lora_a" in attn and "lora_b" in attn
    assert attn["lora_a"].shape == (cfg.dim, 4)
    # lora_b starts at zero: output identical to base model
    base = unbox_params(init_params(LlamaConfig.tiny(), jax.random.PRNGKey(0)))
    tokens = jnp.zeros((1, 16), jnp.int32)
    out_lora = Llama(cfg, None).apply({"params": params}, tokens)
    out_base = Llama(LlamaConfig.tiny(), None).apply({"params": base}, tokens)
    assert float(jnp.abs(out_lora - out_base).max()) < 1e-3


@pytest.mark.slow
def test_graft_entry_dryrun():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "graft",
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "__graft_entry__.py"),
    )
    g = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(g)
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
    g.dryrun_multichip(8)
