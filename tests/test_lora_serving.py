"""Multi-tenant LoRA serving plane (PR 20).

The correctness bar mirrors the other engine-loop features: adapters must
be invisible except in the math. Temperature-0 parity pins the
batched-gather path — a mixed-adapter batch (several tenants + base rows
in ONE jitted step) must emit token-for-token what each tenant gets when
served alone, with the SAME prompt across tenants so the adapter-salted
KV prefix keys are exercised (an unsalted trie would reuse tenant A's
K/V for tenant B). Store tests pin the lease lifecycle (refcount, LRU
evict, backpressure-as-None, rollback); the weight-plane test pins the
publish -> evict -> refill round-trip; the no-stall test pins the
threading claim — a cold attach on a request thread never gaps an
in-flight decode.

Engines are module-scoped where possible: jit programs compile once per
engine instance and per decode width, the dominant cost of this file.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.kvcache import KVCacheManager
from ray_tpu.llm import GenerationRequest, LLMConfig
from ray_tpu.llm.config import AdapterConfig
from ray_tpu.llm.engine import ContinuousBatchingEngine
from ray_tpu.lora import AdapterStore, adapter_target_paths, publish_adapter
from ray_tpu.models.llama import Llama, LlamaConfig, init_params
from ray_tpu.parallel.sharding import unbox_params

RANK = 4


def _adapter_tree(cfg, seed, rank=RANK, scale=0.5):
    """A random nonzero adapter in train/lora.py leaf naming. ``scale``
    is large on purpose: the delta must actually move tiny-model argmaxes
    so per-tenant trajectories diverge from base."""
    rng = np.random.RandomState(seed)
    tree = {}
    for path, in_dim, out_dim in adapter_target_paths(cfg):
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = {
            "lora_a": jnp.asarray(
                rng.normal(0.0, scale, (in_dim, rank)), jnp.float32
            ),
            "lora_b": jnp.asarray(
                rng.normal(0.0, scale, (rank, out_dim)), jnp.float32
            ),
        }
    return tree


@pytest.fixture(scope="module")
def tiny():
    """f32 compute end to end: gather-vs-per-weight parity is then exact,
    not epsilon-close."""
    cfg = LlamaConfig.tiny(max_seq_len=128, dtype=jnp.float32)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    return cfg, params


# -- batched-gather math -----------------------------------------------------


class TestGatherParity:
    def test_gather_matches_per_weight_lora(self, tiny):
        """The same adapter through both code paths — per-weight LoRADense
        params (the train-time path, scales alpha/rank at compute) vs the
        slot bank gather (pre-scaled at attach) — must agree on logits."""
        from flax import traverse_util

        cfg, _ = tiny
        tree = _adapter_tree(cfg, seed=42)
        cfg_l = LlamaConfig.tiny(
            max_seq_len=128, dtype=jnp.float32,
            lora_rank=RANK, lora_alpha=16.0,
        )
        flat = traverse_util.flatten_dict(
            unbox_params(init_params(cfg_l, jax.random.PRNGKey(0)))
        )
        tree_flat = traverse_util.flatten_dict(tree)
        for k in list(flat):
            if k[-1] in ("lora_a", "lora_b"):
                flat[k] = tree_flat[k]
        params_l = traverse_util.unflatten_dict(flat)
        base_params = traverse_util.unflatten_dict({
            k: v for k, v in flat.items()
            if k[-1] not in ("lora_a", "lora_b")
        })

        store = AdapterStore(
            cfg, max_live=2, rank=RANK, alpha=16.0,
            param_dtype=jnp.float32,
        )
        lease = store.acquire("t", tree=tree)
        tokens = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)
        ref = Llama(cfg_l, None).apply({"params": params_l}, tokens)
        got = Llama(cfg, None).apply(
            {"params": base_params}, tokens,
            store.bank(), jnp.asarray([lease.slot], jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_slot_minus_one_is_base_path(self, tiny):
        """Row slot = -1 (no adapter) must equal the base model exactly
        even with a live bank passed in: the mask zeroes the delta, it
        does not gather garbage."""
        cfg, params = tiny
        store = AdapterStore(cfg, max_live=2, rank=RANK,
                             param_dtype=jnp.float32)
        lease = store.acquire("t", tree=_adapter_tree(cfg, seed=7))
        tokens = jnp.asarray([[9, 8, 7, 6]], jnp.int32)
        model = Llama(cfg, None)
        base = model.apply({"params": params}, tokens)
        masked = model.apply(
            {"params": params}, tokens,
            store.bank(), jnp.asarray([-1], jnp.int32),
        )
        tinted = model.apply(
            {"params": params}, tokens,
            store.bank(), jnp.asarray([lease.slot], jnp.int32),
        )
        np.testing.assert_allclose(np.asarray(masked), np.asarray(base))
        assert not np.allclose(np.asarray(tinted), np.asarray(base))


# -- store lifecycle ---------------------------------------------------------


class TestStoreLifecycle:
    def test_lru_evict_backpressure_and_refill_counts(self, tiny):
        cfg, _ = tiny
        calls = []
        trees = {f"t{i}": _adapter_tree(cfg, i) for i in range(3)}

        def source(aid):
            calls.append(aid)
            return trees[aid]

        store = AdapterStore(cfg, max_live=2, rank=RANK, source=source,
                             param_dtype=jnp.float32)
        l0 = store.acquire("t0")
        l1 = store.acquire("t1")
        # every slot pinned -> None (backpressure), never an eviction of
        # an in-flight adapter
        assert store.acquire("t2") is None
        store.release(l0)
        l2 = store.acquire("t2")  # evicts idle t0, keeps pinned t1
        assert store.evictions == 1
        assert sorted(store.stats()["resident"]) == ["t1", "t2"]
        # resident hit: no refetch, same slot
        l1b = store.acquire("t1")
        assert store.hits == 1 and l1b.slot == l1.slot
        store.release(l1)
        store.release(l1b)
        store.release(l2)
        # t0 was evicted: acquiring it again is a second cold attach
        l0b = store.acquire("t0")
        assert calls == ["t0", "t1", "t2", "t0"]
        assert store.cold_attaches == 4
        # release is idempotent
        store.release(l0b)
        store.release(l0b)
        assert store.stats()["slots_pinned"] == 0

    def test_failed_refill_rolls_back_slot(self, tiny):
        cfg, _ = tiny

        def boom(aid):
            raise RuntimeError("registry down")

        store = AdapterStore(cfg, max_live=1, rank=RANK, source=boom,
                             param_dtype=jnp.float32)
        with pytest.raises(RuntimeError, match="registry down"):
            store.acquire("x")
        # the slot returned to the free list: the store is not leaked empty
        assert store.stats()["slots_free"] == 1
        store.prewarm("y", _adapter_tree(cfg, 5))
        assert store.stats()["resident"] == ["y"]

    def test_rank_mismatch_rejected(self, tiny):
        cfg, _ = tiny
        store = AdapterStore(cfg, max_live=1, rank=8,
                             param_dtype=jnp.float32)
        with pytest.raises(ValueError, match="slot_rank"):
            store.acquire("t", tree=_adapter_tree(cfg, 0, rank=4))

    def test_publish_requires_lora_leaves(self):
        with pytest.raises(ValueError, match="lora_a"):
            publish_adapter("t/x", "bad", {"w": jnp.zeros((2, 2))})


# -- mixed-adapter batches on the paged engine -------------------------------


PROMPT = [3, 14, 15, 9, 2, 6, 5]  # ONE length: prefill compiles are per length
TENANTS = ["tenant_a", "tenant_b", "tenant_c"]


@pytest.fixture(scope="module")
def lora_engine(tiny):
    cfg, params = tiny
    trees = {t: _adapter_tree(cfg, 10 + i) for i, t in enumerate(TENANTS)}
    store = AdapterStore(
        cfg, max_live=4, rank=RANK, source=trees.__getitem__,
        param_dtype=jnp.float32,
    )
    kv = KVCacheManager(num_blocks=64, block_size=8)
    eng = ContinuousBatchingEngine(
        cfg, params, num_slots=4, kv_cache=kv, seed=0,
        adapter_store=store,
    )
    return eng, store


def _run_one(eng, store, aid, n=8):
    lease = store.acquire(aid) if aid else None
    try:
        rid = eng.add_request(GenerationRequest(
            token_ids=PROMPT, max_new_tokens=n, temperature=0.0,
            adapter_id=aid, adapter_slot=lease.slot if lease else -1,
        ))
        return eng.run_until_complete()[rid].token_ids
    finally:
        store.release(lease)


class TestMixedBatch:
    def test_mixed_batch_matches_sequential(self, lora_engine):
        """3 tenants + 1 base row decode CONCURRENTLY as one gather batch,
        all on the SAME prompt (so only the adapter-salted KV keys keep
        their prefixes apart) — and each row must equal its solo run."""
        eng, store = lora_engine
        leases = {t: store.acquire(t) for t in TENANTS}
        rids = {}
        for t in TENANTS:
            rids[t] = eng.add_request(GenerationRequest(
                token_ids=PROMPT, max_new_tokens=8, temperature=0.0,
                adapter_id=t, adapter_slot=leases[t].slot,
            ))
        rids[None] = eng.add_request(GenerationRequest(
            token_ids=PROMPT, max_new_tokens=8, temperature=0.0,
        ))
        mixed = eng.run_until_complete()
        for lease in leases.values():
            store.release(lease)

        solo = {aid: _run_one(eng, store, aid) for aid in TENANTS + [None]}
        for aid, rid in rids.items():
            assert mixed[rid].token_ids == solo[aid], f"row {aid} diverged"
        # the adapters actually did something: tenants differ from base
        # (random deltas at scale 0.5 move tiny-model argmaxes)
        assert any(solo[t] != solo[None] for t in TENANTS)

    def test_resident_tenant_is_a_hit(self, lora_engine):
        eng, store = lora_engine
        before = store.stats()
        out1 = _run_one(eng, store, TENANTS[0])
        out2 = _run_one(eng, store, TENANTS[0])
        after = store.stats()
        assert out1 == out2  # temp-0 determinism across runs
        assert after["cold_attaches"] == before["cold_attaches"]
        assert after["hits"] >= before["hits"] + 2


def test_cold_attach_does_not_stall_decodes(tiny):
    """The threading claim: a cold adapter's pull + slot write run on the
    caller's thread (serve: the replica request thread) — while it is in
    flight, an engine stepping on another thread emits one token EVERY
    step, no gaps."""
    import time

    cfg, params = tiny

    def slow_source(aid):
        time.sleep(0.3)  # a weight-plane pull's worth of latency
        return _adapter_tree(cfg, 99)

    store = AdapterStore(cfg, max_live=2, rank=RANK, source=slow_source,
                         param_dtype=jnp.float32)
    eng = ContinuousBatchingEngine(
        cfg, params, num_slots=2,
        kv_cache=KVCacheManager(num_blocks=64, block_size=8), seed=0,
        adapter_store=store,
    )
    rid = eng.add_request(GenerationRequest(
        token_ids=PROMPT, max_new_tokens=100, temperature=0.0,
    ))
    eng.step()  # admit + first token (pays the compiles up front)
    slot = next(iter(eng._slots.values()))
    assert slot.request_id == rid

    got = []
    t = threading.Thread(target=lambda: got.append(store.acquire("cold")))
    t.start()
    overlapped = 0
    while t.is_alive() and len(slot.generated) < 95:
        before = len(slot.generated)
        eng.step()
        assert len(slot.generated) == before + 1, "decode gapped"
        overlapped += 1
    t.join()
    assert overlapped >= 2  # the attach window really overlapped stepping
    assert got and got[0] is not None
    store.release(got[0])
    eng.run_until_complete()


# -- tp=2 sharded slot bank --------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs >=2 (host) devices")
def test_tp2_bank_shards_alongside_base_weights(tiny):
    """Under a PartitionPlan the bank is born sharded: lora_b rows of
    wq/wk/wv split on the output (head) dim like their base kernels, wo's
    lora_a splits on the input dim, and the slot axis stays replicated.
    A slot write must preserve the layout and the row values."""
    from ray_tpu.parallel.plan import PartitionPlan

    cfg, _ = tiny
    plan = PartitionPlan.for_model(cfg, 2)
    store = AdapterStore(cfg, max_live=2, rank=RANK, alpha=16.0,
                         plan=plan, param_dtype=jnp.float32)
    tree = _adapter_tree(cfg, 3)
    lease = store.acquire("t", tree=tree)
    bank = store.bank()
    h = cfg.n_heads * cfg.head_dim
    wq = bank["layer_0"]["attn"]["wq"]
    wo = bank["layer_0"]["attn"]["wo"]
    assert wq["lora_b"].addressable_shards[0].data.shape == \
        (store.num_slots, RANK, h // 2)
    assert wq["lora_a"].addressable_shards[0].data.shape == \
        (store.num_slots, cfg.dim, RANK)  # replicated
    assert wo["lora_a"].addressable_shards[0].data.shape == \
        (store.num_slots, h // 2, RANK)
    np.testing.assert_allclose(
        np.asarray(wq["lora_a"][lease.slot]),
        np.asarray(tree["layer_0"]["attn"]["wq"]["lora_a"]),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(wq["lora_b"][lease.slot]),
        np.asarray(tree["layer_0"]["attn"]["wq"]["lora_b"]) * (16.0 / RANK),
        rtol=1e-6,
    )
    store.release(lease)


# -- weight-plane refill round trip ------------------------------------------


def test_weight_plane_publish_evict_refill(ray_start_regular, tiny):
    """publish_adapter -> cold attach -> LRU evict -> re-attach pulls the
    SAME bytes back off the weight plane (raw codec: exact; int8 codec:
    within quantization error)."""
    cfg, _ = tiny
    t0 = _adapter_tree(cfg, 0)
    t1 = _adapter_tree(cfg, 1)
    publish_adapter("t/adapters", "a0", t0, quantized=False)
    publish_adapter("t/adapters", "a1", t1, quantized=False)
    store = AdapterStore(
        cfg, max_live=1, rank=RANK, alpha=16.0,
        source="weights:t/adapters", param_dtype=jnp.float32,
    )

    def row(leaf, slot):
        node = store.bank()["layer_0"]["attn"]["wq"][leaf]
        return np.asarray(node[slot])

    expect_a0 = np.asarray(t0["layer_0"]["attn"]["wq"]["lora_a"])
    l0 = store.acquire("a0")
    np.testing.assert_allclose(row("lora_a", l0.slot), expect_a0, rtol=1e-6)
    store.release(l0)

    l1 = store.acquire("a1")  # max_live=1: evicts a0
    assert store.evictions == 1
    np.testing.assert_allclose(
        row("lora_b", l1.slot),
        np.asarray(t1["layer_0"]["attn"]["wq"]["lora_b"]) * (16.0 / RANK),
        rtol=1e-6,
    )
    store.release(l1)

    l0b = store.acquire("a0")  # the refill round trip
    assert store.cold_attaches == 3
    np.testing.assert_allclose(row("lora_a", l0b.slot), expect_a0, rtol=1e-6)
    store.release(l0b)

    # int8 publish (the default): quarter the bytes, still attaches close
    publish_adapter("t/adapters", "q0", t0)
    lq = store.acquire("q0")
    np.testing.assert_allclose(
        row("lora_a", lq.slot), expect_a0, rtol=0.05, atol=0.05
    )
    store.release(lq)


# -- serving + batch integration ---------------------------------------------


def test_serve_multiplexed_adapters_on_paged_engine(ray_start_regular):
    """The full plane through serve: AdapterConfig on a paged deployment,
    tenants named via multiplexed model-id AND the explicit adapter_id
    field, concurrent mixed-tenant requests, per-tenant determinism, and
    adapter stats off the replica."""
    from ray_tpu import serve
    from ray_tpu.llm.serving import build_llm_deployment

    llm_config = LLMConfig(
        model_id="llama-tiny",
        max_seq_len=64,
        max_new_tokens=4,
        kv_cache_blocks=32,
        kv_block_size=8,
        resources_per_replica={"CPU": 1.0},
        adapters=AdapterConfig(
            max_live=2, slot_rank=RANK, source="weights:t/lora"
        ),
    )
    mcfg = llm_config.build_model_config()
    publish_adapter("t/lora", "m1", _adapter_tree(mcfg, 1), quantized=False)
    publish_adapter("t/lora", "m2", _adapter_tree(mcfg, 2), quantized=False)

    app = build_llm_deployment(llm_config)
    serve.start(proxy=False)
    handle = serve.run(app, name="llm-lora", route_prefix=None, _proxy=False)
    try:
        body = {"token_ids": [1, 2, 3, 4], "max_new_tokens": 3,
                "temperature": 0.0}
        base = handle.remote(dict(body)).result(timeout_s=180)
        assert len(base["token_ids"]) == 3

        # concurrent mixed-tenant requests: 2 tenants x 2 requests in
        # flight at once against ONE replica's gather batch
        futs = [
            handle.options(
                multiplexed_model_id=f"m{1 + i % 2}"
            ).remote(dict(body))
            for i in range(4)
        ]
        outs = [f.result(timeout_s=180) for f in futs]
        assert outs[0]["token_ids"] == outs[2]["token_ids"]  # m1 == m1
        assert outs[1]["token_ids"] == outs[3]["token_ids"]  # m2 == m2

        # explicit adapter_id field is the same tenant identity
        explicit = handle.remote(
            dict(body, adapter_id="m1")
        ).result(timeout_s=180)
        assert explicit["token_ids"] == outs[0]["token_ids"]

        stats = handle.adapters_stats.remote().result(timeout_s=60)
        assert stats["cold_attaches"] == 2  # m1 + m2, once each
        assert stats["hits"] >= 3
        assert sorted(stats["resident"]) == ["m1", "m2"]
        assert stats["slots_pinned"] == 0  # every lease released
    finally:
        serve.shutdown()


def test_batch_predictor_per_row_adapters(tiny):
    """LLMPredictor multiplexes per-row adapter_id columns through one
    engine: rows for different tenants (and None rows on the base path)
    share a batch, and leases release after the batch."""
    from ray_tpu.llm.batch import LLMPredictor

    cfg, _ = tiny
    trees = {"u1": _adapter_tree(cfg, 21), "u2": _adapter_tree(cfg, 22)}
    llm_config = LLMConfig(
        model_id="llama-tiny",
        max_seq_len=64,
        max_new_tokens=3,
        kv_cache_blocks=32,
        adapters=AdapterConfig(
            max_live=2, slot_rank=RANK, source=trees.__getitem__
        ),
    )
    pred = LLMPredictor(llm_config)
    out = pred({
        "token_ids": [[1, 2, 3], [1, 2, 3], [1, 2, 3], [1, 2, 3]],
        "adapter_id": ["u1", "u2", None, "u1"],
    })
    assert all(len(g) == 3 for g in out["generated"])
    assert out["generated"][0] == out["generated"][3]  # same tenant
    stats = pred._adapter_store.stats()
    assert stats["slots_pinned"] == 0
    assert stats["cold_attaches"] == 2

    # a second batch for resident tenants is all hits
    pred({"token_ids": [[1, 2, 3]], "adapter_id": ["u2"]})
    assert pred._adapter_store.stats()["cold_attaches"] == 2
