"""Ulysses SP, expert parallelism, pipeline parallelism, MoE model — on the
virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.models.moe import MoEConfig, next_token_loss
from ray_tpu.models.moe import init_params as moe_init_params
from ray_tpu.ops.flash_attention import reference_attention
from ray_tpu.parallel.expert import (
    expert_capacity,
    moe_apply_gspmd,
    moe_combine,
    moe_dispatch,
    top_k_gating,
)
from ray_tpu.parallel.mesh import make_mesh
from ray_tpu.parallel.pipeline import pipeline_apply, select_stage_params
from ray_tpu.parallel.sharding import param_shardings, unbox_params
from ray_tpu.parallel.ulysses import ulysses_attention
from ray_tpu._internal.jax_compat import shard_map

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def test_ulysses_matches_reference():
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    b, h, s, d = 2, 4, 128, 16
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (b, h, s, d), jnp.float32)
        for i in range(3)
    )
    spec = P(None, None, "sp", None)
    out = jax.jit(
        shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


def test_ulysses_gqa():
    devs = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("sp",))
    b, h, hk, s, d = 1, 4, 2, 64, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, hk, s, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, hk, s, d), jnp.float32)
    spec = P(None, None, "sp", None)
    out = jax.jit(
        shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


class TestExpertParallel:
    def test_gating_respects_capacity(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (32, 4))
        cap = 4
        dispatch, combine, aux = top_k_gating(logits, cap, k=2)
        # no expert slot is used twice
        per_slot = np.asarray(dispatch).sum(axis=0)  # (E, C)
        assert per_slot.max() <= 1.0 + 1e-6
        # combine weights normalized per token (for non-dropped tokens)
        w = np.asarray(combine).sum(axis=(1, 2))
        assert np.all((np.abs(w - 1.0) < 1e-5) | (w < 1e-6))
        assert float(aux) > 0

    def test_gspmd_apply_identity_experts(self):
        t, d, e = 16, 8, 4
        x = jax.random.normal(jax.random.PRNGKey(0), (t, d))
        logits = jax.random.normal(jax.random.PRNGKey(1), (t, e))
        cap = expert_capacity(t, e, capacity_factor=2.0, k=1)
        dispatch, combine, _ = top_k_gating(logits, cap, k=1)
        out = moe_apply_gspmd(x, dispatch, combine, lambda inp: inp)
        # identity experts + top-1 routing with ample capacity => y == x
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)

    def test_shard_map_dispatch_matches_gspmd(self):
        devs = np.array(jax.devices()[:4]).reshape(4)
        mesh = Mesh(devs, ("ep",))
        t, d, e = 32, 8, 4  # 8 tokens per rank
        x = jax.random.normal(jax.random.PRNGKey(0), (t, d))
        logits = jax.random.normal(jax.random.PRNGKey(1), (t, e))
        cap = expert_capacity(t // 4, e, capacity_factor=2.0, k=1)

        w = jax.random.normal(jax.random.PRNGKey(2), (e, d, d)) * 0.1

        def local(x_local, w_full):
            lg = x_local @ jax.random.normal(jax.random.PRNGKey(1), (d, e)) * 0
            # deterministic local routing from the global logits is awkward
            # inside shard_map; recompute from x to keep shards independent
            lg = x_local[:, :e]
            dispatch, combine, _ = top_k_gating(lg, cap, k=1)
            slabs = moe_dispatch(x_local, dispatch, axis_name="ep")  # (E_l, n*C, d)
            me = jax.lax.axis_index("ep")
            w_local = jax.lax.dynamic_index_in_dim(w_full, me, 0, keepdims=False)
            y = slabs @ w_local  # this rank's single expert
            return moe_combine(y, combine, axis_name="ep")

        sharded = jax.jit(
            shard_map(
                local,
                mesh=mesh,
                in_specs=(P("ep", None), P(None, None, None)),
                out_specs=P("ep", None),
                check_vma=False,
            )
        )(x, w)

        # single-device reference with identical routing
        outs = []
        for r in range(4):
            xl = x[r * 8:(r + 1) * 8]
            lg = xl[:, :e]
            dispatch, combine, _ = top_k_gating(lg, cap, k=1)
            y = moe_apply_gspmd(
                xl, dispatch, combine,
                lambda inp: jnp.einsum("ecd,edf->ecf", inp, w),
            )
            outs.append(y)
        ref = jnp.concatenate(outs, axis=0)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(ref), atol=1e-4
        )


def test_pipeline_apply_4_stages():
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("pp",))
    n_micro, mb = 6, 8
    xs = jax.random.normal(jax.random.PRNGKey(0), (n_micro, mb))
    stage_scales = jnp.array([2.0, 3.0, 5.0, 7.0])  # product 210

    def run(xs, scales):
        params = select_stage_params(scales, axis_name="pp")
        out = pipeline_apply(
            lambda p, x: x * p, params, xs, axis_name="pp"
        )
        # only the last rank holds real outputs (zeros elsewhere): psum home
        return jax.lax.psum(out, "pp")

    out = jax.jit(
        shard_map(
            run,
            mesh=mesh,
            in_specs=(P(), P()),
            out_specs=P(),
            check_vma=False,
        )
    )(xs, stage_scales)
    np.testing.assert_allclose(np.asarray(out), np.asarray(xs) * 210.0, rtol=1e-5)


@pytest.mark.slow
class TestMoEModel:
    def test_loss_and_grads_finite(self):
        cfg = MoEConfig.tiny()
        params = unbox_params(moe_init_params(cfg, jax.random.PRNGKey(0)))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(cfg, None, p, tokens)
        )(params)
        assert np.isfinite(float(loss))
        flat = jax.tree.leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
        # router gradients flow
        assert any(
            "router" in "/".join(map(str, path))
            and float(jnp.abs(leaf).sum()) > 0
            for path, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]
            for path in [tuple(getattr(p, "key", p) for p in path)]
        )

    def test_sharded_loss_matches_single_device(self):
        cfg = MoEConfig.tiny()
        boxed = moe_init_params(cfg, jax.random.PRNGKey(0))
        params = unbox_params(boxed)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 256)
        base = float(next_token_loss(cfg, None, params, tokens))

        mesh = make_mesh(8, fsdp=2, ep=2, tp=2)
        shardings = param_shardings(mesh, boxed)
        params_sharded = jax.device_put(params, shardings)
        with mesh:
            sharded = float(
                jax.jit(lambda p, t: next_token_loss(cfg, None, p, t))(
                    params_sharded, tokens
                )
            )
        assert abs(base - sharded) < 5e-2, (base, sharded)
