"""Quantized transport plane: int8 per-block codec properties, error
feedback, quantized collectives (GCS + XLA backends), the int8 weight-plane
chunk codec, and loss-curve parity of a quantized data-parallel train smoke
against the exact fp reference."""

import os

import jax
import numpy as np
import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu._internal.quantization import (
    DEFAULT_BLOCK,
    MIN_QUANT_BYTES,
    QuantizedArray,
    dequantize_np,
    ef_quantize_np,
    is_quantizable,
    quantize_np,
    quantized_wire_nbytes,
)

# -- codec properties (no cluster) -------------------------------------------


@pytest.mark.parametrize("block", [32, 128, 256])
def test_roundtrip_error_bound_per_block(block):
    """Per-element error is bounded by the block's scale/2 = max|block|/254:
    the bound tightens as blocks shrink around local dynamic range."""
    rng = np.random.default_rng(7)
    x = (rng.standard_normal(1000) * np.repeat(
        rng.uniform(0.1, 100.0, 8), 125
    )).astype(np.float32)
    qa = quantize_np(x, block=block)
    assert qa.block == block
    out = dequantize_np(qa)
    assert out.dtype == x.dtype and out.shape == x.shape
    n = x.size
    pad = (-n) % block
    padded = np.concatenate([x, np.zeros(pad, x.dtype)]).reshape(-1, block)
    bound = np.abs(padded).max(axis=1) / 254.0
    err = np.abs(padded - np.concatenate(
        [out, np.zeros(pad, x.dtype)]
    ).reshape(-1, block))
    assert (err <= bound[:, None] + 1e-7).all()


def test_roundtrip_bf16_and_f64():
    import ml_dtypes

    x16 = np.arange(64, dtype=ml_dtypes.bfloat16) / 7
    qa = quantize_np(x16)
    out = dequantize_np(qa)
    assert out.dtype == x16.dtype
    np.testing.assert_allclose(
        out.astype(np.float32), x16.astype(np.float32), rtol=0.02, atol=0.05
    )
    x64 = np.linspace(-3, 3, 77)
    np.testing.assert_allclose(dequantize_np(quantize_np(x64)), x64, atol=0.02)


def test_edge_cases_zero_constant_nonfinite_remainder():
    # all-zero: zero-scale guard, exact zeros back
    z = np.zeros(300, np.float32)
    np.testing.assert_array_equal(dequantize_np(quantize_np(z)), z)
    # constant block: c quantizes to exactly +/-127 * (|c|/127) = c
    c = np.full(300, -3.25, np.float32)
    np.testing.assert_array_equal(dequantize_np(quantize_np(c)), c)
    # NaN -> 0; +/-inf clips to the block's max finite magnitude
    x = np.array([np.nan, np.inf, -np.inf] + [1.0] * 61, np.float32)
    out = dequantize_np(quantize_np(x, block=64))
    assert out[0] == 0.0 and np.isfinite(out).all()
    assert out[1] == 1.0 and out[2] == -1.0
    np.testing.assert_allclose(out[3:], 1.0, atol=1e-6)
    # sub-block remainder: 300 % 256 != 0 pads internally, slices back
    r = np.random.default_rng(0).standard_normal(300).astype(np.float32)
    assert dequantize_np(quantize_np(r)).shape == (300,)


def test_quantizability_gate():
    assert not is_quantizable(np.ones(4, np.float32))  # 16 B < MIN_QUANT_BYTES
    assert is_quantizable(np.ones(MIN_QUANT_BYTES // 4, np.float32))
    assert not is_quantizable(np.arange(100, dtype=np.int64))
    assert not is_quantizable(np.array(1.0, np.float32))  # scalar too small


def test_wire_nbytes_formula():
    x = np.ones(4096, np.float32)
    qa = quantize_np(x)
    assert qa.wire_nbytes == quantized_wire_nbytes(x.size, DEFAULT_BLOCK)
    assert qa.logical_nbytes == x.nbytes
    # the halved-wire-bytes contract: f32 compresses ~3.9x, bf16 ~1.97x
    assert qa.wire_nbytes < x.nbytes / 2
    import ml_dtypes

    b = np.ones(4096, ml_dtypes.bfloat16)
    assert quantize_np(b).wire_nbytes < b.nbytes / 1.9


def test_np_jax_codec_agreement():
    from ray_tpu._internal.quantization import dequantize_jax, quantize_jax

    x = np.random.default_rng(3).standard_normal(512).astype(np.float32)
    qa = quantize_np(x, block=128)
    q_j, s_j = quantize_jax(x, 128)
    np.testing.assert_array_equal(np.asarray(q_j), qa.q)
    np.testing.assert_array_equal(np.asarray(s_j), qa.scales)
    import jax.numpy as jnp

    out = dequantize_jax(q_j, s_j, x.shape, jnp.float32)
    np.testing.assert_allclose(np.asarray(out), dequantize_np(qa), rtol=1e-6)


def test_error_feedback_beats_plain_quantization():
    """Accumulating many quantized SUM rounds with error feedback tracks the
    exact running sum far more closely than re-quantizing cold each round."""
    rng = np.random.default_rng(11)
    x = rng.standard_normal(2048).astype(np.float32)
    exact = np.zeros_like(x, np.float64)
    acc_plain = np.zeros_like(x, np.float64)
    acc_ef = np.zeros_like(x, np.float64)
    residual = None
    for _ in range(50):
        exact += x
        acc_plain += dequantize_np(quantize_np(x), dtype="float32")
        qa, residual = ef_quantize_np(x, residual)
        acc_ef += dequantize_np(qa, dtype="float32")
    norm = np.linalg.norm(exact)
    err_plain = np.linalg.norm(acc_plain - exact) / norm
    err_ef = np.linalg.norm(acc_ef - exact) / norm
    assert err_ef < err_plain / 5
    assert err_ef < 1e-3


# -- weight-plane chunk codec (no cluster) -----------------------------------


def test_int8_chunk_codec_roundtrip_and_accounting():
    from ray_tpu.weights.manifest import (
        CODEC_INT8,
        assemble_pytree,
        chunk_logical_bytes,
        chunk_pytree,
        leaf_wire_nbytes,
    )

    rng = np.random.default_rng(0)
    tree = {
        "w": rng.standard_normal((64, 64)).astype(np.float32),
        "tiny": rng.standard_normal(8).astype(np.float32),  # stays raw
        "step": np.int64(7),                                # stays raw
    }
    td, chunks, total = chunk_pytree(tree, 1 << 20, codec=CODEC_INT8)
    logical = sum(chunk_logical_bytes(c) for c in chunks)
    wire = sum(leaf_wire_nbytes(v) for c in chunks for v in c)
    assert total == logical == sum(np.asarray(v).nbytes for v in
                                   [tree["w"], tree["tiny"], tree["step"]])
    assert wire < logical / 2  # the halved-wire contract on f32 payloads
    assert any(isinstance(v, QuantizedArray) for c in chunks for v in c)
    out = assemble_pytree(td, chunks)
    np.testing.assert_allclose(out["w"], tree["w"], atol=0.02)
    np.testing.assert_array_equal(out["tiny"], tree["tiny"])  # raw = exact
    assert out["step"] == 7


def test_unknown_codec_rejected():
    from ray_tpu.weights.manifest import chunk_pytree

    with pytest.raises(ValueError, match="codec"):
        chunk_pytree({"a": np.ones(4)}, 1024, codec="zstd")


def test_pre_codec_manifest_defaults():
    """ChunkInfo/Manifest rows written by pre-codec publishers (no codec /
    logical_size fields) must keep reading as raw."""
    from ray_tpu._internal.ids import ObjectID
    from ray_tpu.weights.broadcast import (
        version_logical_bytes,
        version_wire_bytes,
    )
    from ray_tpu.weights.manifest import CODEC_RAW, ChunkInfo

    info = ChunkInfo(
        object_id=ObjectID.from_random(),
        owner_address=("n", 1),
        size=4000,
        num_leaves=2,
    )
    assert info.codec == CODEC_RAW and info.logical_size == 0
    assert version_wire_bytes([info]) == 4000
    assert version_logical_bytes([info]) == 4000  # falls back to packed size


# -- quantized collectives: GCS backend across actors ------------------------


def test_quantized_gcs_group_allreduce(cluster):
    @ray_tpu.remote
    class Member:
        def __init__(self, rank, world):
            import ray_tpu.collective as col

            self.group = col.init_collective_group(
                world, rank, backend="gcs", group_name="q1",
                quantized=True, quant_block=64,
            )
            self.rank = rank

        def do_allreduce(self, scale=1.0):
            x = (np.arange(4096, dtype=np.float32) % 97) * (self.rank + 1)
            return self.group.allreduce(x * scale)

        def do_allgather(self):
            return self.group.allgather(
                np.full(256, float(self.rank), np.float32)
            )

        def wire_stats(self):
            from ray_tpu.util import metrics

            return metrics.collective_summary()

    members = [Member.remote(r, 2) for r in range(2)]
    out = ray_tpu.get([m.do_allreduce.remote() for m in members], timeout=180)
    expect = (np.arange(4096, dtype=np.float32) % 97) * 3  # ranks 1x + 2x
    for arr in out:
        np.testing.assert_allclose(arr, expect, rtol=0.02, atol=2.0)
    gathered = ray_tpu.get(
        [m.do_allgather.remote() for m in members], timeout=180
    )
    for g in gathered:
        np.testing.assert_allclose(np.asarray(g[0]), 0.0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(g[1]), 1.0, atol=0.02)
    # wire accounting: the quantized ops moved well under half the logical
    # bytes (int8 + per-block f32 scales vs f32 payload)
    stats = ray_tpu.get([m.wire_stats.remote() for m in members], timeout=180)
    for s in stats:
        row = s["allreduce"]
        assert 0 < row["wire_bytes"] < row["bytes"] / 2


# -- quantized collectives: XLA backend on the device mesh -------------------


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_quantized_xla_group_collectives():
    from ray_tpu.collective import ReduceOp
    from ray_tpu.collective.xla_group import XlaGroup

    group = XlaGroup(
        1, 0, "xla-q", devices=jax.devices()[:4], quantized=True,
        quant_block=64,
    )
    x = (np.arange(4096, dtype=np.float32) % 31) - 15.0
    total = np.asarray(group.allreduce(x))
    np.testing.assert_allclose(total, x.reshape(4, 1024).sum(0), atol=1.0)
    gathered = np.asarray(group.allgather(x))
    np.testing.assert_allclose(gathered, x, atol=0.2)
    rs = np.asarray(group.reducescatter(x))
    np.testing.assert_allclose(rs, 4 * x, atol=0.5)
    # MIN/MAX never quantize (order statistics): results stay exact
    mn = np.asarray(group.allreduce(x, op=ReduceOp.MIN))
    np.testing.assert_array_equal(mn, x.reshape(4, 1024).min(0))
    # error feedback: the residual carries between calls, so accumulated
    # error over many rounds stays near a single round's instead of drifting
    rounds = 10
    acc = np.zeros(1024, np.float64)
    for _ in range(rounds):
        acc += np.asarray(group.allreduce(x), np.float64)
    exact = x.reshape(4, 1024).sum(0).astype(np.float64) * rounds
    rel = np.linalg.norm(acc - exact) / np.linalg.norm(exact)
    assert rel < 1e-2


# -- mixed fp + quantized manifests in one process ---------------------------


def test_mixed_codec_versions_same_model(cluster):
    from ray_tpu import weights
    from ray_tpu.weights import WeightPublisher, WeightSubscriber

    params = {"w": np.linspace(-2, 2, 100_000).astype(np.float32)}
    pub = WeightPublisher("q/mixed")
    v1 = pub.publish(params)                      # raw
    sub = WeightSubscriber("q/mixed")
    _, raw = sub.get(v1)                          # pins v1 across v2 publish
    np.testing.assert_array_equal(raw["w"], params["w"])
    assert sub.current_codec == "raw"
    logical_after_raw = sub.bytes_pulled
    wire_after_raw = sub.wire_bytes_pulled
    # raw codec: wire == logical up to per-chunk serialization framing
    assert logical_after_raw <= wire_after_raw <= logical_after_raw * 1.01
    v2 = pub.publish(params, quantized=True)      # int8
    _, quant = sub.get(v2)
    np.testing.assert_allclose(quant["w"], params["w"], atol=0.02)
    assert sub.current_codec == "int8"
    d_logical = sub.bytes_pulled - logical_after_raw
    d_wire = sub.wire_bytes_pulled - wire_after_raw
    assert 0 < d_wire < d_logical / 2
    # registry rows carry the codec + wire split for operators
    from ray_tpu.util.state import list_weights

    row = {r["name"]: r for r in list_weights()}["q/mixed"]
    assert row["codec"] == "int8"
    assert row["wire_bytes"] < row["total_bytes"] / 2
    sub.release()


# -- train smoke: quantized gradient allreduce tracks the fp loss curve ------


def _dp_setup(rank):
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    w1 = np.asarray(jax.random.normal(key, (4, 16)) * 0.1, np.float32)
    w2 = np.asarray(
        jax.random.normal(jax.random.PRNGKey(1), (16, 1)) * 0.1, np.float32
    )
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(2 + rank), (64, 4)))
    y = np.asarray(
        (x.sum(axis=1, keepdims=True) > 0).astype(jnp.float32)
    )
    return [w1, w2], (x, y)


def _dp_grads(params, x, y):
    import jax.numpy as jnp

    def loss_fn(ps):
        h = jnp.maximum(jnp.asarray(x) @ ps[0], 0.0)
        p = h @ ps[1]
        return jnp.mean((p - jnp.asarray(y)) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(
        [np.asarray(p) for p in params]
    )
    return [np.asarray(g, np.float32) for g in grads], float(loss)


def _dp_train_loop(config):
    ctx = rt_train.get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    params, (x, y) = _dp_setup(rank)
    for epoch in range(config["epochs"]):
        grads, loss = _dp_grads(params, x, y)
        summed = [rt_train.collective.allreduce(g) for g in grads]
        params = [
            p - 0.5 * np.asarray(s, np.float32) / world
            for p, s in zip(params, summed)
        ]
        rt_train.report({"loss": loss, "epoch": epoch, "rank": rank})


def _dp_exact_losses(world, epochs):
    """The fp reference: same loop, exact gradient sum, no cluster."""
    states = [_dp_setup(r) for r in range(world)]
    params = states[0][0]
    losses = []
    for _ in range(epochs):
        per_rank = [_dp_grads(params, *s[1]) for s in states]
        summed = [
            np.sum([g[i] for g, _ in per_rank], axis=0)
            for i in range(len(params))
        ]
        params = [p - 0.5 * s / world for p, s in zip(params, summed)]
        losses.append(per_rank[0][1])
    return losses


def test_quantized_train_smoke_loss_parity(tmp_path):
    ray_tpu.init(num_cpus=4)
    os.environ["RAY_TPU_STORAGE_PATH"] = str(tmp_path / "results")
    try:
        result = rt_train.JaxTrainer(
            _dp_train_loop,
            train_loop_config={"epochs": 8},
            scaling_config=rt_train.ScalingConfig(num_workers=2),
            run_config=rt_train.RunConfig(name="q-parity"),
            quantized=True,
        ).fit()
        assert result.error is None
        q_losses = [
            m["loss"]
            for m in sorted(
                (m for m in result.metrics_history if m["rank"] == 0),
                key=lambda m: m["epoch"],
            )
        ]
        fp_losses = _dp_exact_losses(world=2, epochs=8)
        assert len(q_losses) == 8
        # error feedback keeps the quantized run on the fp curve: every
        # epoch within 2% relative (+ tiny abs floor), and it converges
        for q, fp in zip(q_losses, fp_losses):
            assert abs(q - fp) <= 0.02 * fp + 1e-3
        assert q_losses[-1] < q_losses[0]
    finally:
        os.environ.pop("RAY_TPU_STORAGE_PATH", None)
        ray_tpu.shutdown()
