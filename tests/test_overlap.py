"""Overlapped gradient collectives (collective/bucketizer.py,
collective/scheduler.py, collective/hierarchical.py).

Four layers:

- bucketizer unit tests: deterministic leaf assignment (the cross-rank
  contract), size-target edge cases (oversized leaf, empty tree, dtype
  mix), pack/unpack inversion, and re-form stability (an epoch+1 rebuild
  over the same model produces identical buckets);
- scheduler unit tests over an in-process fake group: overlapped result ==
  synchronous result bit-for-bit (the stale_grad=0 parity pin), the
  stale_grad=1 one-step-delay pipeline, exposed/overlapped metric split;
- cross-actor tests over the real GCS backend: overlapped == sync parity,
  hierarchical (slice_size) composition == flat sum, and the abort plane —
  a mid-flight bucket handle raises CollectiveAbortedError, never hangs;
- train-session integration: reduce_gradients honors the context knobs.
"""

import time

import jax
import numpy as np
import pytest

import ray_tpu
from ray_tpu.collective import ReduceOp
from ray_tpu.collective.base import BaseGroup
from ray_tpu.collective.bucketizer import GradientBucketizer
from ray_tpu.collective.scheduler import GradientReduceScheduler


def _grad_tree(scale=1.0):
    return {
        "dense0": {
            "kernel": np.full((32, 16), scale, np.float32),
            "bias": np.arange(16, dtype=np.float32) * scale,
        },
        "dense1": {"kernel": np.full((16, 8), 2.0 * scale, np.float32)},
        "steps": np.array([3], np.int64),
    }


def _tree_allclose(a, b, **kw):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


# ---------------------------------------------------------------- bucketizer


def test_bucketizer_deterministic_under_insertion_order():
    """Two ranks building the dict in different insertion orders must get
    the identical assignment — the allreduce sums garbage otherwise."""
    a = {"b": np.ones((4, 4), np.float32), "a": np.zeros((8,), np.float32)}
    b = {"a": np.zeros((8,), np.float32), "b": np.ones((4, 4), np.float32)}
    ba = GradientBucketizer(a, bucket_bytes=1 << 20)
    bb = GradientBucketizer(b, bucket_bytes=1 << 20)
    assert ba.signature() == bb.signature()
    assert [s.paths for s in ba.buckets] == [s.paths for s in bb.buckets]
    packed_a = ba.pack(a)
    packed_b = bb.pack(b)
    for x, y in zip(packed_a, packed_b):
        assert x.shape == y.shape


def test_bucketizer_size_targets_and_oversized_leaf():
    tree = {
        "big": np.zeros((1024,), np.float32),     # 4096 B >= target alone
        "s1": np.zeros((16,), np.float32),
        "s2": np.zeros((16,), np.float32),
    }
    b = GradientBucketizer(tree, bucket_bytes=4096)
    by_paths = {s.paths: s for s in b.buckets}
    # the oversized leaf closes its own bucket; the small leaves share one
    assert ("big",) in by_paths
    assert by_paths[("big",)].nbytes == 4096
    assert ("s1", "s2") in by_paths


def test_bucketizer_dtype_mix_splits_buckets():
    tree = {
        "f": np.zeros((8,), np.float32),
        "h": np.zeros((8,), np.float16),
        "i": np.zeros((8,), np.int32),
    }
    b = GradientBucketizer(tree, bucket_bytes=1 << 20)
    assert b.num_buckets == 3  # dtype-homogeneous despite tiny sizes
    dtypes = {s.dtype for s in b.buckets}
    assert dtypes == {"float32", "float16", "int32"}
    restored = b.unpack(b.pack(tree))
    for k in tree:
        assert restored[k].dtype == tree[k].dtype


def test_bucketizer_empty_tree():
    b = GradientBucketizer({}, bucket_bytes=4096)
    assert b.num_buckets == 0
    assert b.pack({}) == []
    assert b.unpack([]) == {}


def test_bucketizer_scalar_and_roundtrip():
    tree = {"w": np.full((3, 5), 7.0, np.float32),
            "lr": np.float32(0.125)}
    b = GradientBucketizer(tree, bucket_bytes=64)
    restored = b.unpack(b.pack(tree))
    _tree_allclose(tree, restored)
    assert np.asarray(restored["lr"]).shape == ()


def test_bucketizer_reform_rebuilds_identical_buckets():
    """Elastic re-rank invariant: the epoch+1 gang rebuilds the bucketizer
    from the same model tree and must land on byte-identical buckets — the
    assignment depends on structure only, never on rank or history."""
    tree = _grad_tree()
    before = GradientBucketizer(tree, bucket_bytes=2048)
    after = GradientBucketizer(_grad_tree(scale=9.0), bucket_bytes=2048)
    assert before.signature() == after.signature()
    assert [s.paths for s in before.buckets] == [
        s.paths for s in after.buckets
    ]
    assert [s.shapes for s in before.buckets] == [
        s.shapes for s in after.buckets
    ]


def test_bucketizer_rejects_mismatched_tree():
    b = GradientBucketizer(_grad_tree(), bucket_bytes=2048)
    with pytest.raises(ValueError, match="leaves"):
        b.pack({"just": np.ones(3, np.float32)})
    with pytest.raises(ValueError, match="bucket arrays"):
        b.unpack([])


# ----------------------------------------------------------------- scheduler


class _LoopbackGroup(BaseGroup):
    """World-of-one group: allreduce multiplies by a fixed world factor so
    tests can distinguish reduced from unreduced values, with an optional
    per-op sleep to emulate rendezvous latency."""

    backend = "fake"

    def __init__(self, factor=3.0, op_delay=0.0, name="loop"):
        super().__init__(1, 0, name)
        self.factor = factor
        self.op_delay = op_delay
        self.calls = 0

    def allreduce(self, tensor, op=ReduceOp.SUM):
        self.calls += 1
        if self.op_delay:
            time.sleep(self.op_delay)
        return np.asarray(tensor) * self.factor

    def allgather(self, tensor):
        return [tensor]

    def reducescatter(self, tensor, op=ReduceOp.SUM):
        return np.asarray(tensor) * self.factor

    def broadcast(self, tensor, src_rank=0):
        return tensor

    def send(self, tensor, dst_rank):
        raise NotImplementedError

    def recv(self, src_rank):
        raise NotImplementedError

    def barrier(self):
        pass


def test_scheduler_overlapped_matches_sync_exactly():
    """stale_grad=0 parity pin: overlap changes WHEN buckets reduce, not
    what they sum to — the reduced trees must be bit-identical."""
    grads = _grad_tree(scale=1.5)
    sync = GradientReduceScheduler(
        _LoopbackGroup(), bucket_bytes=512, overlap=False
    ).step(grads)
    over = GradientReduceScheduler(
        _LoopbackGroup(), bucket_bytes=512, overlap=True
    ).step(grads)
    for x, y in zip(
        jax.tree_util.tree_leaves(sync), jax.tree_util.tree_leaves(over)
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y))
    _tree_allclose(sync, jax.tree_util.tree_map(
        lambda v: np.asarray(v) * 3.0, grads
    ))


def test_scheduler_stale_grad_pipeline():
    group = _LoopbackGroup(factor=2.0)
    sched = GradientReduceScheduler(
        group, bucket_bytes=512, overlap=True, stale_grad=1
    )
    g1 = _grad_tree(scale=1.0)
    g2 = _grad_tree(scale=10.0)
    assert sched.step(g1) is None  # first step: nothing reduced yet
    out1 = sched.step(g2)          # returns step 1's gradients
    _tree_allclose(
        out1, jax.tree_util.tree_map(lambda v: np.asarray(v) * 2.0, g1)
    )
    tail = sched.flush()           # drains step 2's delayed reduce
    _tree_allclose(
        tail, jax.tree_util.tree_map(lambda v: np.asarray(v) * 2.0, g2)
    )
    assert sched.flush() is None


def test_scheduler_stale_grad_drift_bounded():
    """A 1-step-delayed SGD trajectory drifts from the synchronous one by
    O(lr): with lr small the final params stay within a loose bound (the
    'bounded drift' acceptance criterion, checked arithmetically)."""
    lr = 0.01
    steps = 20

    def run(stale):
        sched = GradientReduceScheduler(
            _LoopbackGroup(factor=1.0), bucket_bytes=256,
            overlap=True, stale_grad=stale,
        )
        w = np.full((8,), 1.0, np.float32)
        for _ in range(steps):
            grad = {"w": 2.0 * w}  # d/dw of w^2
            reduced = sched.step(grad)
            if reduced is not None:
                w = w - lr * np.asarray(reduced["w"])
        tail = sched.flush()
        if stale and tail is not None:
            w = w - lr * np.asarray(tail["w"])
        return w

    exact = run(0)
    delayed = run(1)
    drift = float(np.max(np.abs(exact - delayed)))
    assert drift < 5 * lr, f"stale_grad drift {drift} exceeds bound"


def test_scheduler_rebuilds_bucketizer_on_structure_change():
    sched = GradientReduceScheduler(_LoopbackGroup(), bucket_bytes=512)
    b1 = sched.bucketizer_for(_grad_tree())
    assert sched.bucketizer_for(_grad_tree(scale=2.0)) is b1  # cached
    b2 = sched.bucketizer_for({"other": np.ones(4, np.float32)})
    assert b2 is not b1


def test_scheduler_records_overlap_split():
    from ray_tpu.util import metrics

    group = _LoopbackGroup(op_delay=0.02, name="ovl-metrics")
    sched = GradientReduceScheduler(group, bucket_bytes=512, overlap=True)
    pending = sched.reduce(_grad_tree())
    time.sleep(0.1)  # "backward compute" covering the reduce
    pending.wait()
    summary = metrics.collective_overlap_summary()["ovl-metrics"]
    assert summary["overlapped_s"] > 0
    # the emulated compute fully covers the rendezvous: mostly hidden
    assert summary["overlap_fraction"] > 0.5
    # sync mode on the same group records fully-exposed reductions
    GradientReduceScheduler(group, bucket_bytes=512, overlap=False).step(
        _grad_tree()
    )
    after = metrics.collective_overlap_summary()["ovl-metrics"]
    assert after["exposed_s"] > summary["exposed_s"]


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_xla_allreduce_async_matches_blocking():
    from ray_tpu.collective.xla_group import XlaGroup

    group = XlaGroup(1, 0, "xla-async", devices=jax.devices()[:4])
    x = np.arange(8, dtype=np.float32)
    handle = group.allreduce_async(x)
    out = np.asarray(handle.wait())
    np.testing.assert_allclose(out, np.asarray(group.allreduce(x)))
    assert handle.done()
    # wait() is idempotent
    np.testing.assert_allclose(np.asarray(handle.wait()), out)


# ------------------------------------------------------------- cross-actor


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _make_worker_cls():
    @ray_tpu.remote(max_restarts=0)
    class Worker:
        def join(self, world, rank, group, backend="gcs", **kwargs):
            import os

            from ray_tpu import collective as col

            self.group = col.init_collective_group(
                world, rank, backend=backend, group_name=group, **kwargs
            )
            self.rank = rank
            return os.getpid()

        def reduce_tree(self, scale, overlap, bucket_bytes=512,
                        compute_s=0.0):
            import jax as _jax
            import numpy as _np

            from ray_tpu.collective.scheduler import GradientReduceScheduler

            grads = {
                "k": _np.full((64,), float(scale), _np.float32),
                "b": _np.arange(8, dtype=_np.float32) * float(scale),
            }
            sched = GradientReduceScheduler(
                self.group, bucket_bytes=bucket_bytes, overlap=overlap
            )
            pending = sched.reduce(grads)
            if compute_s:
                time.sleep(compute_s)
            out = pending.wait()
            return {k: _np.asarray(v) for k, v in out.items()}

        def group_allreduce(self, value):
            import numpy as _np

            return _np.asarray(self.group.allreduce(_np.asarray(value)))

        def group_allgather(self, value):
            return self.group.allgather(value)

        def group_broadcast(self, value, src):
            return self.group.broadcast(value, src_rank=src)

        def async_reduce_outcome(self, value):
            import numpy as _np

            from ray_tpu.exceptions import CollectiveAbortedError

            handle = self.group.allreduce_async(_np.asarray(value))
            t0 = time.perf_counter()
            try:
                out = handle.wait()
                return ("ok", float(_np.asarray(out)[0]),
                        time.perf_counter() - t0)
            except CollectiveAbortedError:
                return ("aborted", 0.0, time.perf_counter() - t0)

    return Worker


def test_overlapped_reduce_across_actors_matches_sync(cluster):
    """Real GCS rendezvous, 3 ranks: the overlapped bucketized reduce and
    the plain blocking path produce the identical summed tree."""
    Worker = _make_worker_cls()
    world = 3
    for mode, gname in ((False, "ov-sync"), (True, "ov-async")):
        members = [Worker.remote() for _ in range(world)]
        ray_tpu.get(
            [m.join.remote(world, r, gname) for r, m in enumerate(members)],
            timeout=60,
        )
        outs = ray_tpu.get(
            [m.reduce_tree.remote(r + 1, mode) for r, m in
             enumerate(members)],
            timeout=180,
        )
        # ranks contribute scale 1..3 -> sum factor 6 on "k"
        for out in outs:
            np.testing.assert_allclose(out["k"], np.full((64,), 6.0))
            np.testing.assert_allclose(
                out["b"], np.arange(8, dtype=np.float32) * 6.0
            )


def test_hierarchical_group_matches_flat_semantics(cluster):
    """4 ranks in 2 slices of 2: hier allreduce == flat sum everywhere,
    broadcast routes across slices, allgather is world-rank ordered."""
    Worker = _make_worker_cls()
    world, slice_size = 4, 2
    members = [Worker.remote() for _ in range(world)]
    ray_tpu.get(
        [
            m.join.remote(world, r, "hier0", backend="hier",
                          slice_size=slice_size)
            for r, m in enumerate(members)
        ],
        timeout=60,
    )
    outs = ray_tpu.get(
        [m.group_allreduce.remote([float(r + 1)]) for r, m in
         enumerate(members)],
        timeout=180,
    )
    for out in outs:
        np.testing.assert_allclose(out, [10.0])  # 1+2+3+4
    gathered = ray_tpu.get(
        [m.group_allgather.remote(r * 11) for r, m in enumerate(members)],
        timeout=180,
    )
    for g in gathered:
        assert list(g) == [0, 11, 22, 33]
    # broadcast from a non-leader in the second slice (rank 3)
    bc = ray_tpu.get(
        [m.group_broadcast.remote(100 + r, 3) for r, m in
         enumerate(members)],
        timeout=180,
    )
    assert all(v == 103 for v in bc)


def test_hierarchical_overlapped_reduce(cluster):
    """The scheduler drives a hierarchical group exactly like a flat one
    (the merged-backend contract): async bucketized reduce over hier."""
    Worker = _make_worker_cls()
    world, slice_size = 4, 2
    members = [Worker.remote() for _ in range(world)]
    ray_tpu.get(
        [
            m.join.remote(world, r, "hier-ov", backend="hier",
                          slice_size=slice_size)
            for r, m in enumerate(members)
        ],
        timeout=60,
    )
    outs = ray_tpu.get(
        [m.reduce_tree.remote(1, True) for m in members], timeout=180
    )
    for out in outs:
        np.testing.assert_allclose(out["k"], np.full((64,), 4.0))


def test_async_handle_aborts_instead_of_hanging(cluster):
    """Abort-plane contract for in-flight buckets: a rank blocked in
    handle.wait() on a dispatched async allreduce raises
    CollectiveAbortedError promptly when the group is aborted."""
    from ray_tpu import collective

    Worker = _make_worker_cls()
    members = [Worker.remote() for _ in range(2)]
    ray_tpu.get(
        [m.join.remote(3, r, "ov-abrt") for r, m in enumerate(members)],
        timeout=60,
    )
    # rank 2 never joins the op: both handles stay in-flight
    refs = [m.async_reduce_outcome.remote([1.0]) for m in members]
    time.sleep(0.5)
    assert collective.abort_collective_group("ov-abrt", epoch=0,
                                             reason="test")
    outs = ray_tpu.get(refs, timeout=30)
    assert [o[0] for o in outs] == ["aborted", "aborted"]
    assert all(o[2] < 10.0 for o in outs)


def test_train_session_reduce_gradients_knobs(cluster):
    """reduce_gradients() builds the scheduler from the TrainContext knobs
    (overlap/bucket_bytes/stale_grad) and sums across the gang."""
    @ray_tpu.remote(max_restarts=0)
    class Trainee:
        def run(self, world, rank):
            import numpy as _np

            from ray_tpu import collective as col
            from ray_tpu.train import collective as tcol
            from ray_tpu.train.session import TrainContext, set_context

            ctx = TrainContext(
                world_rank=rank, local_rank=rank, node_rank=0,
                world_size=world, local_world_size=world,
                experiment_name="ov-train", run_dir="/tmp/ov-train",
                collective_group="ov-train-g",
                collective_overlap=True,
                collective_bucket_bytes=256,
            )
            set_context(ctx)
            col.init_collective_group(
                world, rank, backend="gcs", group_name="ov-train-g"
            )
            grads = {"w": _np.full((32,), rank + 1.0, _np.float32)}
            out = tcol.reduce_gradients(grads)
            sched = tcol.gradient_scheduler()
            return (
                float(_np.asarray(out["w"])[0]),
                sched.overlap,
                sched.bucket_bytes,
            )

    world = 2
    members = [Trainee.remote() for _ in range(world)]
    outs = ray_tpu.get(
        [m.run.remote(world, r) for r, m in enumerate(members)], timeout=180
    )
    for total, overlap, bucket_bytes in outs:
        assert total == 3.0  # 1 + 2
        assert overlap is True
        assert bucket_bytes == 256
