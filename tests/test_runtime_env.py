"""Runtime env + tracing tests.

Models the reference's runtime_env tests (python/ray/tests/test_runtime_env*.py):
env_vars via dedicated workers, working_dir/py_modules packaging, pip/conda
rejection, job-level defaults; plus the tracing/timeline surface
(util/tracing tests + `ray timeline`)."""

import os
import sys

import pytest

import ray_tpu
from ray_tpu._internal.runtime_env import (
    RuntimeEnvSetupError,
    env_key,
)
from ray_tpu.util import tracing


def test_env_key_stability():
    a = {"env_vars": {"A": "1", "B": "2"}}
    b = {"env_vars": {"B": "2", "A": "1"}}
    assert env_key(dict(sorted(a.items()))) == env_key(dict(sorted(b.items())))
    assert env_key(None) == ""
    assert env_key({"env_vars": {"A": "2"}}) != env_key({"env_vars": {"A": "1"}})


def test_env_vars_in_dedicated_worker(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"RAY_TPU_TEST_VAR": "hello"}})
    def read_env():
        return os.environ.get("RAY_TPU_TEST_VAR")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "hello"

    @ray_tpu.remote
    def read_env_default():
        return os.environ.get("RAY_TPU_TEST_VAR")

    # default-env workers must not see the dedicated worker's vars
    assert ray_tpu.get(read_env_default.remote(), timeout=60) is None


def test_pip_rejected(ray_start_regular):
    @ray_tpu.remote(runtime_env={"pip": ["requests"]})
    def f():
        return 1

    with pytest.raises(RuntimeEnvSetupError, match="pip/conda"):
        f.remote()


def test_working_dir(tmp_path, ray_start_regular):
    (tmp_path / "datafile.txt").write_text("payload-42")
    (tmp_path / "helper_mod_rt.py").write_text("VALUE = 42\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def read_file():
        import helper_mod_rt  # resolvable: working_dir is on sys.path

        with open("datafile.txt") as f:
            return f.read(), helper_mod_rt.VALUE

    content, value = ray_tpu.get(read_file.remote(), timeout=60)
    assert content == "payload-42"
    assert value == 42


def test_py_modules(tmp_path, ray_start_regular):
    pkg = tmp_path / "mypkg_rt"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("ANSWER = 7\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(tmp_path)]})
    def use_pkg():
        from mypkg_rt import ANSWER

        return ANSWER

    assert ray_tpu.get(use_pkg.remote(), timeout=60) == 7


def test_actor_runtime_env(ray_start_regular):
    @ray_tpu.remote
    class EnvActor:
        def read(self):
            return os.environ.get("RAY_TPU_ACTOR_VAR")

    a = EnvActor.options(
        runtime_env={"env_vars": {"RAY_TPU_ACTOR_VAR": "actor-env"}}
    ).remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == "actor-env"


def test_job_level_runtime_env(shutdown_only):
    ray_tpu.init(
        num_cpus=2,
        resources={"TPU": 0},
        runtime_env={"env_vars": {"JOB_LEVEL_VAR": "job"}},
    )

    @ray_tpu.remote
    def read():
        return os.environ.get("JOB_LEVEL_VAR")

    assert ray_tpu.get(read.remote(), timeout=60) == "job"


class TestTracing:
    def test_span_recording(self):
        tracing.enable_tracing()
        tracing.clear_spans()
        with tracing.trace_span("unit-span", category="test", foo="bar"):
            pass
        spans = tracing.get_spans()
        assert any(s["name"] == "unit-span" for s in spans)
        span = next(s for s in spans if s["name"] == "unit-span")
        assert span["args"]["foo"] == "bar"
        assert span["dur"] >= 0

    def test_timeline_export(self, tmp_path, ray_start_regular):
        tracing.enable_tracing()

        @ray_tpu.remote
        def traced_task():
            return 1

        ray_tpu.get([traced_task.remote() for _ in range(3)], timeout=60)
        import time

        time.sleep(1.5)  # task-event flush interval
        out = tmp_path / "timeline.json"
        events = tracing.timeline(str(out))
        assert out.exists()
        task_events = [e for e in events if e["cat"] == "NORMAL_TASK"]
        assert len(task_events) >= 3
        assert all(e["dur"] >= 0 for e in task_events)
        submit_spans = [e for e in events if e["cat"] == "ray_tpu.task"]
        assert submit_spans
