"""GCS fault tolerance: kill the GCS mid-workload, restart it from durable
storage, and verify actors / named lookups / placement groups / KV resume
(reference: the GCS-FT suites backed by RedisStoreClient,
src/ray/gcs/store_client/redis_store_client.h:126, and raylet reconnect via
NotifyGCSRestart, src/ray/protobuf/node_manager.proto:426)."""

import time

import pytest

import ray_tpu
from ray_tpu.runtime.gcs.store import SqliteStoreClient
from ray_tpu.util.placement_group import placement_group, placement_group_table


def test_sqlite_store_roundtrip(tmp_path):
    path = str(tmp_path / "gcs.db")
    store = SqliteStoreClient(path)
    store.put("kv", "a", b"1")
    store.put("kv", "a", b"2")  # upsert
    store.put("actors", "a", b"actor-a")
    assert store.get("kv", "a") == b"2"
    assert store.get("kv", "missing") is None
    store.delete("kv", "a")
    assert store.get("kv", "a") is None
    assert store.get_all("actors") == {"a": b"actor-a"}
    store.close()
    # durability: a second client sees the first one's writes
    again = SqliteStoreClient(path)
    assert again.get("actors", "a") == b"actor-a"
    again.close()


def test_gcs_restart_preserves_cluster(shutdown_only, tmp_path):
    node = ray_tpu.init(
        num_cpus=4,
        _system_config={"gcs_storage_path": str(tmp_path / "gcs.db")},
    )

    @ray_tpu.remote(max_restarts=2)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.options(name="survivor").remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=60)

    from ray_tpu import _worker_api

    def _kv(method, *args):
        worker = _worker_api.get_core_worker()
        return _worker_api.run_on_worker_loop(
            worker.client_pool.get(*worker.gcs_address).call(method, *args)
        )

    _kv("kv_put", "ft-key", b"ft-value", True)

    node.kill_gcs_for_testing()
    node.restart_gcs_for_testing()

    # the actor's worker never died: calls must keep working through the
    # restarted GCS (client + raylet reconnect transparently)
    assert ray_tpu.get(c.incr.remote(), timeout=120) == 2

    # named-actor lookup resolves from restored state
    h = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(h.incr.remote(), timeout=60) == 3

    # the placement group record survived with its committed bundles
    restored = placement_group_table()
    assert any(
        row["placement_group_id"] == pg.id.hex() and row["state"] == "CREATED"
        for row in restored
    )

    # internal KV survived
    assert _kv("kv_get", "ft-key") == b"ft-value"


@pytest.mark.slow
def test_gcs_restart_restores_actor_after_worker_death(shutdown_only, tmp_path):
    """An actor whose worker dies WHILE the GCS is down is restarted after
    the GCS comes back: the re-registering raylet reports its live workers
    and the reconciler routes the dead one through the restart path."""
    node = ray_tpu.init(
        num_cpus=2,
        _system_config={"gcs_storage_path": str(tmp_path / "gcs.db")},
    )

    @ray_tpu.remote(max_restarts=5, max_task_retries=5)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1

    # find the actor's worker pid via the raylet lease table
    import os
    import signal

    pids = [lease.worker.pid for lease in node.raylet._leases.values()]
    assert pids, "actor worker must hold a lease"

    node.kill_gcs_for_testing()
    for pid in pids:
        os.kill(pid, signal.SIGKILL)
    time.sleep(0.5)
    node.restart_gcs_for_testing()

    # state reset proves a restart happened; the call itself succeeding
    # proves the restored directory scheduled a new worker
    assert ray_tpu.get(c.incr.remote(), timeout=120) == 1
