"""End-to-end serve request tracing, flight recorder, and hang watchdog
(reference model: python/ray/serve request-context propagation tests +
export-event tests). One request entering the HTTP proxy must come out
as ONE chrome trace — proxy, handle-route, replica-admission, and (for
LLM deployments) engine/kvcache spans under a single trace_id — and the
flight recorder + watchdog must make a killed or hung replica explainable
after the fact."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu import testing
from ray_tpu.util import events
from ray_tpu.util import state
from ray_tpu.util import tracing
from ray_tpu.util import watchdog


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=8, resources={"TPU": 4})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_apps():
    yield
    try:
        for app in list(serve.status().keys()):
            serve.delete(app)
    except Exception:
        pass


def _wait_replicas(app, n, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        rows = [
            r for r in testing.list_serve_replicas(app)
            if r["state"] == "RUNNING" and r["pid"]
        ]
        if len(rows) == n:
            return rows
        time.sleep(0.1)
    raise TimeoutError(f"{app}: never reached {n} RUNNING replicas with pids")


def _spans_for_trace(trace_id):
    """All spans in the merged cluster timeline carrying ``trace_id``."""
    return [
        s for s in tracing.timeline()
        if s.get("span_id") and s.get("trace_id") == trace_id
    ]


# ---------------------------------------------------------------------------
# tentpole: one HTTP request -> one trace, proxy to replica
# ---------------------------------------------------------------------------


def test_http_trace_chain_end_to_end(cluster):
    """POST with an X-Trace-Id header: the proxy honors it as the trace
    root, the id is echoed back, and the merged timeline shows
    serve.proxy -> serve.route / serve.replica -> serve.admission all
    sharing that trace_id with intact parent links — across the proxy,
    driver, and replica processes."""

    @serve.deployment
    class Echo:
        def __call__(self, body):
            return {"echo": body}

    serve.run(Echo.bind(), name="traceapp", route_prefix="/traced")
    _wait_replicas("traceapp", 1)

    trace_id = "trace-chain-e2e-test"
    payload = json.dumps({"x": 1}).encode()
    req = urllib.request.Request(
        "http://127.0.0.1:8000/traced", data=payload,
        headers={"Content-Type": "application/json",
                 "X-Trace-Id": trace_id},
    )
    deadline = time.time() + 30
    resp = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                echoed = r.headers.get("X-Trace-Id")
                body = json.loads(r.read())
                resp = (echoed, body)
            break
        except Exception:
            time.sleep(0.5)
    assert resp is not None, "proxy never answered"
    echoed, body = resp
    # the caller joins its latency record to server spans via this echo
    assert echoed == trace_id
    assert body["result"] == {"echo": {"x": 1}}

    # spans flush to the GCS span store on a 1s cadence from the proxy
    # actor AND the replica worker; poll the merged timeline for the chain
    wanted = {"serve.proxy", "serve.route", "serve.replica",
              "serve.admission"}
    deadline = time.time() + 20
    by_name = {}
    while time.time() < deadline:
        spans = _spans_for_trace(trace_id)
        by_name = {s["name"]: s for s in spans}
        if wanted <= set(by_name):
            break
        time.sleep(0.5)
    assert wanted <= set(by_name), (
        f"missing spans: {wanted - set(by_name)}"
    )

    proxy = by_name["serve.proxy"]
    route = by_name["serve.route"]
    replica = by_name["serve.replica"]
    admission = by_name["serve.admission"]
    # proxy span is the trace top (parent = the minted root, empty span_id)
    assert proxy["parent_id"] == ""
    # the handle's route span and the replica span both parent under it
    assert route["parent_id"] == proxy["span_id"]
    assert replica["parent_id"] == proxy["span_id"]
    # admission nests inside the replica stage
    assert admission["parent_id"] == replica["span_id"]
    # proxy, route (proxy process), and replica spans span >= 2 processes
    assert len({proxy["pid"], replica["pid"]}) == 2
    # the route span records where the request was sent
    assert route["args"]["deployment"]


def test_handle_failover_attempt_span_and_replica_id(cluster, monkeypatch):
    """Chaos kill mid-request: the retry appears in the trace as a sibling
    serve.attempt span tagged with the excluded replica and the reason,
    and DeploymentResponse.replica_id() names the replica the FINAL
    resubmission landed on."""
    # keep driver spans in the local ring: the 1s pusher trims flushed
    # spans into the GCS store, racing the get_spans() reads below
    monkeypatch.setattr(tracing, "flush_spans", lambda: None)

    @serve.deployment(num_replicas=2)
    class Slow:
        def __call__(self, x):
            time.sleep(0.8)
            return x * 2

    tracing.enable_tracing()
    try:
        handle = serve.run(Slow.bind(), name="killtrace", _proxy=False)
        rows = _wait_replicas("killtrace", 2)
        known = {r["replica_id"] for r in rows}

        # The kill only produces a failover if a request was in flight on
        # the doomed replica — under host load the dispatch window can
        # race the kill, so retry the round (the controller reconciles
        # the pool back to 2 replicas) until an attempt span appears.
        attempts = []
        final_rids = []
        for _ in range(3):
            responses = [handle.remote(i) for i in range(8)]
            time.sleep(0.3)  # let requests land on both replicas
            killed_rid, pid = testing.kill_serve_replica("killtrace")
            assert killed_rid is not None and pid

            results = [r.result(timeout_s=30) for r in responses]
            assert sorted(results) == [i * 2 for i in range(8)]

            # every response knows its outcome replica, and none of them
            # name the corpse — failover re-points replica_id at the
            # survivor
            final_rids = [r.replica_id() for r in responses]
            assert all(rid is not None for rid in final_rids)
            assert killed_rid not in final_rids

            # the failover is a span, not just a counter: sibling attempt
            # spans under the request trace, tagged with what was excluded
            attempts = [
                s for s in tracing.get_spans()
                if s["name"] == "serve.attempt"
            ]
            if attempts:
                break
            rows = _wait_replicas("killtrace", 2)
            known |= {r["replica_id"] for r in rows}
        assert attempts, "no serve.attempt span after 3 chaos kills"
        att = attempts[-1]["args"]
        assert att["deployment"].endswith("Slow")
        assert att["attempt"] >= 1
        assert att["reason"]
        assert killed_rid in att["excluded"]
        assert att["replica"] in known | set(final_rids)
        assert attempts[-1]["trace_id"]
    finally:
        tracing._enabled = os.environ.get(
            "RAY_TPU_TRACE", "") not in ("", "0")


def test_engine_kvcache_spans_join_request_trace(monkeypatch):
    """Clusterless engine: a traced generate() emits queue-wait, prefill,
    decode, and kvcache acquire/assemble/commit spans that all join the
    caller's trace (the stages `ray_tpu timeline` shows inside the
    replica span for an LLM deployment)."""
    import jax

    # the suite-wide span pusher (started by earlier cluster tests in this
    # process) trims flushed spans from the local ring; pin them here
    monkeypatch.setattr(tracing, "flush_spans", lambda: None)

    from ray_tpu.kvcache import KVCacheManager
    from ray_tpu.llm.engine import ContinuousBatchingEngine, GenerationRequest
    from ray_tpu.models.llama import LlamaConfig, init_params
    from ray_tpu.parallel.sharding import unbox_params

    cfg = LlamaConfig.tiny(max_seq_len=128)
    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    kv = KVCacheManager(num_blocks=16, block_size=16)
    eng = ContinuousBatchingEngine(cfg, params, num_slots=2, kv_cache=kv)
    prompt = list(range(7, 7 + 56))

    tracing.enable_tracing()
    tracing.clear_spans()
    try:
        ctx = tracing.new_trace_context()
        with tracing.request_span("test.request", ctx):
            eng.generate([GenerationRequest(token_ids=prompt,
                                            max_new_tokens=2,
                                            temperature=0.0)])
            # second pass hits the cached prefix -> kvcache.assemble
            eng.generate([GenerationRequest(token_ids=prompt,
                                            max_new_tokens=2,
                                            temperature=0.0)])
        spans = tracing.get_spans()
        mine = [s for s in spans if s["trace_id"] == ctx["trace_id"]]
        names = {s["name"] for s in mine}
        wanted = {"engine.queue_wait", "engine.prefill", "engine.decode",
                  "kvcache.acquire", "kvcache.assemble", "kvcache.commit"}
        assert wanted <= names, f"missing: {wanted - names}"
        # the second prefill rode the prefix cache, and the span says so
        prefills = [s for s in mine if s["name"] == "engine.prefill"]
        assert any(s["args"]["hit"] for s in prefills)
        assert any(
            s["args"]["cached_tokens"] == 48 for s in prefills
        )
        # kvcache spans carry the kvcache category for timeline grouping
        assert all(
            s["cat"] == "kvcache" for s in mine
            if s["name"].startswith("kvcache.")
        )
    finally:
        tracing._enabled = os.environ.get(
            "RAY_TPU_TRACE", "") not in ("", "0")
        tracing.clear_spans()


# ---------------------------------------------------------------------------
# flight recorder: always-on events, SIGKILL-surviving, queryable
# ---------------------------------------------------------------------------


def _gcs(method, *args):
    worker = ray_tpu._worker_api.get_core_worker()
    return ray_tpu._worker_api.run_on_worker_loop(
        worker.client_pool.get(*worker.gcs_address).call(method, *args)
    )


def test_flight_recorder_streams_to_gcs(cluster):
    """record_event is always-on (no tracing flag) and the 1s pusher lands
    the event in the GCS store, queryable via state.list_events."""
    marker = f"obs-flight-{os.getpid()}-{time.time_ns()}"
    events.record_event(events.REPLICA_STATE, state="TESTING", marker=marker)

    deadline = time.time() + 15
    found = []
    while time.time() < deadline:
        found = [
            e for e in state.list_events(name="replica_state")
            if e.get("marker") == marker
        ]
        if found:
            break
        time.sleep(0.5)
    assert found, "event never reached the GCS event store"
    ev = found[0]
    assert ev["pid"] == os.getpid()
    assert ev["state"] == "TESTING"
    assert ev["ts"] > 0


def test_serve_lifecycle_events_recorded(cluster):
    """Controller state transitions land in the cluster event store: a
    deploy produces replica_start events post-mortem-queryable by name."""

    @serve.deployment(num_replicas=2)
    class Lifecycled:
        def __call__(self, x):
            return x

    serve.run(Lifecycled.bind(), name="lifeapp", _proxy=False)
    _wait_replicas("lifeapp", 2)

    deadline = time.time() + 15
    starts = []
    while time.time() < deadline:
        starts = [
            e for e in state.list_events(name="replica_start")
            if e.get("deployment", "").endswith("Lifecycled")
        ]
        if len(starts) >= 2:
            break
        time.sleep(0.5)
    assert len(starts) >= 2, "replica_start events never reached the GCS"


def test_flight_recorder_crash_dump_retrievable(cluster):
    """Acceptance: after a worker dies by SIGKILL, its death is stitched
    into the event stream as a synthetic worker_death marker, retrievable
    via the state API and the `ray_tpu events` CLI."""
    from ray_tpu._internal.ids import WorkerID

    ghost = WorkerID.from_random()
    _gcs("report_worker_death", ghost, "chaos-test-kill")

    rows = [
        e for e in state.list_events(name="worker_death")
        if e.get("worker_id") == ghost.hex()
    ]
    assert rows, "no synthetic worker_death event in the GCS store"
    assert rows[0]["reason"] == "chaos-test-kill"
    assert rows[0]["synthetic"] is True

    node = ray_tpu._worker_api.get_node()
    host, port = node.gcs_address
    out = subprocess.run(
        [
            sys.executable, "-m", "ray_tpu.scripts.cli", "events",
            "--address", f"{host}:{port}", "--name", "worker_death",
            "--limit", "1000",
        ],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "RAY_TPU_JAX_PLATFORM": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    listed = json.loads(out.stdout)
    assert any(e.get("worker_id") == ghost.hex() for e in listed)


# ---------------------------------------------------------------------------
# hang watchdog: stuck-request detection with stack capture
# ---------------------------------------------------------------------------


def test_watchdog_captures_stuck_stacks():
    """A watch past its deadline multiple trips the watchdog: all-thread
    stacks land in the flight recorder, the stuck_requests gauge rises,
    and completing the work emits a recovery event and lowers it."""
    from ray_tpu.util import metrics

    before_stuck = watchdog.stuck_count()
    token = watchdog.watch(
        "obs_test_wait", timeout_s=0.01, multiple=1.0,
        deployment="obsapp", replica="r-test",
    )
    time.sleep(0.05)
    watchdog._scan_once()  # deterministic: don't wait for the 1s scanner

    assert watchdog.stuck_count() == before_stuck + 1
    stuck = [
        e for e in events.get_events(name=str(events.WATCHDOG_STUCK))
        if e.get("watch") == "obs_test_wait"
    ]
    assert stuck, "no watchdog_stuck event recorded"
    ev = stuck[-1]
    assert ev["deployment"] == "obsapp" and ev["replica"] == "r-test"
    assert ev["elapsed_s"] >= ev["deadline_s"]
    # the capture is the post-mortem payload: every thread's stack, and
    # this very test frame is in it
    assert "Thread" in ev["stacks"]
    assert "test_watchdog_captures_stuck_stacks" in ev["stacks"]
    # the gauge mirrors the live count
    gauge = metrics._ensure_watchdog_metrics()["stuck"]
    assert gauge._values[()] == float(before_stuck + 1)

    watchdog.unwatch(token)
    assert watchdog.stuck_count() == before_stuck
    rec = [
        e for e in events.get_events(name=str(events.WATCHDOG_RECOVERED))
        if e.get("watch") == "obs_test_wait"
    ]
    assert rec, "no recovery event after unwatch"
    assert rec[-1]["elapsed_s"] >= 0.01
    assert gauge._values[()] == float(before_stuck)


def test_watchdog_fast_requests_never_trip():
    """The common path — watch/unwatch inside the deadline — records
    nothing and leaves the gauge untouched."""
    base = len(events.get_events(name=str(events.WATCHDOG_STUCK)))
    token = watchdog.watch("obs_fast_op", timeout_s=30.0)
    watchdog._scan_once()
    watchdog.unwatch(token)
    assert len(events.get_events(name=str(events.WATCHDOG_STUCK))) == base
    rec = [
        e for e in events.get_events(name=str(events.WATCHDOG_RECOVERED))
        if e.get("watch") == "obs_fast_op"
    ]
    assert not rec  # never stuck -> no recovery noise


def test_event_name_registry():
    """The taxonomy is closed and snake_case: every constant in
    util/events.py is registered, and the registry is what RT007 audits."""
    names = events.registered_event_names()
    assert "replica_state" in names
    assert "watchdog_stuck" in names
    assert "worker_death" in names
    assert "engine_admission_blocked" in names
    assert names == sorted(names)
    for n in names:
        assert n == n.lower() and " " not in n, n
