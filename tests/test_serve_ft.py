"""Serving fault-tolerance tests: failover, deadlines, drain, shedding
(reference model: python/ray/serve/tests/test_replica_request_context.py,
test_backpressure.py, test_graceful_shutdown.py)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu import testing
from ray_tpu.exceptions import BackPressureError, DeadlineExceededError
from ray_tpu.util.metrics import serve_ft_counters


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=8, resources={"TPU": 4})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_apps():
    yield
    try:
        for app in list(serve.status().keys()):
            serve.delete(app)
    except Exception:
        pass


def _wait_replicas(app, n, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        rows = [
            r for r in testing.list_serve_replicas(app)
            if r["state"] == "RUNNING" and r["pid"]
        ]
        if len(rows) == n:
            return rows
        time.sleep(0.1)
    raise TimeoutError(f"{app}: never reached {n} RUNNING replicas with pids")


def test_kill_replica_mid_request_failover(cluster):
    """Chaos kill one replica while requests are in flight: every caller
    request still completes and at least one retry is counted."""

    @serve.deployment(num_replicas=2)
    class Slow:
        def __call__(self, x):
            time.sleep(0.8)
            return x * 2

    handle = serve.run(Slow.bind(), name="killapp", _proxy=False)
    _wait_replicas("killapp", 2)
    before = serve_ft_counters()["retries"]

    responses = [handle.remote(i) for i in range(8)]
    time.sleep(0.3)  # let requests land on both replicas
    rid, pid = testing.kill_serve_replica("killapp")
    assert rid is not None and pid

    results = [r.result(timeout_s=30) for r in responses]
    assert sorted(results) == [i * 2 for i in range(8)]
    # in-flight work on the killed replica failed over (recorded caller-side)
    assert serve_ft_counters()["retries"] > before


def test_drain_on_scale_down_zero_dropped(cluster):
    """Scaling 2 -> 1 drains the victim: accepted in-flight requests all
    complete, none are dropped."""

    @serve.deployment(num_replicas=2, graceful_shutdown_timeout_s=10.0)
    class Steady:
        def __call__(self, x):
            time.sleep(0.5)
            return x + 100

    app = Steady.bind()
    handle = serve.run(app, name="drainapp", _proxy=False)
    _wait_replicas("drainapp", 2)

    responses = [handle.remote(i) for i in range(10)]
    time.sleep(0.2)  # requests accepted on both replicas
    serve.run(Steady.options(num_replicas=1).bind(), name="drainapp",
              _proxy=False, _blocking=False)

    results = [r.result(timeout_s=30) for r in responses]
    assert sorted(results) == [i + 100 for i in range(10)]
    _wait_replicas("drainapp", 1)


def test_drain_replica_replacement(cluster):
    """controller.drain_replica (the `ray_tpu chaos drain` path) retires
    one replica gracefully and the controller converges back to target."""

    @serve.deployment(num_replicas=2)
    class Svc:
        def __call__(self, x):
            return x

    serve.run(Svc.bind(), name="drainone", _proxy=False)
    rows = _wait_replicas("drainone", 2)
    victim = rows[0]["replica_id"]

    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    assert ray_tpu.get(
        controller.drain_replica.remote("drainone", victim), timeout=10
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        rows = _wait_replicas("drainone", 2)
        if all(r["replica_id"] != victim for r in rows):
            return
        time.sleep(0.1)
    raise AssertionError("drained replica was never replaced")


def test_shed_under_overload(cluster):
    """Queue-cap saturation raises typed BackPressureError fast (<1s), not
    a slow timeout."""

    @serve.deployment(
        max_ongoing_requests=1,
        max_queued_requests=1,
        request_router_config={"retry_backpressure": False},
    )
    class Busy:
        def __call__(self, x):
            time.sleep(3.0)
            return x

    handle = serve.run(Busy.bind(), name="shedapp", _proxy=False)
    _wait_replicas("shedapp", 1)

    fillers = [handle.remote(i) for i in range(2)]  # 1 ongoing + 1 queued
    time.sleep(0.5)  # let the fillers occupy slot and queue

    t0 = time.time()
    with pytest.raises(BackPressureError) as info:
        handle.remote(99).result(timeout_s=10)
    elapsed = time.time() - t0
    assert elapsed < 1.0, f"shed took {elapsed:.2f}s, expected fast rejection"
    assert info.value.retry_after_s > 0

    assert sorted(f.result(timeout_s=30) for f in fillers) == [0, 1]


def test_dead_on_arrival_rejected_by_replica(cluster):
    """A request whose deadline already passed is rejected at admission
    without running user code, and counted in replica metrics."""

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    serve.run(Echo.bind(), name="doaapp", _proxy=False)
    _wait_replicas("doaapp", 1)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    table = ray_tpu.get(controller.get_routing_table.remote("doaapp"))
    _rid, replica, _q = table["Echo"]["replicas"][0]

    with pytest.raises(Exception) as info:
        ray_tpu.get(
            replica.handle_request.remote(
                "__call__", (1,), {}, {"deadline_ts": time.time() - 5.0}
            ),
            timeout=10,
        )
    cause = getattr(info.value, "cause", info.value)
    assert isinstance(cause, DeadlineExceededError)
    metrics = ray_tpu.get(replica.get_metrics.remote(), timeout=10)
    assert metrics["doa_total"] >= 1


def test_caller_deadline_bounds_result(cluster):
    """handle.options(timeout_s=...) bounds the end-to-end wait: a stuck
    replica surfaces a TimeoutError near the deadline, not 60s later."""

    @serve.deployment
    class Stuck:
        def __call__(self, x):
            time.sleep(5.0)
            return x

    handle = serve.run(Stuck.bind(), name="deadlineapp", _proxy=False)
    _wait_replicas("deadlineapp", 1)

    t0 = time.time()
    with pytest.raises(TimeoutError):
        handle.options(timeout_s=0.5).remote(1).result()
    assert time.time() - t0 < 3.0


def test_stale_routing_table_failover(cluster):
    """A dead controller must not fail the request path once a routing
    table is cached; a never-refreshed router still raises."""
    from ray_tpu.serve.handle import Router

    @serve.deployment
    class Ok:
        def __call__(self, x):
            return x

    serve.run(Ok.bind(), name="staleapp", _proxy=False)
    _wait_replicas("staleapp", 1)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")

    class _BoomMethod:
        def remote(self, *a, **k):
            raise ConnectionError("controller unreachable")

    class _DeadController:
        def __getattr__(self, name):
            return _BoomMethod()

    router = Router(controller, "staleapp")
    rid, _ = router.pick("Ok")
    assert rid is not None

    router._controller = _DeadController()
    router._refresh(force=True)  # swallowed: serve from the stale cache
    rid2, replica = router.pick("Ok", force_refresh=True)
    assert rid2 == rid
    assert ray_tpu.get(
        replica.handle_request.remote("__call__", (7,), {}, {}), timeout=10
    ) == 7

    fresh = Router(_DeadController(), "staleapp")
    with pytest.raises(Exception):
        fresh.pick("Ok")


def test_stream_error_closes_generator(cluster):
    """A mid-stream user error surfaces once and the generator is closed —
    further iteration stops instead of hanging."""

    @serve.deployment
    class Flaky:
        def __call__(self, n):
            yield "first"
            raise ValueError("boom mid-stream")

    handle = serve.run(Flaky.bind(), name="flakystream", _proxy=False)
    _wait_replicas("flakystream", 1)

    gen = handle.options(stream=True).remote(2)
    assert next(gen) == "first"
    with pytest.raises(Exception) as info:
        next(gen)
    assert "boom mid-stream" in str(info.value)
    with pytest.raises(StopIteration):
        next(gen)


def test_local_mode_parity_new_knobs():
    """local_testing_mode accepts the failover-era handle options so code
    under test runs unchanged."""

    @serve.deployment
    class Greeter:
        def __call__(self, name):
            return f"hi {name}"

        def stream_n(self, n):
            for i in range(n):
                yield i

    handle = serve.run(Greeter.bind(), name="localft",
                       _local_testing_mode=True)
    h = handle.options(timeout_s=5.0, prefix_affinity_tokens=4)
    assert h.remote("x").result() == "hi x"
    out = list(
        h.options(method_name="stream_n", stream=True).remote(3)
    )
    assert out == [0, 1, 2]
