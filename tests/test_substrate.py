"""Unit tests for IDs, serialization, and the RPC substrate."""

import asyncio

import numpy as np
import pytest

from ray_tpu._internal import serialization
from ray_tpu._internal.event_loop import LoopThread
from ray_tpu._internal.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._internal.rpc import RpcClient, RpcServer, set_rpc_chaos
from ray_tpu.exceptions import RpcError


def test_object_id_derivation():
    job = JobID.from_int(7)
    task = TaskID.of(job)
    assert task.job_id() == job
    oid = ObjectID.for_task_return(task, 2)
    assert oid.task_id() == task
    assert oid.return_index() == 2
    assert not oid.is_put()
    put = ObjectID.for_put(task, 5)
    assert put.is_put() and put.return_index() == 5
    assert ActorID.of(job).job_id() == job


def test_id_equality_and_pickle():
    import pickle

    t = TaskID.of(JobID.from_int(1))
    assert pickle.loads(pickle.dumps(t)) == t
    assert TaskID.nil().is_nil()


def test_serialization_roundtrip_zero_copy():
    arr = np.arange(10000, dtype=np.float32)
    packed = serialization.pack({"x": arr, "y": "hello"})
    out = serialization.unpack(packed)
    assert out["y"] == "hello"
    np.testing.assert_array_equal(out["x"], arr)


def test_pack_into_matches_pack():
    value = {"a": np.ones((64, 64)), "b": list(range(100))}
    meta, bufs = serialization.serialize(value)
    size = serialization.packed_size(meta, bufs)
    dest = bytearray(size)
    written = serialization.pack_into(meta, bufs, memoryview(dest))
    assert written == size
    out = serialization.unpack(memoryview(dest))
    np.testing.assert_array_equal(out["a"], value["a"])
    assert out["b"] == value["b"]


class _EchoService:
    async def handle_echo(self, x):
        return x

    async def handle_boom(self):
        raise ValueError("boom")


def test_rpc_roundtrip():
    loop = LoopThread("test-rpc")

    async def scenario():
        server = RpcServer("echo")
        server.register_service(_EchoService())
        port = await server.start()
        client = RpcClient("127.0.0.1", port)
        out = await client.call("echo", {"a": 1})
        assert out == {"a": 1}
        with pytest.raises(ValueError, match="boom"):
            await client.call("boom")
        # concurrent calls multiplex on one connection
        outs = await asyncio.gather(*[client.call("echo", i) for i in range(50)])
        assert outs == list(range(50))
        await client.close()
        await server.stop()

    loop.run(scenario(), timeout=30)
    loop.stop()


def test_frame_v2_zero_copy_buffers():
    """v2 framing: large buffers travel out-of-band and are reconstructed
    as views over the received body — no copy."""
    from ray_tpu._internal import rpc

    arr = np.arange(1 << 16, dtype=np.uint8)
    parts = rpc._encode_frame((1, "m", (arr,), {}))
    assert len(parts) >= 3  # header, meta, at least one oob buffer
    blob = b"".join(bytes(p) for p in parts)
    body = memoryview(blob)[4:]  # strip the u32 length prefix
    req_id, method, args, kwargs = rpc._decode_body(body)
    assert (req_id, method) == (1, "m")
    out = args[0]
    np.testing.assert_array_equal(out, arr)
    # buffer identity: the decoded array aliases the received frame body
    assert np.shares_memory(out, np.frombuffer(blob, np.uint8))


def test_frame_v2_no_header_body_concat():
    """The multi-MB payload must appear in the parts list as a raw buffer
    view, not be copied into a concatenated header+body bytes object."""
    from ray_tpu._internal import rpc

    arr = np.zeros(4 << 20, dtype=np.uint8)
    parts = rpc._encode_frame((0, "m", (arr,), {}))
    assert any(
        isinstance(p, memoryview) and p.nbytes == arr.nbytes for p in parts
    )
    assert all(
        len(bytes(p)) < 1 << 20 for p in parts[:2]
    )  # header + meta stay small


def test_frame_v1_interop():
    """A legacy v1 body (raw pickle) still decodes — v2 readers accept v1
    senders."""
    import pickle

    from ray_tpu._internal import rpc

    body = pickle.dumps((7, True, {"x": 1}))
    assert rpc._decode_body(body) == (7, True, {"x": 1})


def test_v1_peer_gets_v1_replies():
    """A legacy peer sending raw-pickle (v1) frames must get raw-pickle
    replies — the C++ xlang client's minimal pickle reader cannot parse the
    v2 header (first reply body byte must be the 0x80 PROTO opcode)."""
    import pickle
    import struct

    loop = LoopThread("test-v1peer")

    async def scenario():
        server = RpcServer("echo")
        server.register_service(_EchoService())
        port = await server.start()
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        body = pickle.dumps((1, "echo", ("hi",), {}))
        writer.write(struct.pack("<I", len(body)) + body)
        await writer.drain()
        (length,) = struct.unpack("<I", await reader.readexactly(4))
        reply = await reader.readexactly(length)
        assert reply[0] == 0x80, hex(reply[0])  # v1 raw pickle, no v2 header
        assert pickle.loads(reply) == (1, True, "hi")
        writer.close()
        await server.stop()

    loop.run(scenario(), timeout=30)
    loop.stop()


def test_rpc_oob_roundtrip_over_socket():
    """Socket-level v2 round trip: arrays cross client->server->client with
    the out-of-band counters advancing on both directions."""
    from ray_tpu._internal import rpc

    loop = LoopThread("test-v2")

    async def scenario():
        server = RpcServer("echo")
        server.register_service(_EchoService())
        port = await server.start()
        client = RpcClient("127.0.0.1", port)
        before = rpc.frame_stats()
        arr = np.arange(1 << 18, dtype=np.float32)
        out = await client.call("echo", arr)
        np.testing.assert_array_equal(out, arr)
        after = rpc.frame_stats()
        assert after["oob_buffers_sent"] - before["oob_buffers_sent"] >= 2
        assert (
            after["oob_buffers_received"] - before["oob_buffers_received"] >= 2
        )
        # closures still work via the cloudpickle fallback
        out = await client.call("echo", lambda: 41)
        assert out() == 41
        await client.close()
        await server.stop()

    loop.run(scenario(), timeout=30)
    loop.stop()


def test_recv_loop_survives_non_exception_error_payload():
    """A hostile/malformed server sending a non-exception error payload must
    surface as RpcError on that call — not TypeError killing the recv loop."""
    loop = LoopThread("test-baderr")

    async def scenario():
        from ray_tpu._internal.rpc import _write_frame

        async def on_client(reader, writer):
            # speak just enough protocol: echo an error for every request
            from ray_tpu._internal.rpc import _read_frame

            while True:
                try:
                    req_id, method, args, kwargs = await _read_frame(reader)
                except Exception:
                    return
                if req_id == -1:
                    continue
                if method == "bad":
                    _write_frame(writer, (req_id, False, "not an exception"))
                else:
                    _write_frame(writer, (req_id, True, "fine"))
                await writer.drain()

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = RpcClient("127.0.0.1", port)
        with pytest.raises(RpcError, match="non-exception"):
            await client.call("bad")
        # the recv loop survived: the connection still serves calls
        assert await client.call("ok") == "fine"
        await client.close()
        server.close()
        await server.wait_closed()

    loop.run(scenario(), timeout=30)
    loop.stop()


def test_auth_preamble_gates_v2_frames():
    """With a token set, a v2 frame from a client that skipped the auth
    preamble is dropped before any parsing."""
    from ray_tpu._internal import rpc

    loop = LoopThread("test-v2auth")

    async def scenario():
        rpc.set_auth_token("secret")
        try:
            server = RpcServer("echo")
            server.register_service(_EchoService())
            port = await server.start()
            # raw connection, no preamble: write a valid v2 frame
            rpc.set_auth_token(None)  # encode/connect without the token
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            rpc.set_auth_token("secret")
            writer.writelines(rpc._encode_frame((1, "echo", (1,), {})))
            await writer.drain()
            # server drops the connection without answering
            assert await reader.read(1) == b""
            writer.close()
            await server.stop()
        finally:
            rpc.set_auth_token(None)

    loop.run(scenario(), timeout=30)
    loop.stop()


def test_rpc_chaos_injection():
    loop = LoopThread("test-chaos")

    async def scenario():
        set_rpc_chaos({"echo": 1.0})
        try:
            server = RpcServer("echo")
            server.register_service(_EchoService())
            port = await server.start()
            client = RpcClient("127.0.0.1", port)
            with pytest.raises(RpcError, match="injected"):
                await client.call("echo", 1)
            await client.close()
            await server.stop()
        finally:
            set_rpc_chaos({})

    loop.run(scenario(), timeout=30)
    loop.stop()
