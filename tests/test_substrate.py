"""Unit tests for IDs, serialization, and the RPC substrate."""

import asyncio

import numpy as np
import pytest

from ray_tpu._internal import serialization
from ray_tpu._internal.event_loop import LoopThread
from ray_tpu._internal.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._internal.rpc import RpcClient, RpcServer, set_rpc_chaos
from ray_tpu.exceptions import RpcError


def test_object_id_derivation():
    job = JobID.from_int(7)
    task = TaskID.of(job)
    assert task.job_id() == job
    oid = ObjectID.for_task_return(task, 2)
    assert oid.task_id() == task
    assert oid.return_index() == 2
    assert not oid.is_put()
    put = ObjectID.for_put(task, 5)
    assert put.is_put() and put.return_index() == 5
    assert ActorID.of(job).job_id() == job


def test_id_equality_and_pickle():
    import pickle

    t = TaskID.of(JobID.from_int(1))
    assert pickle.loads(pickle.dumps(t)) == t
    assert TaskID.nil().is_nil()


def test_serialization_roundtrip_zero_copy():
    arr = np.arange(10000, dtype=np.float32)
    packed = serialization.pack({"x": arr, "y": "hello"})
    out = serialization.unpack(packed)
    assert out["y"] == "hello"
    np.testing.assert_array_equal(out["x"], arr)


def test_pack_into_matches_pack():
    value = {"a": np.ones((64, 64)), "b": list(range(100))}
    meta, bufs = serialization.serialize(value)
    size = serialization.packed_size(meta, bufs)
    dest = bytearray(size)
    written = serialization.pack_into(meta, bufs, memoryview(dest))
    assert written == size
    out = serialization.unpack(memoryview(dest))
    np.testing.assert_array_equal(out["a"], value["a"])
    assert out["b"] == value["b"]


class _EchoService:
    async def handle_echo(self, x):
        return x

    async def handle_boom(self):
        raise ValueError("boom")


def test_rpc_roundtrip():
    loop = LoopThread("test-rpc")

    async def scenario():
        server = RpcServer("echo")
        server.register_service(_EchoService())
        port = await server.start()
        client = RpcClient("127.0.0.1", port)
        out = await client.call("echo", {"a": 1})
        assert out == {"a": 1}
        with pytest.raises(ValueError, match="boom"):
            await client.call("boom")
        # concurrent calls multiplex on one connection
        outs = await asyncio.gather(*[client.call("echo", i) for i in range(50)])
        assert outs == list(range(50))
        await client.close()
        await server.stop()

    loop.run(scenario(), timeout=30)
    loop.stop()


def test_rpc_chaos_injection():
    loop = LoopThread("test-chaos")

    async def scenario():
        set_rpc_chaos({"echo": 1.0})
        try:
            server = RpcServer("echo")
            server.register_service(_EchoService())
            port = await server.start()
            client = RpcClient("127.0.0.1", port)
            with pytest.raises(RpcError, match="injected"):
                await client.call("echo", 1)
            await client.close()
            await server.stop()
        finally:
            set_rpc_chaos({})

    loop.run(scenario(), timeout=30)
    loop.stop()
