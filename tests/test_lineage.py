"""Lineage-based object reconstruction (reference: ObjectRecoveryManager,
src/ray/core_worker/object_recovery_manager.h:41 + TaskManager lineage
pinning): when every copy of a task-produced object is lost, the owner
re-executes the creating task — transitively for lost args — bounded by
max_retries."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.exceptions import ObjectLostError


@pytest.fixture
def two_node_cluster():
    # tight death-detection window: these tests block on the cluster
    # noticing a killed node. Must go to Cluster(), not connect() — the
    # GCS reads its config when the head node is created.
    cluster = Cluster(
        head_node_args=dict(num_cpus=2),
        _system_config={"health_check_timeout_s": 3.0},
    )
    cluster.add_node(resources={"side": 2.0}, num_cpus=2)
    cluster.connect()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def _pids(cluster):
    return {
        n.node_id.hex(): n for n in cluster.list_nodes()
    }


def test_reconstruction_after_node_death(two_node_cluster):
    cluster = two_node_cluster

    @ray_tpu.remote(max_retries=3)
    def produce(tag):
        # big enough for plasma (> max_direct_call_object_size), primary
        # copy lives on the executing node only
        return np.full((200_000,), tag, np.float32)

    # pin execution to the side node so the head holds NO copy
    ref = produce.options(resources={"side": 1.0}).remote(7)
    # wait for completion WITHOUT fetching (a get would copy it local)
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60, fetch_local=False)
    assert ready

    victim = next(n for n in cluster.list_nodes() if not n.head)
    cluster.remove_node(victim, graceful=False)

    # the resubmitted task still demands the 'side' resource: add a fresh
    # side node, like a machine replacement — reconstruction must re-lease
    # through normal scheduling
    cluster.add_node(resources={"side": 2.0}, num_cpus=2)

    value = ray_tpu.get(ref, timeout=120)
    assert value.shape == (200_000,)
    assert float(value[0]) == 7.0


@pytest.mark.slow
def test_transitive_reconstruction(two_node_cluster):
    """A lost object whose creating task needs another lost object: both
    re-execute (the re-executed consumer's arg fetch fails on its executor,
    which asks the owner to reconstruct the producer)."""
    cluster = two_node_cluster

    @ray_tpu.remote(max_retries=3)
    def base():
        return np.ones((150_000,), np.float32)

    @ray_tpu.remote(max_retries=3)
    def double(x):
        return x * 2.0

    a = base.options(resources={"side": 1.0}).remote()
    b = double.options(resources={"side": 1.0}).remote(a)
    ready, _ = ray_tpu.wait([b], num_returns=1, timeout=60, fetch_local=False)
    assert ready

    victim = next(n for n in cluster.list_nodes() if not n.head)
    cluster.remove_node(victim, graceful=False)
    cluster.add_node(resources={"side": 2.0}, num_cpus=2)

    value = ray_tpu.get(b, timeout=180)
    assert float(value[0]) == 2.0


def test_non_retriable_task_not_reconstructed(two_node_cluster):
    cluster = two_node_cluster

    @ray_tpu.remote(max_retries=0)
    def produce():
        return np.zeros((150_000,), np.float32)

    ref = produce.options(resources={"side": 1.0}).remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60, fetch_local=False)
    assert ready

    victim = next(n for n in cluster.list_nodes() if not n.head)
    cluster.remove_node(victim, graceful=False)

    with pytest.raises(ObjectLostError):
        ray_tpu.get(ref, timeout=60)
