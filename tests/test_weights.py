"""Weight plane (ray_tpu.weights): registry versioning + GC, pinned
subscribes, staleness/prefetch, spill exemption, consumer wiring, and the
rllib put-once serialization regression guard."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import weights
from ray_tpu.weights import WeightHandle, WeightPublisher, WeightSubscriber


def _params(scale=1.0, n=200_000):
    return {
        "dense": {"w": (np.arange(n, dtype=np.float32) * scale)},
        "bias": np.full(16, scale, np.float32),
    }


# -- manifest (no cluster) ---------------------------------------------------


def test_chunk_pytree_roundtrip_and_split():
    from ray_tpu.weights.manifest import assemble_pytree, chunk_pytree

    params = {
        "a": np.arange(1000, dtype=np.float32),  # 4000 B
        "b": np.ones((100, 10), np.float64),     # 8000 B
        "c": np.int32(7),                        # scalar leaf
    }
    treedef_blob, chunks, total = chunk_pytree(params, chunk_size=5000)
    # greedy packing: "a" alone busts 5000 with "b"; arrays never split
    assert len(chunks) >= 2
    assert total == 4000 + 8000 + 4
    rebuilt = assemble_pytree(treedef_blob, chunks)
    np.testing.assert_array_equal(rebuilt["a"], params["a"])
    np.testing.assert_array_equal(rebuilt["b"], params["b"])
    assert rebuilt["c"] == 7


def test_binomial_tree_shape():
    from ray_tpu.runtime.gcs.weight_registry import _tree_depth, _tree_parent

    assert _tree_parent(0) is None          # seed pulls from the publisher
    assert _tree_parent(1) == 0
    assert _tree_parent(2) == 0
    assert _tree_parent(3) == 1
    assert _tree_parent(6) == 2
    assert _tree_parent(12) == 4
    assert _tree_depth(1) == 1
    assert _tree_depth(2) == 2
    assert _tree_depth(4) == 3   # deepest is position 3 (0b11): pub→0→1→3
    assert _tree_depth(5) == 3
    assert _tree_depth(8) == 4
    # every node's parent has a strictly smaller position (acyclic, rooted),
    # and the hop count never exceeds the advertised depth
    for n in range(1, 64):
        for p in range(1, n):
            assert 0 <= _tree_parent(p) < p
            hops, q = 1, p
            while q > 0:
                q = _tree_parent(q)
                hops += 1
            assert hops <= _tree_depth(n)


# -- publish / subscribe -----------------------------------------------------


def test_publish_fetch_versions_and_staleness(cluster):
    pub = WeightPublisher("t/model")
    v1 = pub.publish(_params(1.0))
    assert v1 == 1
    sub = WeightSubscriber("t/model")
    version, got = sub.get()
    assert version == 1
    np.testing.assert_array_equal(got["dense"]["w"], _params(1.0)["dense"]["w"])
    assert sub.staleness() == 0

    v2 = pub.publish(_params(2.0))
    assert v2 == 2
    assert sub.staleness() == 1  # gauge: one version behind head
    from ray_tpu.util import metrics

    assert metrics.weights_staleness("t/model") == 1.0
    version, got = sub.get()
    assert version == 2
    np.testing.assert_array_equal(got["bias"], np.full(16, 2.0, np.float32))
    assert sub.staleness() == 0
    sub.release()


def test_multi_chunk_publish(cluster):
    """A model larger than weights_chunk_size splits into several store
    objects and reassembles exactly."""
    pub = WeightPublisher("t/chunky", chunk_size=256 * 1024)
    params = {f"layer{i}": np.full(100_000, i, np.float32) for i in range(4)}
    pub.publish(params)
    from ray_tpu.util.state import list_weights

    rows = {r["name"]: r for r in list_weights()}
    assert rows["t/chunky"]["num_chunks"] >= 4
    sub = WeightSubscriber("t/chunky")
    _, got = sub.get()
    for i in range(4):
        np.testing.assert_array_equal(got[f"layer{i}"], params[f"layer{i}"])
    sub.release()


def test_weight_handle_resolve(cluster):
    handle = weights.publish("t/handle", _params(3.0))
    assert isinstance(handle, WeightHandle) and handle.version == 1
    resolved = weights.resolve(handle)
    np.testing.assert_array_equal(
        resolved["bias"], np.full(16, 3.0, np.float32)
    )
    assert weights.resolve({"plain": 1}) == {"plain": 1}  # passthrough


def test_prefetch_adopts_instantly(cluster):
    pub = WeightPublisher("t/prefetch")
    pub.publish(_params(1.0))
    sub = WeightSubscriber("t/prefetch")
    sub.get()
    pub.publish(_params(2.0))
    assert sub.prefetch(block=True) == 2
    version, got = sub.get()  # served from the prefetched pin, no refetch
    assert version == 2
    np.testing.assert_array_equal(got["bias"], np.full(16, 2.0, np.float32))
    sub.release()


# -- registry unit: release queue, pin leases, tree repair -------------------


class _FakeGcs:
    """Storage/publisher/config stand-in so GcsWeightRegistry runs without a
    server (the registry only touches these three attributes)."""

    class _Storage:
        def __init__(self):
            self.tables = {}

        def put(self, table, key, value):
            self.tables.setdefault(table, {})[key] = value

        def delete(self, table, key):
            self.tables.get(table, {}).pop(key, None)

        def get_all(self, table):
            return dict(self.tables.get(table, {}))

    class _Publisher:
        def __init__(self):
            self.events = []

        def publish(self, channel, msg):
            self.events.append((channel, msg))

    def __init__(self, **config_overrides):
        from ray_tpu._internal.config import Config

        self.storage = self._Storage()
        self.publisher = self._Publisher()
        self.config = Config()
        for key, value in config_overrides.items():
            setattr(self.config, key, value)


def _registry(**config_overrides):
    from ray_tpu.runtime.gcs.weight_registry import GcsWeightRegistry

    return GcsWeightRegistry(_FakeGcs(**config_overrides))


def test_registry_unpin_never_consumes_release_queue():
    """A release triggered by a subscriber unpin must stay queued for the
    publisher: draining it into the (ignored) unpin reply would leak the
    version's chunks for the rest of the run."""
    reg = _registry()
    r1 = reg.publish("m", b"m1")
    assert r1["version"] == 1 and r1["released"] == [] and r1["live"] == [1]
    reg.pin("m", 1, "reader-a")
    r2 = reg.publish("m", b"m2")
    assert r2["released"] == []  # v1 pinned: survives the supersede
    reg.unpin("m", 1, "reader-a")  # tombstones v1 ...
    assert reg.get("m", 1) is None
    collected = reg.collect("m")  # ... queued until the publisher drains
    assert collected["released"] == [1] and collected["live"] == [2]
    assert reg.collect("m")["released"] == []  # drained exactly once


def test_registry_publish_reply_delivers_queued_releases():
    """The steady-state rllib flow: version N is still pinned when N+1
    publishes, so its release happens at a later subscriber unpin — the
    NEXT publish reply must deliver it (no explicit collect needed)."""
    reg = _registry()
    reg.publish("m", b"m1")
    reg.pin("m", 1, "r")
    reg.publish("m", b"m2")
    reg.unpin("m", 1, "r")  # queued, not delivered
    r3 = reg.publish("m", b"m3")
    assert set(r3["released"]) == {1, 2} and r3["live"] == [3]


def test_registry_pin_lease_expiry_reaps_dead_reader():
    """A pin not refreshed within weights_pin_lease_s stops blocking GC: a
    crashed env-runner re-pins under a fresh reader_id, so its old pin would
    otherwise leak forever."""
    import time as _time

    reg = _registry(weights_pin_lease_s=0.05)
    reg.publish("m", b"m1")
    reg.pin("m", 1, "dead-reader")
    reg.publish("m", b"m2")
    assert reg.get("m", 1) is not None  # lease still fresh: pin holds
    _time.sleep(0.06)
    collected = reg.collect("m")  # GC pass reaps the lapsed lease
    assert collected["released"] == [1]
    assert reg.get("m", 1) is None


def test_registry_tree_prunes_dead_and_hung_parents():
    """Node death drops a node from the tree immediately; two fallback
    reports prune a hung-but-connectable parent. Surviving children
    reparent via recomputed positions on their next plan()."""
    reg = _registry()
    reg.publish("m", b"m1")
    a, b, c = ("n1", 1), ("n2", 1), ("n3", 1)
    assert reg.plan("m", a)["position"] == 0
    assert reg.plan("m", b)["position"] == 1
    plan_c = reg.plan("m", c)
    assert plan_c["position"] == 2 and tuple(plan_c["parent"]) == a

    reg.on_node_death(a)
    plan_b = reg.plan("m", b)
    assert plan_b["position"] == 0 and plan_b["parent"] is None
    plan_c = reg.plan("m", c)
    assert plan_c["position"] == 1 and tuple(plan_c["parent"]) == b
    assert plan_c["num_nodes"] == 2

    reg.report_fallback("m", b)  # one report: benefit of the doubt
    assert tuple(reg.plan("m", c)["parent"]) == b
    reg.report_fallback("m", b)  # second report prunes the hung parent
    plan_c = reg.plan("m", c)
    assert plan_c["position"] == 0 and plan_c["parent"] is None


# -- GC: tombstones gated on pinned readers ---------------------------------


def test_superseded_version_gc_waits_for_pinned_reader(cluster):
    pub = WeightPublisher("t/gc")
    pub.publish(_params(1.0))
    sub = WeightSubscriber("t/gc")
    version, _ = sub.get()
    assert version == 1

    # v1 is pinned: publishing v2 must NOT tombstone it
    pub.publish(_params(2.0))
    from ray_tpu.util.state import _gcs_call

    resolved = _gcs_call("weights_get", "t/gc", 1)
    assert resolved is not None and resolved["version"] == 1
    assert 1 in pub._held  # publisher still holds v1's chunk refs

    # moving the subscriber to head unpins v1 -> tombstoned + released
    version, _ = sub.get()
    assert version == 2
    assert _gcs_call("weights_get", "t/gc", 1) is None
    pub.collect()
    assert 1 not in pub._held
    assert 2 in pub._held  # head version stays resident
    sub.release()


def test_release_unpins_and_head_survives(cluster):
    pub = WeightPublisher("t/rel")
    pub.publish(_params(1.0))
    with WeightSubscriber("t/rel") as sub:
        sub.get()
    # released subscriber leaves head resolvable and re-subscribable
    sub2 = WeightSubscriber("t/rel")
    version, _ = sub2.get()
    assert version == 1
    sub2.release()


def test_registry_gc_survives_gcs_restart(shutdown_only, tmp_path):
    """GCS-restart reload keeps the head version resolvable; tombstoned
    versions stay tombstoned (mirrors the actor-tombstone compaction)."""
    node = ray_tpu.init(
        num_cpus=2,
        _system_config={"gcs_storage_path": str(tmp_path / "gcs.db")},
    )
    pub = WeightPublisher("t/ft")
    pub.publish(_params(1.0))
    pub.publish(_params(5.0))  # supersedes + tombstones v1 (no pins)

    node.kill_gcs_for_testing()
    node.restart_gcs_for_testing()

    sub = WeightSubscriber("t/ft")
    version, got = sub.get(timeout=60)
    assert version == 2
    np.testing.assert_array_equal(got["bias"], np.full(16, 5.0, np.float32))
    from ray_tpu.util.state import _gcs_call

    assert _gcs_call("weights_get", "t/ft", 1) is None  # tombstone survived
    rows = {r["name"]: r for r in _gcs_call("weights_list")}
    assert rows["t/ft"]["head"] == 2
    sub.release()


def test_publish_drains_subscriber_unpinned_versions(cluster):
    """Versions released by subscriber unpins are freed on the publisher's
    next publish — no explicit collect() required (the unpin reply is
    ignored by subscribers, so the release must ride the publish path)."""
    pub = WeightPublisher("t/drain")
    pub.publish(_params(1.0))
    sub = WeightSubscriber("t/drain")
    sub.get()
    pub.publish(_params(2.0))  # v1 still pinned by the subscriber
    assert 1 in pub._held
    sub.get()  # adopt v2 -> unpin v1 -> tombstone queued in the registry
    pub.publish(_params(3.0))  # publish reply delivers the queued release
    assert 1 not in pub._held
    assert 2 in pub._held and 3 in pub._held  # v2 pinned, v3 head
    sub.release()


def test_resolve_falls_back_to_head_after_gc(cluster):
    """A WeightHandle holds no registry pin, so its exact version can
    tombstone before resolve; resolve() must serve head (one version of
    staleness) instead of spinning out the timeout and crashing the task."""
    import time as _time

    handle1 = weights.publish("t/fb", _params(1.0))
    weights.publish("t/fb", _params(2.0))  # no pins: v1 tombstones now
    t0 = _time.monotonic()
    value = weights.resolve(handle1)
    assert _time.monotonic() - t0 < 10.0  # no full-timeout spin
    np.testing.assert_array_equal(value["bias"], np.full(16, 2.0, np.float32))

    # an explicit pinned get without fallback fails fast with KeyError
    # (the version is gone for good — waiting cannot bring it back)
    sub = WeightSubscriber("t/fb")
    with pytest.raises(KeyError):
        sub.get(1, timeout=30.0)
    sub.release()


def test_prefetch_result_losing_race_is_released(cluster):
    """A background prefetch completing after get() adopted the same (or a
    newer) version must release its pins instead of parking an orphan
    _PinnedVersion that nothing ever pops."""
    from ray_tpu.weights.subscriber import _PinnedVersion

    pub = WeightPublisher("t/race")
    pub.publish(_params(1.0))
    sub = WeightSubscriber("t/race")
    sub.get()
    pub.publish(_params(2.0))
    sub.get()  # current = v2
    stale = _PinnedVersion(1, {"w": 0}, None, [])
    assert sub._offer_prefetched(1, stale) is False  # raced: released
    assert sub._prefetched == {}
    fresh = _PinnedVersion(3, {"w": 1}, None, [])
    assert sub._offer_prefetched(3, fresh) is True  # newer: parked
    assert 3 in sub._prefetched
    sub.release()


def test_pin_lease_heartbeat_keeps_idle_reader_alive(shutdown_only):
    """staleness()/get() re-pin held versions at half-lease, so only readers
    that actually died lose their pins to the registry's lease reaper."""
    import time as _time

    node = ray_tpu.init(num_cpus=2)
    pub = WeightPublisher("t/lease")
    pub.publish(_params(1.0))
    sub = WeightSubscriber("t/lease")
    sub.get()
    pub.publish(_params(2.0))  # v1 superseded but pinned

    registry = node.gcs.weight_registry
    model = registry._models["t/lease"]
    # age both the registry lease and the subscriber's local stamp far past
    # the window: without a heartbeat the next GC pass would reap the pin
    model.pins[1][sub.reader_id] = _time.time() - 100 * 600
    sub._current.pinned_at = 0.0
    assert sub.staleness() == 1  # heartbeats the v1 pin
    assert model.pins[1][sub.reader_id] > _time.time() - 60
    pub.collect()  # GC pass: v1 must survive, its lease is fresh again
    from ray_tpu.util.state import _gcs_call

    assert _gcs_call("weights_get", "t/lease", 1) is not None
    sub.release()


# -- spill exemption ---------------------------------------------------------


def test_store_weight_pin_exempt_from_spill_and_eviction():
    """Unit: a weight-pinned object is invisible to lru_spillable and to
    LRU eviction until unpinned (runtime/object_store/store.py)."""
    from ray_tpu._internal.ids import ObjectID
    from ray_tpu.exceptions import ObjectStoreFullError
    from ray_tpu.runtime.object_store.store import ObjectStore

    store = ObjectStore(capacity_bytes=1000, session_id="wpin")
    chunk = ObjectID.from_random()
    store.create_and_write(chunk, b"w" * 400)
    store.pin_primary(chunk)  # publisher chunks are primary copies
    assert store.lru_spillable() == chunk
    assert store.pin_weight(chunk)
    assert store.lru_spillable() is None  # pinned: not spillable
    # eviction under pressure must pick other objects, never the pinned one
    other = ObjectID.from_random()
    store.create_and_write(other, b"o" * 400)
    with pytest.raises(ObjectStoreFullError):
        store.create(ObjectID.from_random(), 900)  # can't evict the pin
    assert store.contains(chunk)
    assert store.free_if_unpinned(chunk) is False  # free also deferred
    store.unpin_weight(chunk)
    assert store.lru_spillable() == chunk  # spillable again
    store.shutdown()


def test_spill_pressure_during_inflight_subscribe(shutdown_only):
    """Integration: under object-store pressure, spilling victimizes other
    primaries while a subscribed version's chunks stay resident."""
    ray_tpu.init(
        num_cpus=2,
        object_store_memory=24 * 1024 * 1024,
        _system_config={"object_transfer_native_enabled": False},
    )
    pub = WeightPublisher("t/spill")
    pub.publish({"w": np.ones(1_000_000, np.float32)})  # 4 MB chunk
    sub = WeightSubscriber("t/spill")
    _, got = sub.get()  # chunks now weight-pinned locally

    node = ray_tpu._worker_api.get_node()
    chunk_ids = {c.object_id for c in sub._current.manifest.chunks}
    # fill the store with other primaries until spill kicks in
    filler = [ray_tpu.put(np.full(1_000_000, i, np.float32)) for i in range(8)]
    spilled = set(getattr(node.raylet, "_spilled", {}))
    assert not (spilled & chunk_ids), "pinned weight chunk was spilled"
    # the subscribed value still reads correctly (zero-copy views intact)
    np.testing.assert_array_equal(got["w"], np.ones(1_000_000, np.float32))
    del filler
    sub.release()


# -- consumers: train checkpoint publish + serve/llm hot reload -------------


def _wp_train_loop(config):
    import os
    import pickle
    import tempfile

    import numpy as np

    from ray_tpu import train as rt_train

    ctx = rt_train.get_context()
    for epoch in range(config["epochs"]):
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp(prefix="wp_ckpt_")
            with open(os.path.join(d, "state.pkl"), "wb") as f:
                pickle.dump({"w": np.full(64, float(epoch), np.float32)}, f)
            rt_train.report(
                {"epoch": epoch},
                checkpoint=rt_train.Checkpoint.from_directory(d),
            )
        else:
            rt_train.report({"epoch": epoch})


def test_train_checkpoint_publish_callback(shutdown_only, tmp_path):
    """Every reported checkpoint becomes one weight-plane version."""
    import ray_tpu.train as rt_train

    ray_tpu.init(num_cpus=4)
    trainer = rt_train.DataParallelTrainer(
        _wp_train_loop,
        train_loop_config={"epochs": 2},
        scaling_config=rt_train.ScalingConfig(num_workers=2),
        run_config=rt_train.RunConfig(
            name="wp-run",
            storage_path=str(tmp_path),
            callbacks=[rt_train.WeightPublishCallback("t/train")],
        ),
    )
    trainer.fit()
    version, state = weights.fetch("t/train")
    assert version == 2  # one version per checkpointed epoch
    np.testing.assert_array_equal(state["w"], np.full(64, 1.0, np.float32))


def test_llm_serve_hot_reload(ray_start_regular):
    """llm replica subscribed to the weight plane: serves the published
    version and hot-swaps on reload_weights without a restart."""
    import jax

    try:
        from ray_tpu.models.llama import init_params
    except TypeError:
        # old jax: custom_partitioning.def_partition has no sharding_rule,
        # so the llama stack (ops.rmsnorm) is unimportable on this box
        pytest.skip("jax too old for custom_partitioning sharding_rule")

    from ray_tpu import serve
    from ray_tpu.llm.config import LLMConfig
    from ray_tpu.llm.serving import build_llm_deployment
    from ray_tpu.parallel.sharding import unbox_params

    llm_config = LLMConfig(
        model_id="llama-tiny",
        max_seq_len=64,
        max_new_tokens=4,
        resources_per_replica={"CPU": 1.0},
    )
    params = unbox_params(
        init_params(llm_config.build_model_config(), jax.random.PRNGKey(0))
    )
    weights.publish("t/llm", params)

    app = build_llm_deployment(llm_config, weights_name="t/llm")
    serve.start(proxy=False)
    handle = serve.run(app, name="llm-wp", route_prefix=None, _proxy=False)
    try:
        out = handle.remote(
            {"token_ids": [1, 2, 3, 4], "max_new_tokens": 2}
        ).result(timeout_s=120)
        assert len(out["token_ids"]) == 2
        info = handle.weights_info.remote().result(timeout_s=60)
        assert info["version"] == 1 and info["staleness"] == 0

        weights.publish("t/llm", jax.tree.map(lambda a: a * 0, params))
        info = handle.reload_weights.remote().result(timeout_s=120)
        assert info["version"] == 2 and info["staleness"] == 0
        out2 = handle.remote(
            {"token_ids": [1, 2, 3, 4], "max_new_tokens": 2}
        ).result(timeout_s=120)
        assert len(out2["token_ids"]) == 2  # still serving, new weights
    finally:
        serve.shutdown()


# -- rllib put-once regression guard ----------------------------------------


@pytest.mark.slow
def test_rllib_params_serialized_once_per_iteration(shutdown_only):
    """Params must travel once per train() iteration (api.put + ObjectRef),
    never inline per env-runner: with N runners, driver-side task-arg bytes
    stay far below N × params size (util/metrics serialization counters)."""
    import ray_tpu.rllib as rllib
    from ray_tpu._internal import serialization
    from ray_tpu.util import metrics

    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    algo = (
        rllib.PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=3, num_envs_per_env_runner=1,
                     rollout_fragment_length=16)
        .build()
    )
    try:
        algo.train()  # warm: function export, worker start, first put
        params_bytes = len(serialization.pack(algo.learner.get_params()))
        assert params_bytes > 10_000  # the guard below must be meaningful

        before = metrics.object_serializations()
        algo.train()
        after = metrics.object_serializations()

        task_arg_delta = after["task_arg"]["bytes"] - before.get(
            "task_arg", {}
        ).get("bytes", 0.0)
        put_delta = after["put"]["bytes"] - before.get("put", {}).get(
            "bytes", 0.0
        )
        # inline args for one iteration (3 sample calls + misc) must not
        # carry the params pytree even once
        assert task_arg_delta < params_bytes, (
            f"params leaked into inline task args: {task_arg_delta} bytes "
            f"vs params {params_bytes}"
        )
        # exactly one params-sized put per iteration (not one per runner)
        assert put_delta >= params_bytes
        assert put_delta < 2 * params_bytes, (
            f"params serialized more than once: {put_delta} bytes "
            f"vs params {params_bytes}"
        )
    finally:
        algo.stop()


def test_rllib_weight_plane_mode(shutdown_only):
    """config.weight_sync(use_weight_plane=True): runners resolve a
    WeightHandle through the broadcast plane and training still learns."""
    import ray_tpu.rllib as rllib

    ray_tpu.init(num_cpus=4, resources={"TPU": 4})
    algo = (
        rllib.PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=1,
                     rollout_fragment_length=16)
        .weight_sync(use_weight_plane=True, weight_plane_name="t/ppo")
        .build()
    )
    try:
        result = algo.train()
        assert result["num_env_steps_sampled"] > 0
        result = algo.train()
        assert result["training_iteration"] == 2
        from ray_tpu.util.state import list_weights

        rows = {r["name"]: r for r in list_weights()}
        assert rows["t/ppo"]["head"] >= 1
    finally:
        algo.stop()
