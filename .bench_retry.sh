#!/bin/bash
# Retry bench.py until the axon tunnel is back; append the first successful
# measurement to /tmp/bench_success.json and exit.
cd /root/repo
for i in $(seq 1 40); do
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[retry $i] tunnel alive, running bench" >&2
    out=$(timeout 1500 python bench.py 2>/tmp/bench_retry_stderr.log)
    echo "$out"
    val=$(echo "$out" | python -c "import json,sys; print(json.loads(sys.stdin.readline())['value'])" 2>/dev/null)
    if [ -n "$val" ] && [ "$val" != "0.0" ]; then
      echo "$out" > /tmp/bench_success.json
      exit 0
    fi
    echo "[retry $i] bench returned zero/failed" >&2
  else
    echo "[retry $i] tunnel down" >&2
  fi
  sleep 300
done
exit 1
