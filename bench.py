"""Benchmark: Llama-family training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures tokens/sec for full train steps (fwd + bwd + adamw) on a scaled
Llama config in bfloat16 with the Pallas flash-attention kernel. K steps run
inside one jitted lax.scan so device compute dominates and per-dispatch
tunnel/host latency is amortized away.

The reference publishes no throughput numbers (BASELINE.md: "published" is
empty), so vs_baseline is the ratio against a fixed MFU target recorded
below — it rises as the kernels/schedule improve across rounds.
"""

from __future__ import annotations

import json
import time


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import LlamaConfig, init_params, next_token_loss
    from ray_tpu.parallel.sharding import unbox_params

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=8, n_heads=16, n_kv_heads=16,
            intermediate=2816, max_seq_len=1024, remat=False,
        )
        batch, steps = 8, 20
    else:  # smoke fallback for dev boxes
        cfg = LlamaConfig.tiny()
        batch, steps = 2, 3
    seq = cfg.max_seq_len

    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    optimizer = optax.adamw(1e-3)
    opt_state = optimizer.init(params)

    def loss_fn(p, tokens):
        return next_token_loss(cfg, None, p, tokens)

    def one_step(carry, tokens):
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens)
        updates, s2 = optimizer.update(grads, s, p)
        return (optax.apply_updates(p, updates), s2), loss

    @jax.jit
    def run(p, s, data):
        (p2, s2), losses = jax.lax.scan(one_step, (p, s), data)
        return p2, s2, losses

    # Timing through the remote-execution tunnel: block_until_ready does not
    # round-trip, so force scalar materialization, and cancel the fixed
    # dispatch overhead by timing two different step counts and using the
    # slope (dt(2K steps) - dt(K steps)) / K.
    def timed(n_steps, seed):
        def make_data(s):
            return jax.random.randint(
                jax.random.PRNGKey(s), (n_steps, batch, seq), 0, cfg.vocab_size
            )

        _, _, losses = run(params, opt_state, make_data(seed + 1000))
        float(losses[-1])  # compile + warm
        # time with DIFFERENT data: the tunnel may serve repeated identical
        # dispatches from cache
        t0 = time.perf_counter()
        _, _, losses = run(params, opt_state, make_data(seed))
        float(losses[-1])
        return time.perf_counter() - t0

    t_short = timed(steps, seed=1)
    t_long = timed(2 * steps, seed=2)
    dt = max(t_long - t_short, 1e-9)

    tokens_per_sec = steps * batch * seq / dt

    # rough model FLOPs/token (6 * params for fwd+bwd, attention extra)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.dim * seq * 0.5
    achieved = tokens_per_sec * flops_per_token
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = achieved / peak
    # vs_baseline: achieved MFU against a 40% MFU target for this model size
    vs_baseline = mfu / 0.40

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
