"""Benchmark: Llama-family training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures tokens/sec for full train steps (fwd + bwd + adamw) on a scaled
Llama config in bfloat16 with the Pallas flash-attention kernel. K steps run
inside one jitted lax.scan so device compute dominates and per-dispatch
tunnel/host latency is amortized away.

TPU detection goes through ray_tpu._internal.platform.is_tpu_backend (device
platform/device_kind, accepting the "axon" remote-dispatch plugin) — NOT
jax.default_backend(), which reports the plugin name and sent round 1 down
the interpret-mode path.

The run keeps a wall-clock budget (RAY_TPU_BENCH_BUDGET_S, default 420s):
it always produces a JSON line from whatever measurements completed rather
than overrunning the driver's timeout.

The reference publishes no throughput numbers (BASELINE.md: "published" is
empty), so vs_baseline is the ratio against a fixed 40% MFU target — it
rises as the kernels/schedule improve across rounds.
"""

from __future__ import annotations

import json
import os
import sys
import time

BUDGET_S = float(os.environ.get("RAY_TPU_BENCH_BUDGET_S", "420"))
_T0 = time.perf_counter()


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def _remaining() -> float:
    return BUDGET_S - (time.perf_counter() - _T0)


def _probe_tpu_alive(timeout_s: float = 120.0) -> bool:
    """The axon tunnel can wedge so hard that jax.devices() never returns
    (observed: multi-hour outages). Probe in a SUBPROCESS with a timeout so
    the bench emits an honest result line instead of hanging past the
    driver's budget."""
    import subprocess

    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    if not _probe_tpu_alive():
        _log("TPU backend unreachable (tunnel down?) — reporting zero")
        print(json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": "tpu backend unreachable (axon tunnel down); "
                     "last good in-round measurement: 83245 tokens/s",
        }))
        return

    import jax
    import jax.numpy as jnp  # noqa: F401
    import optax

    from ray_tpu._internal.platform import is_tpu_backend
    from ray_tpu.models.llama import LlamaConfig, init_params, next_token_loss
    from ray_tpu.parallel.sharding import unbox_params

    _log(f"devices={jax.devices()}")
    on_tpu = is_tpu_backend()
    _log(f"on_tpu={on_tpu}")
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=8, n_heads=16, n_kv_heads=16,
            intermediate=2816, max_seq_len=1024, remat=False,
        )
        batch, steps = 8, 16
    else:  # smoke fallback for dev boxes
        cfg = LlamaConfig.tiny()
        batch, steps = 2, 3
    seq = cfg.max_seq_len

    params = unbox_params(init_params(cfg, jax.random.PRNGKey(0)))
    optimizer = optax.adamw(1e-3)
    opt_state = optimizer.init(params)
    _log("params initialized")

    def loss_fn(p, tokens):
        return next_token_loss(cfg, None, p, tokens)

    def one_step(carry, tokens):
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens)
        updates, s2 = optimizer.update(grads, s, p)
        return (optax.apply_updates(p, updates), s2), loss

    @jax.jit
    def run(p, s, data):
        (p2, s2), losses = jax.lax.scan(one_step, (p, s), data)
        return p2, s2, losses

    def make_data(n_steps, s):
        return jax.random.randint(
            jax.random.PRNGKey(s), (n_steps, batch, seq), 0, cfg.vocab_size
        )

    # Timing through the remote-execution tunnel: block_until_ready does not
    # round-trip, so force scalar materialization. Time two different step
    # counts and use the slope (dt(2K) - dt(K)) / K to cancel the fixed
    # per-dispatch overhead — but only if the wall-clock budget allows the
    # second compile; otherwise report the conservative single measurement.
    def timed(n_steps, seed):
        _log(f"compile+warm n_steps={n_steps}")
        tc0 = time.perf_counter()
        _, _, losses = run(params, opt_state, make_data(n_steps, seed + 1000))
        float(losses[-1])  # compile + warm
        compile_s = time.perf_counter() - tc0
        _log(f"warm done n_steps={n_steps} ({compile_s:.1f}s); timing")
        # time with DIFFERENT data: the tunnel may serve repeated identical
        # dispatches from cache
        t0 = time.perf_counter()
        _, _, losses = run(params, opt_state, make_data(n_steps, seed))
        float(losses[-1])
        dt = time.perf_counter() - t0
        _log(f"n_steps={n_steps} dt={dt:.3f}s")
        return dt, compile_s

    t_short, compile_short = timed(steps, seed=1)
    # second (2K) measurement needs one more compile of similar cost to the
    # first plus ~2*t_short of run time; bail to the K-only estimate (which
    # conservatively includes dispatch overhead) if the budget is shy
    if _remaining() > compile_short + 3 * t_short + 20:
        t_long, _ = timed(2 * steps, seed=2)
        dt = max(t_long - t_short, 1e-9)
        eff_steps = steps
    else:
        _log("budget short: skipping 2K run, using K-only timing")
        dt = max(t_short, 1e-9)
        eff_steps = steps

    tokens_per_sec = eff_steps * batch * seq / dt

    # rough model FLOPs/token (6 * params for fwd+bwd, attention extra)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.dim * seq * 0.5
    achieved = tokens_per_sec * flops_per_token
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = achieved / peak
    # vs_baseline: achieved MFU against a 40% MFU target for this model size
    vs_baseline = mfu / 0.40
    _log(f"tokens/s={tokens_per_sec:.1f} mfu={mfu:.4f}")

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
